"""Host-side SHA-256 primitives: constants, pure-Python compression, midstates.

The midstate trick is the core of the TPU design: the searched message is
``data + " " + ascii_decimal(nonce)``, so for any fixed prefix all complete
64-byte blocks can be absorbed ONCE on the host; the device kernel only
processes the final one or two blocks where the nonce digits live. hashlib
does not expose internal state, hence this small implementation.
"""

from __future__ import annotations

import struct

_M32 = 0xFFFFFFFF

# FIPS 180-4 round constants (first 32 bits of cube roots of primes 2..311).
SHA256_K = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)

# Initial hash state (first 32 bits of square roots of primes 2..19).
SHA256_H0 = (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
             0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19)


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _M32


def compress_host(state: tuple, block: bytes) -> tuple:
    """One SHA-256 compression round over a 64-byte block."""
    assert len(block) == 64
    w = list(struct.unpack(">16I", block))
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> 3)
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> 10)
        w.append((w[t - 16] + s0 + w[t - 7] + s1) & _M32)
    a, b, c, d, e, f, g, h = state
    for t in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g & _M32)
        t1 = (h + s1 + ch + SHA256_K[t] + w[t]) & _M32
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj) & _M32
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & _M32, c, b, a, (t1 + t2) & _M32
    return tuple((x + y) & _M32 for x, y in zip(state, (a, b, c, d, e, f, g, h)))


def sha256_midstate(prefix: bytes) -> tuple[tuple, bytes]:
    """Absorb all complete 64-byte blocks of ``prefix``.

    Returns (state after full blocks, remaining tail bytes). The caller
    appends the nonce digits + padding to the tail and finishes on device.
    """
    state = SHA256_H0
    full = len(prefix) - (len(prefix) % 64)
    for off in range(0, full, 64):
        state = compress_host(state, prefix[off:off + 64])
    return state, prefix[full:]


def sigma0(x: int) -> int:
    """Message-schedule small sigma-0 (FIPS 180-4 4.6)."""
    return _rotr(x, 7) ^ _rotr(x, 18) ^ (x >> 3)


def sigma1(x: int) -> int:
    """Message-schedule small sigma-1 (FIPS 180-4 4.7)."""
    return _rotr(x, 17) ^ _rotr(x, 19) ^ (x >> 10)


def schedule_words(block_words) -> list:
    """Full 64-entry message schedule of one 16-word block (host ints).

    The lane-invariant half of the hoist: a tail block that carries no
    nonce-digit bytes (e.g. the pure padding+length block of a 2-block
    tail) has a fully constant schedule, so ``K[t] + W[t]`` can be
    precombined ONCE here and the device compression runs with no
    schedule arithmetic at all.
    """
    w = [int(x) & _M32 for x in block_words]
    assert len(w) == 16
    for t in range(16, 64):
        w.append((w[t - 16] + sigma0(w[t - 15]) + w[t - 7]
                  + sigma1(w[t - 2])) & _M32)
    return w


def compress_rounds(state: tuple, w, start: int, stop: int) -> tuple:
    """Run SHA-256 rounds [start, stop) from raw round-state ``state``.

    ``w`` is the (absolute-indexed) message schedule, at least ``stop``
    entries. Returns the raw (a..h) round state WITHOUT the final
    feed-forward — the device kernel continues from exactly this state.
    This is both the builder for the hoisted deep midstate (the first
    ``rem // 4`` rounds of block 0 consume only constant words, so they
    run once per plan here instead of once per lane on device) and the
    bit-exactness oracle the hoist tests check device entry paths
    against.
    """
    a, b, c, d, e, f, g, h = (int(x) & _M32 for x in state)
    for t in range(start, stop):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g & _M32)
        t1 = (h + s1 + ch + SHA256_K[t] + (int(w[t]) & _M32)) & _M32
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj) & _M32
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & _M32, c, b, a, (t1 + t2) & _M32
    return a, b, c, d, e, f, g, h


def sha256_finish_host(state: tuple, tail: bytes, total_len: int) -> bytes:
    """Finish a hash from a midstate (host oracle for the device path)."""
    padded = tail + b"\x80"
    pad_blocks = 1 if len(padded) + 8 <= 64 else 2
    padded = padded.ljust(pad_blocks * 64 - 8, b"\x00")
    padded += struct.pack(">Q", total_len * 8)
    for off in range(0, len(padded), 64):
        state = compress_host(state, padded[off:off + 64])
    return struct.pack(">8I", *state)
