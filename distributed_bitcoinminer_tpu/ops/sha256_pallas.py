"""Pallas TPU kernel tier: blockwise SHA-256 arg-min search.

The hot op of the framework (ref: bitcoin/hash.go:13-17 driven by
bitcoin/miner/miner.go:52-59), hand-lowered for the TPU VPU:

- Grid = lane blocks of ``rows x 128`` nonces; each grid step formats the k
  ASCII digits in registers, runs all 64 compression rounds fully unrolled
  on (rows, 128) uint32 tiles (schedule window held in registers — no HBM
  round-trips inside the hash), and reduces its block to one
  (hash_hi, hash_lo, index) triple written to a per-step output row.
- All parameters (span start, valid window, midstate, tail template) ride in
  a single scalar-prefetch uint32 vector; the kernel touches HBM only for
  the 3-word per-step result.
- The final cross-step lexicographic argmin is a tiny jnp reduce.

Bit-identical to the host oracle, including ties (lowest nonce wins: within
a step via the masked lex-argmin, across steps because indices ascend with
the grid).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .sha256_host import SHA256_K
from .sha256_jnp import digit_positions, lex_argmin

_MAX_U32 = np.uint32(0xFFFFFFFF)
_LANES = 128


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _kernel(scal_ref, out_ref, *, rem: int, k: int, nblocks: int, rows: int):
    step = pl.program_id(0)
    i0 = scal_ref[0]
    lo = scal_ref[1]
    hi = scal_ref[2]

    row = jax.lax.broadcasted_iota(jnp.uint32, (rows, _LANES), 0)
    col = jax.lax.broadcasted_iota(jnp.uint32, (rows, _LANES), 1)
    lane = row * np.uint32(_LANES) + col
    i = i0 + step.astype(jnp.uint32) * np.uint32(rows * _LANES) + lane

    # ASCII digit contributions at their static byte positions.
    contrib = {}
    for j, (blk, word, shift) in enumerate(digit_positions(rem, k)):
        div = np.uint32(10 ** (k - 1 - j))
        digit = (i // div) % np.uint32(10) + np.uint32(48)
        key = (blk, word)
        add = digit << np.uint32(shift)
        contrib[key] = contrib[key] + add if key in contrib else add

    state = tuple(scal_ref[3 + r] for r in range(8))
    a, b, c, d, e, f, g, h = (jnp.full((rows, _LANES), s, jnp.uint32)
                              for s in state)
    for blk in range(nblocks):
        w = []
        for word in range(16):
            base = scal_ref[11 + blk * 16 + word]
            if (blk, word) in contrib:
                wv = contrib[(blk, word)] | base
            else:
                wv = jnp.full((rows, _LANES), base, jnp.uint32)
            w.append(wv)
        sa, sb, sc, sd, se, sf, sg, sh = a, b, c, d, e, f, g, h
        for t in range(64):
            if t >= 16:
                wt = w[t % 16]
                s0 = _rotr(w[(t + 1) % 16], 7) ^ _rotr(w[(t + 1) % 16], 18) \
                    ^ (w[(t + 1) % 16] >> np.uint32(3))
                s1 = _rotr(w[(t + 14) % 16], 17) ^ _rotr(w[(t + 14) % 16], 19) \
                    ^ (w[(t + 14) % 16] >> np.uint32(10))
                wt = wt + s0 + w[(t + 9) % 16] + s1
                w[t % 16] = wt
            else:
                wt = w[t]
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + np.uint32(SHA256_K[t]) + wt
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + s0 + maj
        a, b, c, d = sa + a, sb + b, sc + c, sd + d
        e, f, g, h = se + e, sf + f, sg + g, sh + h

    valid = (i >= lo) & (i <= hi)
    hi_h = jnp.where(valid, a, _MAX_U32)
    lo_h = jnp.where(valid, b, _MAX_U32)
    idx = jnp.where(valid, i, _MAX_U32)

    min_hi = jnp.min(hi_h)
    on_hi = hi_h == min_hi
    min_lo = jnp.min(jnp.where(on_hi, lo_h, _MAX_U32))
    min_idx = jnp.min(jnp.where(on_hi & (lo_h == min_lo), idx, _MAX_U32))
    out_ref[0, 0] = min_hi
    out_ref[0, 1] = min_lo
    out_ref[0, 2] = min_idx


@functools.partial(
    jax.jit,
    static_argnames=("rem", "k", "rows", "nsteps", "interpret"))
def pallas_search_span(midstate, template, i0, lo_i, hi_i, *, rem: int,
                       k: int, rows: int, nsteps: int,
                       interpret: bool = False):
    """Scan lanes ``i0 + [0, nsteps*rows*128)`` masked to [lo_i, hi_i].

    Same contract as :func:`ops.search.search_span`; ``rows`` is the sublane
    count per grid step (lanes per step = rows * 128).
    """
    midstate = jnp.asarray(midstate, dtype=jnp.uint32).reshape(8)
    template = jnp.asarray(template, dtype=jnp.uint32)
    nblocks = template.shape[0]
    scal = jnp.concatenate([
        jnp.asarray([i0, lo_i, hi_i], dtype=jnp.uint32),
        midstate, template.reshape(-1)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nsteps,),
        in_specs=[],
        out_specs=pl.BlockSpec((1, 3), lambda s, scal: (s, 0),
                               memory_space=pltpu.VMEM),
    )
    partials = pl.pallas_call(
        functools.partial(_kernel, rem=rem, k=k, nblocks=nblocks, rows=rows),
        out_shape=jax.ShapeDtypeStruct((nsteps, 3), jnp.uint32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scal)
    return lex_argmin(partials[:, 0], partials[:, 1], partials[:, 2])
