"""Pallas TPU kernel tier: blockwise SHA-256 arg-min search.

The hot op of the framework (ref: bitcoin/hash.go:13-17 driven by
bitcoin/miner/miner.go:52-59), hand-lowered for the TPU VPU:

- Grid = lane blocks of ``rows x 128`` nonces; each grid step formats the k
  ASCII digits in registers and runs the 64-round compression on
  (rows, 128) uint32 tiles. ALL 64 rounds run as one ``lax.fori_loop``
  over four 16-round schedule blocks whose window lives in loop-carried
  registers and whose K constants are dynamic reads from the
  scalar-prefetch SMEM vector; block 0 skips the schedule update via a
  cheap ``where`` guard (measured better than the "obvious" fix: a
  ``lax.cond`` that actually skips the ~21 ops/round schedule for block 0
  benched 3% SLOWER on-chip despite ~10% fewer ops — Mosaic pipelines
  the straight-line guard better than branchy control flow; round 3).
  The rolled form keeps the traced graph ~16x
  smaller than a full unroll, which both Mosaic and — critically — the
  XLA:CPU interpret path need (an unrolled SHA graph sends XLA:CPU's pass
  pipeline into a superlinear blowup; reconfirmed on-box in round 3).
  Mosaic layout inference needs one extra nudge: every value carried into
  the loop is de-replicated first (see the ``nz`` comment in the kernel),
  because a replicated-layout carry init meeting the body's plain vector
  yield is an illegal back-edge relayout.
- The result rides in three (rows, 128) accumulator outputs holding the
  elementwise running lexicographic min across grid steps. Their BlockSpec
  is the WHOLE array with a constant index map, which is always
  Mosaic-legal (round 2 shipped a per-step (1, 3) output tile, violating
  the (8, 128) tiling rule and failing to lower) and keeps the
  accumulators resident in VMEM for the entire sequential grid.
- All parameters (span start, valid window, midstate, tail template, K
  table) ride in a single scalar-prefetch uint32 vector; the kernel never
  touches HBM after prefetch.
- The final cross-lane lexicographic argmin over rows*128 entries is a
  tiny jnp reduce outside the kernel.

Bit-identical to the host oracle, including ties (lowest nonce wins:
within a lane position across steps because the strict-less merge keeps
the earlier step; across lane positions via the masked lex-argmin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .searchop import fold_argmin, fold_until
from .sha256_host import SHA256_K
from .sha256_jnp import (_sig0, _sig1, digit_contrib, hoist_structure,
                         lex_argmin)

_MAX_U32 = np.uint32(0xFFFFFFFF)
_LANES = 128
#: scal layout: [i0, lo, hi] ++ midstate(8) ++ template(nblocks*16) ++ K(64)
_TMPL_OFF = 11
#: Sublane cap per grid step. Swept on-chip through the searcher at 2^26
#: lanes (round 3): 8 -> 544, 16 -> 576, 32 -> 562, 64 -> 544 M nonces/s;
#: 16 rows (2 vregs per carried tile, ~54 live vregs) balances register
#: pressure against per-step overhead best.
_ROWS_MAX = 16


def interpret_on(platform: str) -> bool:
    """Interpret (Mosaic TPU simulator) iff ``platform`` is not a real
    chip. ``platform`` must describe the devices the kernel actually runs
    on (``mesh.devices.flat[0].platform`` / ``jax.devices()[0].platform``)
    — NOT ``jax.default_backend()``, which this image's sitecustomize can
    pin to the axon plugin while the devices in play are CPU."""
    from ..utils.config import CHIP_PLATFORMS
    return platform not in CHIP_PLATFORMS


def peel_enabled() -> bool:
    """Whether dispatch wrappers build the peeled-compression kernel.

    Default OFF until the peeled structure has passed an on-chip smoke:
    the rolled kernel is the chip-validated one, and a Mosaic layout
    regression in an unvalidated variant must never cost a scarce
    tunnel window (round-5 outage). Flip with ``DBM_PEEL=1`` (e.g. via
    ``scripts/pallas_chip_smoke.py`` under the chain) and make it the
    default here once validated."""
    from ..utils._env import str_env
    return str_env("DBM_PEEL", "0") == "1"


def pallas_argmin(midstate, template, i0, lo_i, hi_i, *, rem: int, k: int,
                  total: int, platform: str, vma: tuple = (), hoist=None):
    """THE dispatch wrapper for the argmin kernel: geometry + interpret
    flag derived in one place for every call site (single-device and mesh
    — the two drifted once in round 2). ``hoist`` (HoistPlan.ops) is
    consumed only by the peeled kernel shape — the rolled fori-over-blocks
    kernel cannot start block 0 mid-round, so the chip-validated default
    stays byte-identical when DBM_PEEL is off."""
    rows, nsteps = pallas_geometry(total)
    peel = peel_enabled()
    # Static-signature boundedness (the dbmlint jit-static suppressions
    # below): rows/nsteps derive from ``total``, which every caller
    # quantizes to batch * pow2 sub-dispatch sizes
    # (models.NonceSearcher._sub_dispatches), and interpret/peel are
    # two-valued booleans fixed for a process — the signature set is
    # small and geometry-keyed, not runtime-drifting.
    return pallas_search_span(
        midstate, template, i0, lo_i, hi_i,
        hoist if peel else None, rem=rem, k=k,
        rows=rows, nsteps=nsteps,  # dbmlint: ok[jit-static] pow2 geometry
        interpret=interpret_on(platform),  # dbmlint: ok[jit-static] bool
        peel=peel,  # dbmlint: ok[jit-static] bool knob
        vma=vma)


def pallas_until(midstate, template, i0, lo_i, hi_i, t_hi, t_lo, *,
                 rem: int, k: int, total: int, platform: str,
                 vma: tuple = (), hoist=None):
    """Dispatch wrapper for the difficulty-target kernel (see
    :func:`pallas_argmin`)."""
    rows, nsteps = pallas_geometry(total)
    peel = peel_enabled()
    # Same boundedness argument as pallas_argmin above.
    return pallas_search_span_until(
        midstate, template, i0, lo_i, hi_i, t_hi, t_lo,
        hoist if peel else None, rem=rem, k=k,
        rows=rows, nsteps=nsteps,  # dbmlint: ok[jit-static] pow2 geometry
        interpret=interpret_on(platform),  # dbmlint: ok[jit-static] bool
        peel=peel,  # dbmlint: ok[jit-static] bool knob
        vma=vma)


def devloop_pallas_enabled() -> bool:
    """Whether the pallas tier serves device-resident span loops
    (ISSUE 19 persistent grid).

    Default OFF, the ``DBM_PEEL``/``DBM_COALESCE_PALLAS`` rollout
    discipline: the devloop grid shape is interpret-validated (Mosaic
    simulator) in tier-1 but has not had an on-chip smoke, and the
    chip-validated kernel must stay byte-identical until one lands
    (``scripts/chip_chain.py`` step ``devloop-smoke``). With the knob
    off, ``DBM_DEVLOOP`` miners on the pallas tier simply keep the
    stock per-sub dispatch path. Flip with ``DBM_DEVLOOP_PALLAS=1``
    once chip-validated."""
    from ..utils._env import str_env
    return str_env("DBM_DEVLOOP_PALLAS", "0") == "1"


def batch_enabled() -> bool:
    """Whether the pallas tier serves coalesced batches (ISSUE 9).

    Default OFF, the ``DBM_PEEL`` rollout discipline: the batched entry
    is interpret-validated (Mosaic simulator) but has not had an
    on-chip smoke, and the chip-validated single-plan kernel must stay
    byte-identical until one lands. With the knob off, coalescing
    miners simply fall back to one-chunk-one-dispatch on the pallas
    tier; the jnp tier batches unconditionally. Flip with
    ``DBM_COALESCE_PALLAS=1`` once chip-validated."""
    from ..utils._env import str_env
    return str_env("DBM_COALESCE_PALLAS", "0") == "1"


def pallas_segmin(midstates, templates, i0s, lo_is, hi_is, seg, *,
                  rem: int, k: int, total: int, nrows: int, platform: str,
                  hoists=None):
    """Dispatch wrapper for the batched (segment-min) pallas entry: one
    host dispatch + one force covering ``nrows`` independent rows (see
    :func:`ops.search.search_span_segmin` for the contract). Geometry
    derives per row from ``total`` exactly like :func:`pallas_argmin`;
    ``nrows`` must already be pow2-bucketed (``ops.search.pow2_bucket``)
    by the batch planner."""
    rows, nsteps = pallas_geometry(total)
    peel = peel_enabled()
    # Same boundedness argument as pallas_argmin: rows/nsteps are pow2
    # geometry from the quantized ``total``; nrows is pow2-bucketed by
    # the caller (the planner routes it through pow2_bucket, which the
    # jit-static analyzer recognizes as bounded).
    return pallas_search_span_batch(
        midstates, templates, i0s, lo_is, hi_is, seg,
        hoists if peel else None, rem=rem, k=k,
        rows=rows, nsteps=nsteps,  # dbmlint: ok[jit-static] pow2 geometry
        nrows=nrows,  # dbmlint: ok[jit-static] pow2_bucket-quantized
        interpret=interpret_on(platform),  # dbmlint: ok[jit-static] bool
        peel=peel,  # dbmlint: ok[jit-static] bool knob
    )


@functools.partial(
    jax.jit,
    static_argnames=("rem", "k", "rows", "nsteps", "nrows", "interpret",
                     "peel"))
def pallas_search_span_batch(midstates, templates, i0s, lo_is, hi_is, seg,
                             hoists=None, *, rem: int, k: int, rows: int,
                             nsteps: int, nrows: int, interpret: bool = False,
                             peel: bool = False):
    """Batched segment-min entry for the Mosaic tier: ONE jitted
    program (one host dispatch, one force) containing ``nrows``
    invocations of the chip-validated span kernel plus the segment-min
    combine — the continuous-batching shape at the XLA-program level.

    The per-row kernels stay byte-identical to :func:`pallas_search_span`
    (same ``_run_kernel`` builder, same scalar-prefetch layout), so the
    batched entry inherits the rolled kernel's chip validation per row;
    what is new — and what the interpret validation covers — is only
    the jnp-level segment combine stitched around them. Collapsing the
    rows into a single multi-row Mosaic grid is the on-chip follow-up
    (ROADMAP); the host-side dispatch/force/serialize overhead this PR
    targets is already amortized at this level.
    """
    his, los, idxs = [], [], []
    for r in range(nrows):
        hoist_r = None
        if hoists is not None:
            hoist_r = {name: hoists[name][r] for name in hoists}
        h, l, i = _run_kernel(
            midstates[r], templates[r], i0s[r], lo_is[r], hi_is[r],
            rem=rem, k=k, rows=rows, nsteps=nsteps, interpret=interpret,
            vma=(), peel=peel, hoist=hoist_r)
        bh, bl, bi = lex_argmin(h.ravel(), l.ravel(), i.ravel())
        his.append(bh)
        los.append(bl)
        idxs.append(bi)
    from .search import segmin_rows
    return segmin_rows(jnp.stack(his), jnp.stack(los), jnp.stack(idxs),
                       seg, nrows)


def pallas_geometry(total: int) -> tuple[int, int]:
    """(rows, nsteps) for a dispatch covering ``total`` lanes.

    The ONE sizing rule shared by the single-device and mesh dispatch
    paths (they drifted once in round 2 — floor vs ceil — and the review
    asked for a single site). nsteps rounds UP: overscanned lanes past
    ``hi_i`` are masked to the sentinel inside the kernel, while flooring
    silently skipped the top of non-step-aligned blocks.
    """
    rows = max(1, min(total, _ROWS_MAX * _LANES) // _LANES)
    return rows, -(-total // (rows * _LANES))


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _round(a, b, c, d, e, f, g, h, kw):
    """One SHA-256 round; ``kw`` is the precombined K[t] + W[t] tile."""
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    # ch/maj in their 3-op / 4-op forms (vs the definitional 4/5): the
    # kernel is VPU-ALU-bound, so every op/round is ~0.5% end-to-end.
    ch = g ^ (e & (f ^ g))
    t1 = h + s1 + ch + kw
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & (b ^ c)) ^ (b & c)
    return t1 + s0 + maj, a, b, c, d + t1, e, f, g


def _round_ab(a, b, c, d, e, f, g, h, kw):
    """The truncated FINAL round: only digest words 0 and 1 are ever read
    (hi/lo hash lanes), so of the last round's two real updates only
    ``t1 + s0 + maj`` (the a-chain) survives — the ``d + t1`` e-chain
    update is dead and dropped. Expressible only in the unrolled tail the
    peeled kernel ends with; returns ``(a_64, a_63)``."""
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = g ^ (e & (f ^ g))
    t1 = h + s1 + ch + kw
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & (b ^ c)) ^ (b & c)
    return t1 + s0 + maj, a


def _make_round16(scal_ref, ckoff: int):
    """Rounds-only 16-round fori body for a fully-constant block: the
    whole schedule was precombined on the host into K[t]+W[t] scalars at
    ``ckoff`` (SMEM), so the carry is just the 8 state tiles — no window,
    no sigma arithmetic, 1/3 the loop carry of the scheduled body."""
    def round16(bi, carry):
        a, b, c, d, e, f, g, h = carry
        for j in range(16):
            a, b, c, d, e, f, g, h = _round(
                a, b, c, d, e, f, g, h, scal_ref[ckoff + bi * 16 + j])
        return (a, b, c, d, e, f, g, h)
    return round16


def _make_block16(scal_ref, koff: int, guard_first: bool):
    """The 16-round schedule-block fori body, built ONCE for both kernel
    shapes: ``guard_first=True`` is the rolled kernel (fori over blocks
    0-3, block 0 keeps the window untouched via the ``where`` guard);
    ``guard_first=False`` is the peeled kernel (fori over blocks 1-3
    only — rounds 0-15 ran straight-line, so the expansion is
    unconditional). One copy keeps the layout-sensitive round/schedule
    body from diverging between the two paths."""
    def block16(bi, carry):
        a, b, c, d, e, f, g, h = carry[:8]
        w = list(carry[8:])
        first = (bi == 0) if guard_first else None
        for j in range(16):
            s0 = (_rotr(w[(j + 1) % 16], 7) ^ _rotr(w[(j + 1) % 16], 18)
                  ^ (w[(j + 1) % 16] >> np.uint32(3)))
            s1 = (_rotr(w[(j + 14) % 16], 17) ^ _rotr(w[(j + 14) % 16], 19)
                  ^ (w[(j + 14) % 16] >> np.uint32(10)))
            upd = w[j] + s0 + w[(j + 9) % 16] + s1
            w[j] = jnp.where(first, w[j], upd) if guard_first else upd
            kj = scal_ref[koff + bi * 16 + j]
            a, b, c, d, e, f, g, h = _round(
                a, b, c, d, e, f, g, h, w[j] + kj)
        return (a, b, c, d, e, f, g, h, *w)
    return block16


def _peel_hoisted(scal_ref, contrib, nz, *, rem: int, k: int, nblocks: int,
                  rows: int, until: bool):
    """Peeled compression consuming the HOST hoist (the tentpole):

    - block 0 enters at the host-extended deep midstate (SMEM scalars at
      ``hoff``) — the ``rem // 4`` lane-invariant head rounds that the
      plain peel recomputed on the scalar plane EVERY grid step now run
      once per plan on the host;
    - rounds t*..15 are schedule-free off host-precombined K+W scalars;
    - rounds 16..31 run as static code computing only the lane-VARYING
      schedule taps; the constant s0/s1 terms and additive taps ride the
      ``cw`` SMEM scalars (sha256_jnp.build_hoist);
    - a digit-free block (2-block tails whose digits fit block 0) runs
      with ZERO schedule arithmetic off the full K[t]+W[t] vector at
      ``ckoff``, its fori carrying 8 tiles instead of 24;
    - the final block's last 16 rounds are static so the one dead update
      (round 64's e-chain) and the 6 dead feed-forward adds drop — only
      digest words 0 and 1 are ever read.

    Returns the two live output tiles ``(a_out, b_out)``.
    """
    struct = hoist_structure(rem, k, nblocks)
    koff = _TMPL_OFF + 16 * nblocks
    hoff = koff + 64 + (2 if until else 0)
    kwoff = hoff + 8
    cwoff = kwoff + 16 * nblocks
    ckoff = cwoff + 16 * nblocks
    shape = (rows, _LANES)
    vec = None                        # 8-tuple of tiles between blocks
    out_a = out_b = None
    for blk in range(nblocks):
        varying, taps, full = struct[blk]
        final = blk == nblocks - 1
        if full:
            # Only the padding+length block of a 2-block tail can be
            # digit-free, so a full-const block is always final and its
            # entry state is always lane-varying tiles.
            ff = vec
            a, b, c, d, e, f, g, h = vec
            for j in range(16):
                a, b, c, d, e, f, g, h = _round(
                    a, b, c, d, e, f, g, h, scal_ref[ckoff + j])
            a, b, c, d, e, f, g, h = jax.lax.fori_loop(
                1, 3, _make_round16(scal_ref, ckoff),
                (a, b, c, d, e, f, g, h))
            for j in range(15):
                a, b, c, d, e, f, g, h = _round(
                    a, b, c, d, e, f, g, h, scal_ref[ckoff + 48 + j])
            a, b = _round_ab(a, b, c, d, e, f, g, h, scal_ref[ckoff + 63])
            out_a, out_b = ff[0] + a, ff[1] + b
            continue
        if vec is None:               # block 0: deep-midstate entry
            t_star = varying[0]       # == rem // 4
            deep = tuple(scal_ref[hoff + r] for r in range(8))
            ff = tuple(scal_ref[3 + r] for r in range(8))
            a, b, c, d, e, f, g, h = (
                jnp.full(shape, s, jnp.uint32) + nz for s in deep)
        else:
            t_star = 0                # digit spill: word 0 varies
            ff = vec
            a, b, c, d, e, f, g, h = vec
        # Lane-varying initial window values (const taps ride cw).
        wv = {j: contrib[(blk, j)] | scal_ref[_TMPL_OFF + blk * 16 + j]
              for j in varying}
        for j in range(t_star, 16):
            kwj = scal_ref[kwoff + blk * 16 + j]
            if j in wv:
                kwj = wv[j] + scal_ref[koff + j]
            a, b, c, d, e, f, g, h = _round(a, b, c, d, e, f, g, h, kwj)
        for i16, tv in enumerate(taps):
            t = 16 + i16
            acc = scal_ref[cwoff + blk * 16 + i16]
            for kind, tap in tv:
                x = wv[tap]
                acc = acc + (x if kind == "w"
                             else _sig0(x) if kind == "s0" else _sig1(x))
            wv[t] = acc               # SMEM scalar when tv is empty
            a, b, c, d, e, f, g, h = _round(
                a, b, c, d, e, f, g, h, acc + scal_ref[koff + t])
        w = [wv[16 + j] if taps[j] else
             jnp.full(shape, wv[16 + j], jnp.uint32) + nz
             for j in range(16)]
        if final:
            carry = jax.lax.fori_loop(   # rounds 32..47, rolled
                2, 3, _make_block16(scal_ref, koff, guard_first=False),
                (a, b, c, d, e, f, g, h, *w))
            a, b, c, d, e, f, g, h = carry[:8]
            w = list(carry[8:])
            for j in range(16):          # rounds 48..63, static + truncated
                s0 = _sig0(w[(j + 1) % 16])
                s1 = _sig1(w[(j + 14) % 16])
                w[j] = w[j] + s0 + w[(j + 9) % 16] + s1
                kwj = w[j] + scal_ref[koff + 48 + j]
                if j == 15:
                    a, b = _round_ab(a, b, c, d, e, f, g, h, kwj)
                else:
                    a, b, c, d, e, f, g, h = _round(
                        a, b, c, d, e, f, g, h, kwj)
            out_a, out_b = ff[0] + a, ff[1] + b
        else:
            carry = jax.lax.fori_loop(   # rounds 32..63, rolled
                2, 4, _make_block16(scal_ref, koff, guard_first=False),
                (a, b, c, d, e, f, g, h, *w))
            st8 = carry[:8]
            vec = tuple(fv + sv for fv, sv in zip(ff, st8))
    return out_a, out_b


def _kernel(scal_ref, *refs, rem: int, k: int, nblocks: int, rows: int,
            until: bool = False, peel: bool = False, hoisted: bool = False,
            devloop: bool = False):
    if devloop:
        # ISSUE 19 persistent grid: the grid is sized for the STATIC
        # pow2 step cap, and the second scalar-prefetch operand carries
        # the LIVE step count — steps at or past it skip the SHA body
        # entirely (a scalar SMEM read + branch, the same skip shape as
        # the until flag below). The scal layout is untouched, so the
        # chip-validated kernel is byte-identical when the knob is off.
        live_ref, *refs = refs
    hi_ref, lo_ref, idx_ref, *extra_refs = refs
    step = pl.program_id(0)
    if until:
        # In-kernel early exit (VERDICT r3 task 2): the grid is sequential
        # on TPU, so once any earlier step found a qualifying lane —
        # recorded in the SMEM flag accumulator — every later step skips
        # the whole SHA body. A skipped step costs a scalar SMEM read and
        # a branch (~µs) vs ~3.3k VPU ops/lane, collapsing the
        # time-to-first-hit of a large dispatch from the full grid to the
        # hit step, with no host round-trips. Step 0 zeroes the flag
        # BEFORE the read below — `&` does not short-circuit, so masking
        # an uninitialized load with a `step != 0` conjunct would still
        # execute the load and is fragile under lowering changes
        # (ADVICE r4). The body's step-0 init then overwrites the zero
        # with this step's own hit count.
        f_ref, flag_ref = extra_refs

        @pl.when(step == jnp.int32(0))
        def _zero_flag():
            flag_ref[0] = jnp.uint32(0)

        done = flag_ref[0] != jnp.uint32(0)
        run = jnp.logical_not(done)
        if devloop:
            # live is clamped >= 1 by the caller, so step 0 (accumulator
            # init + flag overwrite) always runs.
            run = run & (step < live_ref[0])

        @pl.when(run)
        def _work():
            # ``step`` rides in from the enclosing scope (a cond operand):
            # calling pl.program_id INSIDE the when-branch would put the
            # primitive in the cond's branch jaxpr, which jax 0.4.x's
            # generic pallas interpreter cannot substitute (chip lowering
            # is identical either way — the grid is sequential).
            _kernel_body(scal_ref, hi_ref, lo_ref, idx_ref, f_ref, flag_ref,
                         step=step, rem=rem, k=k, nblocks=nblocks,
                         rows=rows, until=True, peel=peel, hoisted=hoisted)
    elif devloop:
        @pl.when(step < live_ref[0])
        def _work_argmin():
            _kernel_body(scal_ref, hi_ref, lo_ref, idx_ref, None, None,
                         step=step, rem=rem, k=k, nblocks=nblocks,
                         rows=rows, until=False, peel=peel, hoisted=hoisted)
    else:
        _kernel_body(scal_ref, hi_ref, lo_ref, idx_ref, None, None,
                     step=step, rem=rem, k=k, nblocks=nblocks, rows=rows,
                     until=False, peel=peel, hoisted=hoisted)


def _kernel_body(scal_ref, hi_ref, lo_ref, idx_ref, f_ref, flag_ref, *,
                 step, rem: int, k: int, nblocks: int, rows: int,
                 until: bool, peel: bool = False, hoisted: bool = False):
    i0 = scal_ref[0]
    lo = scal_ref[1]
    hi = scal_ref[2]
    koff = _TMPL_OFF + 16 * nblocks

    row = jax.lax.broadcasted_iota(jnp.uint32, (rows, _LANES), 0)
    col = jax.lax.broadcasted_iota(jnp.uint32, (rows, _LANES), 1)
    lane = row * np.uint32(_LANES) + col
    step_base = i0 + step.astype(jnp.uint32) * np.uint32(rows * _LANES)
    i = step_base + lane

    # ASCII digit contributions at their static byte positions. Digits
    # above the step's 10^m window ride the scalar plane: two candidate
    # values + one per-lane select instead of k div/mod chains
    # (sha256_jnp.digit_contrib, VERDICT r4 task 3).
    contrib = digit_contrib(i, rem, k, base=step_base, span=rows * _LANES)

    # Every carry entering a fori_loop must already have the plain
    # {0,0} vector register layout: jnp.full broadcasts of SMEM scalars
    # get the *replicated* {*,*} layout, the loop body yields {0,0}
    # vectors, and Mosaic rejects the back-edge relayout ("Invalid
    # relayout: Non-singleton logical dimension is replicated in
    # destination but not in source" — the round-3 on-chip failure).
    # ``nz`` is an iota-derived zero (lane < 2^31 always) that layout
    # inference cannot fold away, de-replicating each init for one
    # shift + add per carried tile per grid step.
    nz = lane >> np.uint32(31)
    state = tuple(scal_ref[3 + r] for r in range(8))

    def w_tiles(blk):
        w = []
        for word in range(16):
            base = scal_ref[_TMPL_OFF + blk * 16 + word]
            if (blk, word) in contrib:
                wv = contrib[(blk, word)] | base
            else:
                wv = jnp.full((rows, _LANES), base, jnp.uint32)
            w.append(wv + nz)
        return w

    if hoisted and peel:
        out_a, out_b = _peel_hoisted(scal_ref, contrib, nz, rem=rem, k=k,
                                     nblocks=nblocks, rows=rows, until=until)
    elif not peel:
        a, b, c, d, e, f, g, h = (jnp.full((rows, _LANES), s, jnp.uint32)
                                  + nz for s in state)
        for blk in range(nblocks):
            w = w_tiles(blk)
            sa, sb, sc, sd, se, sf, sg, sh = a, b, c, d, e, f, g, h

            # All 64 rounds as ONE fori_loop over four 16-round schedule
            # blocks; block 0 keeps the window untouched via a cheap
            # ``where`` guard. The rolled form keeps the traced graph
            # ~16x smaller than a full unroll, which is what keeps the
            # interpret/test path viable: XLA:CPU's pass pipeline blows
            # up super-linearly on an unrolled SHA graph (round-2
            # finding, reconfirmed in round 3 — one unrolled interpret
            # step exceeded 240 s). K rides in SMEM via the
            # scalar-prefetch ref (dynamic per-round reads).
            carry = jax.lax.fori_loop(
                0, 4, _make_block16(scal_ref, koff, guard_first=True),
                (a, b, c, d, e, f, g, h, *w))
            a, b, c, d, e, f, g, h = carry[:8]
            a, b, c, d = sa + a, sb + b, sc + c, sd + d
            e, f, g, h = se + e, sf + f, sg + g, sh + h
        out_a, out_b = a, b
    elif peel:
        # Peeled compression (round 5): rounds 0-15 of each compression
        # run as STATIC straight-line code with no schedule expansion —
        # the rolled loop's block-0 ``where`` guard computes and
        # discards ~21 ops/round of σ0/σ1 schedule math (the VPU
        # executes both sides of a select), ~16% of the kernel's vector
        # ops. Straight-line, so the round-3 negative result on
        # ``lax.cond`` (branchy skip benched 3% slower) does not apply.
        # On top, rounds before the first digit-carrying word of the
        # FIRST compression see lane-invariant state AND schedule, and
        # ride the scalar plane entirely (state enters as SMEM scalars;
        # ``rem//4`` rounds — up to 15 for long 2-block data). Static
        # graph cost: +16 traced rounds per compression, far below the
        # full-unroll blowup documented above.
        vec = None                       # vector state, once broadcast
        cur = state                      # scalar state until broadcast
        for blk in range(nblocks):
            digit_words = sorted(wd for (b, wd) in contrib if b == blk)
            scalar_entry = vec is None
            t_star = digit_words[0] if scalar_entry and digit_words else 0
            ff = cur if scalar_entry else vec    # feed-forward base
            if scalar_entry:
                for j in range(t_star):          # scalar-plane rounds
                    wj = scal_ref[_TMPL_OFF + blk * 16 + j]
                    cur = _round(*cur, wj + scal_ref[koff + j])
                vec = tuple(jnp.full((rows, _LANES), s, jnp.uint32) + nz
                            for s in cur)
            a, b, c, d, e, f, g, h = vec
            w = w_tiles(blk)
            for j in range(t_star, 16):          # peeled vector rounds
                if (blk, j) in contrib:
                    kw = w[j] + scal_ref[koff + j]
                else:
                    # Constant word: K[j]+W[j] on the scalar plane; it
                    # broadcasts inside _round's existing t1 add, saving
                    # the per-lane add on the materialized tile.
                    kw = (scal_ref[_TMPL_OFF + blk * 16 + j]
                          + scal_ref[koff + j])
                a, b, c, d, e, f, g, h = _round(
                    a, b, c, d, e, f, g, h, kw)

            carry = jax.lax.fori_loop(   # rounds 16-63, rolled
                1, 4, _make_block16(scal_ref, koff, guard_first=False),
                (a, b, c, d, e, f, g, h, *w))
            a, b, c, d, e, f, g, h = carry[:8]
            vec = (ff[0] + a, ff[1] + b, ff[2] + c, ff[3] + d,
                   ff[4] + e, ff[5] + f, ff[6] + g, ff[7] + h)
        out_a, out_b = vec[0], vec[1]

    valid = (i >= lo) & (i <= hi)
    hi_h = jnp.where(valid, out_a, _MAX_U32)
    lo_h = jnp.where(valid, out_b, _MAX_U32)
    idx = jnp.where(valid, i, _MAX_U32)
    if until:
        # Difficulty-target accumulator: per lane position, the minimum
        # (= first, since idx ascends with step) index whose hash beats
        # the 64-bit target (appended after the K table in scal).
        # Sentinel-masked lanes carry (MAX, MAX) which never qualifies
        # under strict lex-less. The SMEM flag is the skip signal for
        # later steps: int32 add-reduction (well-legalized, unlike the
        # unsigned min the f accumulator itself would need) counts this
        # step's qualifying lanes.
        t_hi = scal_ref[koff + 64]
        t_lo = scal_ref[koff + 65]
        qual = (hi_h < t_hi) | ((hi_h == t_hi) & (lo_h < t_lo))
        f_q = jnp.where(qual, idx, _MAX_U32)
        hit = (jnp.sum(qual.astype(jnp.int32)) > 0).astype(jnp.uint32)

    @pl.when(step == 0)
    def _init():
        hi_ref[...] = hi_h
        lo_ref[...] = lo_h
        idx_ref[...] = idx
        if until:
            f_ref[...] = f_q
            flag_ref[0] = hit

    @pl.when(step != 0)
    def _merge():
        p_hi = hi_ref[...]
        p_lo = lo_ref[...]
        p_idx = idx_ref[...]
        # Strict less: at a fixed lane position the nonce index ascends with
        # the step, so keeping prev on (hi, lo) ties preserves the earliest
        # nonce (Go first-seen-wins, ref: bitcoin/miner/miner.go:54-58).
        take = (hi_h < p_hi) | ((hi_h == p_hi) & (lo_h < p_lo))
        hi_ref[...] = jnp.where(take, hi_h, p_hi)
        lo_ref[...] = jnp.where(take, lo_h, p_lo)
        idx_ref[...] = jnp.where(take, idx, p_idx)
        if until:
            # compare+select, not jnp.minimum: Mosaic has no legalization
            # for vector arith.minui (round-3 on-chip failure).
            p_f = f_ref[...]
            f_ref[...] = jnp.where(f_q < p_f, f_q, p_f)
            flag_ref[0] = flag_ref[0] | hit


@functools.partial(
    jax.jit,
    static_argnames=("rem", "k", "rows", "nsteps", "interpret", "vma",
                     "peel"))
def pallas_search_span(midstate, template, i0, lo_i, hi_i, hoist=None, *,
                       rem: int, k: int, rows: int, nsteps: int,
                       interpret: bool = False, vma: tuple = (),
                       peel: bool = False):
    """Scan lanes ``i0 + [0, nsteps*rows*128)`` masked to [lo_i, hi_i].

    Same contract as :func:`ops.search.search_span`; ``rows`` is the sublane
    count per grid step (lanes per step = rows * 128).

    ``interpret=True`` selects the Mosaic TPU *simulator*
    (``pltpu.InterpretParams``), not the generic XLA interpret path: the
    simulator evaluates the kernel jaxpr op-by-op in seconds, while the
    generic path hands XLA:CPU the whole grid program whose compile blows
    up super-linearly on SHA-shaped graphs (round-3 finding; round 2
    misread the never-finishing forced result as "interpret is slow").

    Inside ``shard_map`` pass the mesh axes as ``vma``: with varying
    inputs, shard_map's vma checker requires the pallas outputs to declare
    which mesh axes they vary over.
    """
    hi_h, lo_h, idx = _run_kernel(
        midstate, template, i0, lo_i, hi_i, rem=rem, k=k, rows=rows,
        nsteps=nsteps, interpret=interpret, vma=vma, peel=peel,
        hoist=hoist)
    return lex_argmin(hi_h.ravel(), lo_h.ravel(), idx.ravel())


@functools.partial(
    jax.jit,
    static_argnames=("rem", "k", "rows", "nsteps", "interpret", "vma",
                     "peel"))
def pallas_search_span_until(midstate, template, i0, lo_i, hi_i, t_hi, t_lo,
                             hoist=None, *, rem: int, k: int, rows: int,
                             nsteps: int, interpret: bool = False,
                             vma: tuple = (), peel: bool = False):
    """Difficulty-target span scan on the Mosaic kernel.

    Same lane coverage as :func:`pallas_search_span` plus a 4th in-VMEM
    accumulator holding, per lane position, the first (minimum) index
    whose hash is lex-less than the 64-bit target ``(t_hi, t_lo)``.

    Returns uint32 scalars ``(found, f_idx, best_hi, best_lo, best_idx)``
    — no qualifying HASH: the caller recomputes the one qualifying hash
    with the host oracle (one sha256). In-kernel early exit (r4): after
    the first step with a qualifying lane sets the SMEM flag, every later
    grid step skips the SHA body, so time-to-first-hit is per-STEP
    (rows*128 lanes) granular even for a large dispatch — matching the
    jnp tier's per-batch ``while_loop`` — and ``best_*`` then cover only
    the steps up to the hit (callers use them only when found=0, i.e.
    when no step was skipped). The first-qualifying-nonce contract holds
    because sub-dispatches are forced in ascending order
    (models.miner_model._until_block).
    """
    hi_h, lo_h, idx, f, flag = _run_kernel(
        midstate, template, i0, lo_i, hi_i, rem=rem, k=k, rows=rows,
        nsteps=nsteps, interpret=interpret, vma=vma, target=(t_hi, t_lo),
        peel=peel, hoist=hoist)
    f_idx = jnp.min(f.ravel())
    found = (flag[0] != 0).astype(jnp.uint32)
    b_hi, b_lo, b_idx = lex_argmin(hi_h.ravel(), lo_h.ravel(), idx.ravel())
    return found, f_idx, b_hi, b_lo, b_idx


def _out_struct(shape, vma):
    """Output ShapeDtypeStruct, typed device-varying over ``vma`` when this
    jax HAS vma typing (shard_map's varying-axis checker requires it); on
    jax 0.4.x the kwarg does not exist and replication is check_rep's job,
    so the plain struct is the correct spelling."""
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, jnp.uint32,
                                        vma=frozenset(vma))
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, jnp.uint32)


def _run_kernel(midstate, template, i0, lo_i, hi_i, *, rem, k, rows, nsteps,
                interpret, vma, target=None, peel=False, hoist=None,
                live=None):
    """Shared pallas_call builder for the argmin and difficulty variants.

    With ``hoist`` (peeled shape only), the host-precomputed sections are
    APPENDED to the scalar-prefetch vector — deep midstate (8), K+W for
    rounds 0..15 (16 per block), the rounds-16..31 constant schedule
    terms (16 per block) and, when a digit-free block exists, its full
    K[t]+W[t] precombination (64) — so the chip-validated layout of the
    rolled kernel is byte-identical when the hoist is off.

    With ``live`` (ISSUE 19 devloop), the traced live step count rides
    as a SECOND scalar-prefetch operand — NOT appended to ``scal``, so
    the chip-validated scal layout is unshifted — and the kernel
    predicates each grid step on ``step < live``; ``nsteps`` is then the
    static pow2 step cap. ``live`` is clamped to >= 1 here (step 0 must
    run: it initializes the accumulators and the until flag)."""
    midstate = jnp.asarray(midstate, dtype=jnp.uint32).reshape(8)
    template = jnp.asarray(template, dtype=jnp.uint32)
    nblocks = template.shape[0]
    hoisted = peel and hoist is not None
    parts = [
        jnp.asarray([i0, lo_i, hi_i], dtype=jnp.uint32),
        midstate, template.reshape(-1),
        jnp.asarray(SHA256_K, dtype=jnp.uint32)]
    if target is not None:
        parts.append(jnp.stack([jnp.asarray(t, dtype=jnp.uint32)
                                for t in target]))
    if hoisted:
        parts += [jnp.asarray(hoist["deep"], dtype=jnp.uint32),
                  jnp.asarray(hoist["kw"], dtype=jnp.uint32).reshape(-1),
                  jnp.asarray(hoist["cw"], dtype=jnp.uint32).reshape(-1)]
        if "ckw" in hoist:
            parts.append(jnp.asarray(hoist["ckw"], dtype=jnp.uint32))
    scal = jnp.concatenate(parts)

    devloop = live is not None
    # Accumulator BlockSpec = the whole (rows, 128) array with a constant
    # index map: always Mosaic-legal, and the revisited block stays resident
    # in VMEM across the entire sequential grid. Index maps take one
    # positional per scalar-prefetch operand, so the devloop shape (scal +
    # live) needs the three-arg spelling.
    if devloop:
        acc_spec = pl.BlockSpec((rows, _LANES), lambda s, scal, live: (0, 0),
                                memory_space=pltpu.VMEM)
        flag_spec = pl.BlockSpec((1,), lambda s, scal, live: (0,),
                                 memory_space=pltpu.SMEM)
    else:
        acc_spec = pl.BlockSpec((rows, _LANES), lambda s, scal: (0, 0),
                                memory_space=pltpu.VMEM)
        flag_spec = pl.BlockSpec((1,), lambda s, scal: (0,),
                                 memory_space=pltpu.SMEM)
    acc_shape = _out_struct((rows, _LANES), vma)
    n_out = 3 if target is None else 4
    out_specs = (acc_spec,) * n_out
    out_shapes = (acc_shape,) * n_out
    if target is not None:
        # 5th output: the early-exit flag, an SMEM scalar accumulator the
        # kernel reads at every step start to skip work after a hit.
        out_specs += (flag_spec,)
        out_shapes += (_out_struct((1,), vma),)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2 if devloop else 1,
        grid=(nsteps,),
        in_specs=[],
        out_specs=out_specs,
    )
    args = (scal,)
    if devloop:
        live_arr = jnp.maximum(
            jnp.asarray(live, dtype=jnp.int32).reshape(1), jnp.int32(1))
        args = (scal, live_arr)
    return pl.pallas_call(
        functools.partial(_kernel, rem=rem, k=k, nblocks=nblocks, rows=rows,
                          until=target is not None, peel=peel,
                          hoisted=hoisted, devloop=devloop),
        out_shape=out_shapes,
        grid_spec=grid_spec,
        # Mosaic TPU simulator where this jax has it; jax 0.4.x predates
        # pltpu.InterpretParams and interprets via the boolean flag.
        interpret=(pltpu.InterpretParams()
                   if interpret and hasattr(pltpu, "InterpretParams")
                   else bool(interpret)),
    )(*args)


# --------------------------------------------------------------------------
# ISSUE 19 devloop entries: persistent grid over a whole block.
#
# The grid is sized once for the static pow2 sub-window CAP
# (``pallas_geometry(batch * cap)``); the live step count — derived from
# the TRACED ``nsub`` — rides as the second scalar-prefetch operand and
# predicates each step, so one launch covers any live size up to the cap
# with no masked overscan work and no per-size recompiles. The running
# min stays in the VMEM accumulators across all grid steps (the grid IS
# the persistent loop — sequential on TPU), and the only thing that
# leaves the device per span is the searchop carry.


def _devloop_live(nsub, batch: int, rows: int):
    """Traced live grid-step count covering ``nsub * batch`` lanes."""
    lanes = jnp.asarray(nsub, dtype=jnp.int32) * jnp.int32(batch)
    per = jnp.int32(rows * _LANES)
    return (lanes + per - jnp.int32(1)) // per


def pallas_devloop_scan(midstate, template, i0, lo_i, hi_i, nsub, *,
                        rem: int, k: int, batch: int, cap: int,
                        platform: str, vma: tuple = (), hoist=None):
    """Unjitted devloop argmin scan -> (best_hi, best_lo, best_i)
    scalars; the shard_map per-device body of
    ``parallel.mesh_search.mesh_devloop_span`` (callers are already
    inside jit). ``batch``/``cap`` describe the sub-window geometry the
    jnp tier uses; the kernel re-tiles the same lane range to its own
    ``rows x 128`` steps, which is coverage-identical (everything past
    ``hi_i`` masks to the sentinel)."""
    rows, nsteps = pallas_geometry(batch * cap)
    peel = peel_enabled()
    live = _devloop_live(nsub, batch, rows)
    hi_h, lo_h, idx = _run_kernel(
        midstate, template, i0, lo_i, hi_i, rem=rem, k=k, rows=rows,
        nsteps=nsteps, interpret=interpret_on(platform), vma=vma,
        peel=peel, hoist=hoist if peel else None, live=live)
    return lex_argmin(hi_h.ravel(), lo_h.ravel(), idx.ravel())


def pallas_devloop_until_scan(midstate, template, i0, lo_i, hi_i, t_hi,
                              t_lo, nsub, found_prev, *, rem: int, k: int,
                              batch: int, cap: int, platform: str,
                              vma: tuple = (), hoist=None):
    """Unjitted devloop difficulty scan -> the
    ``(found, f_idx, best_hi, best_lo, best_idx)`` contract of
    :func:`pallas_search_span_until`. ``found_prev`` (the carry's found
    word) clamps the live step count to 1 — a launch chained after a hit
    costs one grid step instead of a block's worth (the in-launch SMEM
    flag already handles exits WITHIN a launch)."""
    rows, nsteps = pallas_geometry(batch * cap)
    peel = peel_enabled()
    live = _devloop_live(nsub, batch, rows)
    live = jnp.where(jnp.asarray(found_prev, dtype=jnp.uint32)
                     != jnp.uint32(0), jnp.int32(1), live)
    hi_h, lo_h, idx, f, flag = _run_kernel(
        midstate, template, i0, lo_i, hi_i, rem=rem, k=k, rows=rows,
        nsteps=nsteps, interpret=interpret_on(platform), vma=vma,
        target=(t_hi, t_lo), peel=peel, hoist=hoist if peel else None,
        live=live)
    f_idx = jnp.min(f.ravel())
    found = (flag[0] != 0).astype(jnp.uint32)
    b_hi, b_lo, b_idx = lex_argmin(hi_h.ravel(), lo_h.ravel(), idx.ravel())
    return found, f_idx, b_hi, b_lo, b_idx


@functools.partial(
    jax.jit,
    static_argnames=("rem", "k", "batch", "cap", "interpret", "peel"))
def _pallas_devloop_span_jit(midstate, template, carry, i0, lo_i, hi_i,
                             nsub, base_hi, base_lo, hoist=None, *,
                             rem: int, k: int, batch: int, cap: int,
                             interpret: bool, peel: bool):
    rows, nsteps = pallas_geometry(batch * cap)
    live = _devloop_live(nsub, batch, rows)
    hi_h, lo_h, idx = _run_kernel(
        midstate, template, i0, lo_i, hi_i, rem=rem, k=k, rows=rows,
        nsteps=nsteps, interpret=interpret, vma=(), peel=peel,
        hoist=hoist, live=live)
    b_hi, b_lo, b_i = lex_argmin(hi_h.ravel(), lo_h.ravel(), idx.ravel())
    carry = jnp.asarray(carry, dtype=jnp.uint32)
    return fold_argmin(carry, b_hi, b_lo, b_i, base_hi, base_lo)


def pallas_devloop_span(midstate, template, carry, i0, lo_i, hi_i, nsub,
                        base_hi, base_lo, *, rem: int, k: int, batch: int,
                        cap: int, platform: str, hoist=None):
    """Single-device devloop block launch (pallas tier): ONE jitted
    launch scanning the whole block's lanes and folding the merged
    candidate into the 5-word searchop carry — the pallas twin of
    ``ops.search.devloop_span``. Returns the updated carry device
    value."""
    peel = peel_enabled()
    # Static-signature boundedness: batch is the searcher's fixed lane
    # width and cap is devloop_cap-quantized by the model layer.
    return _pallas_devloop_span_jit(
        midstate, template, carry, i0, lo_i, hi_i, nsub, base_hi, base_lo,
        hoist if peel else None, rem=rem, k=k, batch=batch,
        cap=cap,  # dbmlint: ok[jit-static] devloop_cap-quantized pow2
        interpret=interpret_on(platform),  # dbmlint: ok[jit-static] bool
        peel=peel)  # dbmlint: ok[jit-static] bool knob


@functools.partial(
    jax.jit,
    static_argnames=("rem", "k", "batch", "cap", "interpret", "peel"))
def _pallas_devloop_until_jit(midstate, template, carry, i0, lo_i, hi_i,
                              t_hi, t_lo, nsub, base_hi, base_lo,
                              hoist=None, *, rem: int, k: int, batch: int,
                              cap: int, interpret: bool, peel: bool):
    rows, nsteps = pallas_geometry(batch * cap)
    carry = jnp.asarray(carry, dtype=jnp.uint32)
    live = _devloop_live(nsub, batch, rows)
    live = jnp.where(carry[0] != jnp.uint32(0), jnp.int32(1), live)
    hi_h, lo_h, idx, f, flag = _run_kernel(
        midstate, template, i0, lo_i, hi_i, rem=rem, k=k, rows=rows,
        nsteps=nsteps, interpret=interpret, vma=(), target=(t_hi, t_lo),
        peel=peel, hoist=hoist, live=live)
    f_idx = jnp.min(f.ravel())
    b_hi, b_lo, b_i = lex_argmin(hi_h.ravel(), lo_h.ravel(), idx.ravel())
    return fold_until(carry, f_idx, b_hi, b_lo, b_i, base_hi, base_lo)


def pallas_devloop_span_until(midstate, template, carry, i0, lo_i, hi_i,
                              t_hi, t_lo, nsub, base_hi, base_lo, *,
                              rem: int, k: int, batch: int, cap: int,
                              platform: str, hoist=None):
    """Single-device devloop difficulty block launch (pallas tier): one
    jitted launch -> updated 8-word searchop carry, the pallas twin of
    ``ops.search.devloop_span_until``. An already-found carry clamps the
    live grid to one step, so chained launches after a hit are ~free."""
    peel = peel_enabled()
    return _pallas_devloop_until_jit(
        midstate, template, carry, i0, lo_i, hi_i, t_hi, t_lo, nsub,
        base_hi, base_lo, hoist if peel else None, rem=rem, k=k,
        batch=batch,
        cap=cap,  # dbmlint: ok[jit-static] devloop_cap-quantized pow2
        interpret=interpret_on(platform),  # dbmlint: ok[jit-static] bool
        peel=peel)  # dbmlint: ok[jit-static] bool knob
