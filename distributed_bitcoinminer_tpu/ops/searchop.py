"""Search-op seam: carry codec + merge semiring behind one small protocol.

ISSUE 19 moves the span loop on device, which forces the question "what
IS a search op?" into one place: a search op is (a) a carry layout —
the few uint32 words of running state a launch consumes and emits, (b)
a fold — how one launch's merged candidate enters that carry, and (c) a
decode — how the host reads the final carry back into Python values.
The argmin op (minimal (hash, nonce)) and the first-hit/difficulty op
(first *qualifying* nonce, argmin fallback) are the two instances; the
ROADMAP's op-agnostic item starts from this interface instead of a
rewrite.

The codec here is PR 14's mesh carry, verbatim — ``parallel/
mesh_search.py`` re-exports these names (``mesh_carry_init`` et al.) so
existing imports and the on-chip-validated jaxprs are unchanged. The
device-resident span drivers (``ops/search.py`` jnp tier, ``ops/
sha256_pallas.py`` pallas tier) thread the same words, so a whole span
— any number of 10^k blocks and sub-windows — crosses the PCIe/ICI
boundary as ONE <= 32-byte vector (20 bytes for argmin), fetched once
at finalize.

Merge rule (both ops): full lexicographic strict-less on
(hash_hi, hash_lo, nonce_hi, nonce_lo) among seen candidates — minimal
hash, earliest nonce on ties, exactly the host finalize walk and the Go
scan's first-seen-wins strict ``<`` (ref: bitcoin/miner/miner.go:54-58).
The full lex (not hash-only) matters because chain order is not nonce
order: mesh stripe windows interleave lane coverage across chained
folds, so the tie-break must be explicit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax.numpy as jnp
import numpy as np

_MAX_U32 = np.uint32(0xFFFFFFFF)
_MAX_U64 = 0xFFFFFFFFFFFFFFFF

#: Carry layouts (uint32 words).
#: argmin: [hash_hi, hash_lo, nonce_hi, nonce_lo, seen]
#: until:  [found, f_nonce_hi, f_nonce_lo] + the argmin layout.
CARRY_WORDS = 5
UNTIL_CARRY_WORDS = 8


def carry_init() -> np.ndarray:
    """Neutral argmin carry: nothing seen yet."""
    return np.array([0xFFFFFFFF] * 4 + [0], dtype=np.uint32)


def until_carry_init() -> np.ndarray:
    """Neutral difficulty carry: no hit, nothing seen."""
    return np.array([0, 0xFFFFFFFF, 0xFFFFFFFF]
                    + [0xFFFFFFFF] * 4 + [0], dtype=np.uint32)


def lex_less(a, b):
    """Strict lexicographic ``a < b`` over matching leading words of two
    uint32 vectors (element 0 most significant)."""
    out = a[-1] < b[-1]
    for i in range(len(a) - 2, -1, -1):
        out = (a[i] < b[i]) | ((a[i] == b[i]) & out)
    return out


def global_nonce(base_hi, base_lo, idx):
    """64-bit ``base + idx`` as a (hi, lo) uint32 pair (idx < 2^32; the
    unsigned-add wrap test carries into the high word)."""
    n_lo = base_lo + idx
    return base_hi + (n_lo < idx).astype(jnp.uint32), n_lo


def fold_argmin(carry, m_hi, m_lo, m_idx, base_hi, base_lo):
    """Fold one launch's merged candidate into the argmin carry."""
    valid = ~((m_hi == _MAX_U32) & (m_lo == _MAX_U32)
              & (m_idx == _MAX_U32))
    n_hi, n_lo = global_nonce(base_hi, base_lo, m_idx)
    cand = jnp.stack([m_hi, m_lo, n_hi, n_lo])
    prev = carry[:4]
    better = valid & ((carry[4] == 0) | lex_less(cand, prev))
    best = jnp.where(better, cand, prev)
    seen = jnp.where(better, jnp.uint32(1), carry[4])
    return jnp.concatenate([best, seen[None]])


def fold_until(carry, f_idx, b_hi, b_lo, b_idx, base_hi, base_lo):
    """Fold one launch's first-hit lane + argmin fallback into the 8-word
    difficulty carry.

    ``f_idx`` is the window's minimal qualifying lane (MAX sentinel when
    none): the carry keeps the lex-lower 64-bit qualifying nonce across
    chained folds (chain order is not nonce order under interleaved
    stripe windows, so the min — not first-write-wins — is the correct
    rule). The argmin fallback folds exactly like :func:`fold_argmin`
    and answers only when the whole span misses the target.
    """
    cand_found = f_idx != _MAX_U32
    f_hi, f_lo = global_nonce(base_hi, base_lo, f_idx)
    fcand = jnp.stack([f_hi, f_lo])
    prev_f = carry[1:3]
    f_better = cand_found & ((carry[0] == 0) | lex_less(fcand, prev_f))
    new_f = jnp.where(f_better, fcand, prev_f)
    new_found = jnp.maximum(carry[0], cand_found.astype(jnp.uint32))
    tail = fold_argmin(carry[3:], b_hi, b_lo, b_idx, base_hi, base_lo)
    return jnp.concatenate([new_found[None], new_f, tail])


def decode_argmin(words, default_nonce: int) -> Tuple[int, int]:
    """Host decode of a fetched argmin carry -> (best_hash, nonce).

    An unseen carry (empty effective range) decodes to the MAX-hash
    sentinel at ``default_nonce`` — the same contract as an all-invalid
    host-merged span.
    """
    v = [int(x) for x in np.asarray(words).ravel()[:CARRY_WORDS]]
    if not v[4]:
        return _MAX_U64, int(default_nonce)
    return (v[0] << 32) | v[1], (v[2] << 32) | v[3]


def decode_until(words, default_nonce: int
                 ) -> Tuple[bool, int, int, int]:
    """Host decode of a fetched until carry ->
    ``(found, f_nonce, best_hash, best_nonce)``. The qualifying HASH is
    deliberately absent (the model layer recomputes that one value with
    the host oracle — the existing contract of ``search_span_until``)."""
    v = [int(x) for x in np.asarray(words).ravel()[:UNTIL_CARRY_WORDS]]
    found = bool(v[0])
    f_nonce = (v[1] << 32) | v[2]
    best_hash, best_nonce = decode_argmin(v[3:], default_nonce)
    return found, f_nonce, best_hash, best_nonce


@dataclasses.dataclass(frozen=True)
class SearchOp:
    """The minimal op protocol a device-resident span driver needs.

    ``init`` mints the neutral host-side carry, ``fold`` runs on device
    (jnp, inside jit/shard_map) merging one window's candidate into the
    carry, ``decode`` reads the final fetched words on the host. The
    span *body* (how lanes get hashed and reduced to a candidate) stays
    with the tier — ops/search.py and ops/sha256_pallas.py — because it
    is tier-shaped, not op-shaped; the op is everything downstream of
    the per-window reduction.
    """
    name: str
    carry_words: int
    init: Callable[[], np.ndarray]
    fold: Callable[..., "jnp.ndarray"]
    decode: Callable[..., tuple]

    @property
    def nbytes(self) -> int:
        """Size of the per-span host transfer this op costs (uint32s)."""
        return 4 * self.carry_words


ARGMIN_OP = SearchOp("argmin", CARRY_WORDS, carry_init,
                     fold_argmin, decode_argmin)
UNTIL_OP = SearchOp("until", UNTIL_CARRY_WORDS, until_carry_init,
                    fold_until, decode_until)
