"""Lane-vectorized SHA-256 arg-min search, jnp tier.

TPU-first design (replaces the reference's scalar hot loop,
ref: bitcoin/miner/miner.go:52-59 + bitcoin/hash.go:13-17):

- The search range is split on the host into chunks that live inside one
  aligned ``10^k`` block, so every nonce in a device call shares its top
  decimal digits. Those top digits join the constant prefix
  ``data + " " + top_digits`` whose complete 64-byte SHA blocks are absorbed
  into a host midstate; only the final 1-2 blocks run on device.
- A device call hashes a dense lane vector ``i = i0 + arange(B)`` of low-digit
  offsets (``i < 10^k <= 10^9`` fits uint32), formats the k ASCII digits in
  registers, runs the 64-round compression fully vectorized in uint32, and
  reduces to an exact lexicographic (hash_hi, hash_lo, index) arg-min.
- uint64 never materializes on device: the 8-byte big-endian hash prefix is
  carried as two uint32 lanes; ties resolve to the lowest index, matching the
  Go scan's first-seen-wins strict ``<``.

Everything is static-shaped; one compilation per (rem, k, nblocks, batch)
signature, reused across the whole search.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .sha256_host import SHA256_H0, SHA256_K

_MAX_U32 = np.uint32(0xFFFFFFFF)


def digit_positions(rem: int, k: int) -> list[tuple[int, int, int]]:
    """Static placement of the k ASCII digit bytes inside the tail blocks.

    Digit j (most significant first) sits at byte ``rem + j``; returns
    (block, word, shift) per digit for big-endian uint32 word packing.
    """
    out = []
    for j in range(k):
        pos = rem + j
        out.append((pos // 64, (pos % 64) // 4, (3 - pos % 4) * 8))
    return out


def digit_contrib(i, rem: int, k: int, base=None, span: int = 0):
    """Per-(block, word) uint32 contributions of the k ASCII digit bytes
    for the lane vector ``i``.

    High-digit hoist (VERDICT r4 task 3): when ``base`` (the scalar start
    of the window ``i`` covers) and ``span`` (its static length) are
    given, every digit whose divisor is at least the smallest 10^m >=
    span is constant across the window except at the single possible
    10^m boundary inside it. Those digits are computed ONCE on the
    scalar plane for the two candidate high parts (base // 10^m and the
    next) and selected per lane with one compare — replacing their
    per-lane div/mod chains; only the low m digits keep per-lane
    arithmetic. Lanes past the top of the digit class can receive
    garbage high digits from the +1 candidate; callers always mask such
    lanes invalid (they are outside [lo, hi]).
    """
    positions = list(digit_positions(rem, k))
    m = None
    if base is not None and span:
        m = next((t for t in range(1, k) if 10 ** t >= span), None)
    contrib: dict[tuple[int, int], jax.Array] = {}
    if m is None:
        for j, (blk, word, shift) in enumerate(positions):
            div = np.uint32(10 ** (k - 1 - j))
            digit = (i // div) % np.uint32(10) + np.uint32(48)
            key = (blk, word)
            add = digit << np.uint32(shift)
            contrib[key] = contrib[key] + add if key in contrib else add
        return contrib
    tenm = np.uint32(10 ** m)
    hb = base // tenm
    boundary = (hb + np.uint32(1)) * tenm
    # boundary wraps uint32 only when the true boundary exceeds 2^32, in
    # which case every lane of the window is below it.
    in_low = (i < boundary) | (boundary <= base)
    sel_a: dict[tuple[int, int], jax.Array] = {}
    sel_b: dict[tuple[int, int], jax.Array] = {}
    for j, (blk, word, shift) in enumerate(positions):
        div = 10 ** (k - 1 - j)
        key = (blk, word)
        if div >= 10 ** m:
            sub = np.uint32(div // 10 ** m)
            for hval, acc in ((hb, sel_a), (hb + np.uint32(1), sel_b)):
                d = (hval // sub) % np.uint32(10) + np.uint32(48)
                add = d << np.uint32(shift)
                acc[key] = acc[key] + add if key in acc else add
        else:
            digit = (i // np.uint32(div)) % np.uint32(10) + np.uint32(48)
            add = digit << np.uint32(shift)
            contrib[key] = contrib[key] + add if key in contrib else add
    for key, a_val in sel_a.items():
        sel = jnp.where(in_low, a_val, sel_b[key])
        contrib[key] = contrib[key] + sel if key in contrib else sel
    return contrib


def build_tail_template(tail: bytes, k: int, total_len: int) -> np.ndarray:
    """Padded final block(s) as (nblocks, 16) uint32, digit bytes zeroed.

    ``tail`` is the prefix remainder (< 64 bytes); the k digit bytes follow
    it, then 0x80, zero padding, and the 64-bit message bit length.
    """
    rem = len(tail)
    msg_len = rem + k
    data = bytearray(tail) + bytes(k)  # digit positions left as 0
    data.append(0x80)
    nblocks = 1 if msg_len + 1 + 8 <= 64 else 2
    data = data.ljust(nblocks * 64 - 8, b"\x00")
    data += int(total_len * 8).to_bytes(8, "big")
    words = np.frombuffer(bytes(data), dtype=">u4").astype(np.uint32)
    return words.reshape(nblocks, 16)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


_K64 = np.asarray(SHA256_K, dtype=np.uint32)


def ensure_varying(x, axes):
    """Type ``x`` as device-varying over ``axes`` (no-op for axes it already
    varies over) so shard_map loop carries have uniform varying-axis types."""
    x = jnp.asarray(x)
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        # jax 0.4.x (jax.experimental.shard_map): no vma type system —
        # replication is tracked by check_rep without annotations, so
        # there is nothing to cast.
        return x
    vma = getattr(typeof(x), "vma", frozenset())
    missing = tuple(a for a in axes if a not in vma)
    if not missing:
        return x
    return jax.lax.pcast(x, missing, to="varying")


def _round(a, b, c, d, e, f, g, h, kw):
    """One SHA-256 round; ``kw`` is the precombined K[t] + W[t] term."""
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    # ch/maj in their 3-op / 4-op forms (vs the definitional 4/5): the
    # kernel is VPU-ALU-bound, so every op/round is ~0.5% end-to-end.
    ch = g ^ (e & (f ^ g))
    t1 = h + s1 + ch + kw
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & (b ^ c)) ^ (b & c)
    return t1 + s0 + maj, a, b, c, d + t1, e, f, g


def _schedule_block(st, w, kvec):
    """16 rounds with the in-place mod-16 message-schedule window:
    w[t] = w[t-16] + s0(w[t-15]) + w[t-7] + s1(w[t-2]), in-place so later
    taps see already-updated entries."""
    for j in range(16):
        s0 = (_rotr(w[(j + 1) % 16], 7) ^ _rotr(w[(j + 1) % 16], 18)
              ^ (w[(j + 1) % 16] >> np.uint32(3)))
        s1 = (_rotr(w[(j + 14) % 16], 17) ^ _rotr(w[(j + 14) % 16], 19)
              ^ (w[(j + 14) % 16] >> np.uint32(10)))
        w[j] = w[j] + s0 + w[(j + 9) % 16] + s1
        st = _round(*st, kvec[j] + w[j])
    return st, w


def _compress(state, w16, vary_axes=(), unroll: bool = False):
    """One vectorized compression. state: 8 arrays; w16: 16 arrays.

    Two lowerings of the same bit-exact math:

    - rolled (default on CPU): a ``fori_loop`` over 16-round blocks with the
      classic in-place mod-16 message-schedule window. XLA:CPU compiles the
      fully unrolled 64-round chain in minutes (a superlinear pass blows up
      on the dependence chain); the rolled form compiles in seconds.
    - unrolled (opt-in): all 64 rounds static. Measured on TPU v5e this is
      ~300x SLOWER end-to-end at large batch (the live unrolled chain spills
      through HBM), so the rolled form is the default everywhere; the
      register-resident unrolled form lives in the Pallas kernel tier
      (``sha256_pallas``) where Mosaic keeps it on-chip.

    Inside ``shard_map`` pass the mesh axes as ``vary_axes`` so the rolled
    loop carry is uniformly device-varying.
    """
    if vary_axes:
        state = tuple(ensure_varying(x, vary_axes) for x in state)
        w16 = [ensure_varying(x, vary_axes) for x in w16]

    st = tuple(state)
    w = list(w16)
    # Rounds 0-15: static, schedule window untouched.
    for j in range(16):
        st = _round(*st, np.uint32(SHA256_K[j]) + w[j])

    if unroll:
        for blk in range(1, 4):
            st, w = _schedule_block(st, w, _K64[blk * 16:(blk + 1) * 16])
    else:
        k64 = jnp.asarray(_K64)

        def block(i, carry):
            st, w = carry
            kvec = jax.lax.dynamic_slice(k64, (i * 16,), (16,))
            st, w = _schedule_block(st, list(w), kvec)
            return st, tuple(w)

        st, _ = jax.lax.fori_loop(1, 4, block, (st, tuple(w)))
    return tuple(s + v for s, v in zip(state, st))


def lex_argmin(hi, lo, idx):
    """Exact argmin over (hi, lo) uint32 pairs; lowest idx wins ties."""
    min_hi = jnp.min(hi)
    on_hi = hi == min_hi
    min_lo = jnp.min(jnp.where(on_hi, lo, _MAX_U32))
    on_both = on_hi & (lo == min_lo)
    min_idx = jnp.min(jnp.where(on_both, idx, _MAX_U32))
    return min_hi, min_lo, min_idx


@functools.partial(jax.jit, static_argnames=("rem", "k", "batch"))
def _search_chunk(midstate, template, i0, lo_i, hi_i, *, rem: int, k: int,
                  batch: int):
    """Search lanes ``i0 + [0, batch)``; valid window is [lo_i, hi_i].

    midstate: (8,) uint32 after absorbing the full prefix blocks.
    template: (nblocks, 16) uint32 padded tail with digit bytes zeroed.
    Returns (min_hi, min_lo, argmin_i) uint32 scalars; invalid lanes carry
    the sentinel (0xffffffff, 0xffffffff, 0xffffffff).
    """
    i = i0 + jnp.arange(batch, dtype=jnp.uint32)
    nblocks = template.shape[0]

    # ASCII digit contributions, placed at their static byte positions;
    # digits above the window hoisted to the scalar plane (digit_contrib).
    contrib = digit_contrib(i, rem, k, base=i0, span=batch)

    state = tuple(jnp.broadcast_to(midstate[r], i.shape) for r in range(8))
    for blk in range(nblocks):
        w16 = []
        for word in range(16):
            base = jnp.broadcast_to(template[blk, word], i.shape)
            if (blk, word) in contrib:
                base = base | contrib[(blk, word)]
            w16.append(base)
        state = _compress(state, w16)

    valid = (i >= lo_i) & (i <= hi_i)
    hi_h = jnp.where(valid, state[0], _MAX_U32)
    lo_h = jnp.where(valid, state[1], _MAX_U32)
    idx = jnp.where(valid, i, _MAX_U32)
    return lex_argmin(hi_h, lo_h, idx)


def chunk_search_fn(rem: int, k: int, batch: int):
    """Bind the static signature; returns f(midstate, template, i0, lo, hi)."""
    def run(midstate, template, i0, lo_i, hi_i):
        return _search_chunk(
            jnp.asarray(midstate, dtype=jnp.uint32),
            jnp.asarray(template, dtype=jnp.uint32),
            jnp.uint32(i0), jnp.uint32(lo_i), jnp.uint32(hi_i),
            rem=rem, k=k, batch=batch)
    return run
