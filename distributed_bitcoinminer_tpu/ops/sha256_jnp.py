"""Lane-vectorized SHA-256 arg-min search, jnp tier.

TPU-first design (replaces the reference's scalar hot loop,
ref: bitcoin/miner/miner.go:52-59 + bitcoin/hash.go:13-17):

- The search range is split on the host into chunks that live inside one
  aligned ``10^k`` block, so every nonce in a device call shares its top
  decimal digits. Those top digits join the constant prefix
  ``data + " " + top_digits`` whose complete 64-byte SHA blocks are absorbed
  into a host midstate; only the final 1-2 blocks run on device.
- A device call hashes a dense lane vector ``i = i0 + arange(B)`` of low-digit
  offsets (``i < 10^k <= 10^9`` fits uint32), formats the k ASCII digits in
  registers, runs the 64-round compression fully vectorized in uint32, and
  reduces to an exact lexicographic (hash_hi, hash_lo, index) arg-min.
- uint64 never materializes on device: the 8-byte big-endian hash prefix is
  carried as two uint32 lanes; ties resolve to the lowest index, matching the
  Go scan's first-seen-wins strict ``<``.

Everything is static-shaped; one compilation per (rem, k, nblocks, batch)
signature, reused across the whole search.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .sha256_host import SHA256_H0, SHA256_K

_MAX_U32 = np.uint32(0xFFFFFFFF)
_M32 = 0xFFFFFFFF


def digit_positions(rem: int, k: int) -> list[tuple[int, int, int]]:
    """Static placement of the k ASCII digit bytes inside the tail blocks.

    Digit j (most significant first) sits at byte ``rem + j``; returns
    (block, word, shift) per digit for big-endian uint32 word packing.
    """
    out = []
    for j in range(k):
        pos = rem + j
        out.append((pos // 64, (pos % 64) // 4, (3 - pos % 4) * 8))
    return out


def digit_contrib(i, rem: int, k: int, base=None, span: int = 0):
    """Per-(block, word) uint32 contributions of the k ASCII digit bytes
    for the lane vector ``i``.

    High-digit hoist (VERDICT r4 task 3): when ``base`` (the scalar start
    of the window ``i`` covers) and ``span`` (its static length) are
    given, every digit whose divisor is at least the smallest 10^m >=
    span is constant across the window except at the single possible
    10^m boundary inside it. Those digits are computed ONCE on the
    scalar plane for the two candidate high parts (base // 10^m and the
    next) and selected per lane with one compare — replacing their
    per-lane div/mod chains; only the low m digits keep per-lane
    arithmetic. Lanes past the top of the digit class can receive
    garbage high digits from the +1 candidate; callers always mask such
    lanes invalid (they are outside [lo, hi]).
    """
    positions = list(digit_positions(rem, k))
    m = None
    if base is not None and span:
        m = next((t for t in range(1, k) if 10 ** t >= span), None)
    contrib: dict[tuple[int, int], jax.Array] = {}
    if m is None:
        for j, (blk, word, shift) in enumerate(positions):
            div = np.uint32(10 ** (k - 1 - j))
            digit = (i // div) % np.uint32(10) + np.uint32(48)
            key = (blk, word)
            add = digit << np.uint32(shift)
            contrib[key] = contrib[key] + add if key in contrib else add
        return contrib
    tenm = np.uint32(10 ** m)
    hb = base // tenm
    boundary = (hb + np.uint32(1)) * tenm
    # boundary wraps uint32 only when the true boundary exceeds 2^32, in
    # which case every lane of the window is below it.
    in_low = (i < boundary) | (boundary <= base)
    sel_a: dict[tuple[int, int], jax.Array] = {}
    sel_b: dict[tuple[int, int], jax.Array] = {}
    for j, (blk, word, shift) in enumerate(positions):
        div = 10 ** (k - 1 - j)
        key = (blk, word)
        if div >= 10 ** m:
            sub = np.uint32(div // 10 ** m)
            for hval, acc in ((hb, sel_a), (hb + np.uint32(1), sel_b)):
                d = (hval // sub) % np.uint32(10) + np.uint32(48)
                add = d << np.uint32(shift)
                acc[key] = acc[key] + add if key in acc else add
        else:
            digit = (i // np.uint32(div)) % np.uint32(10) + np.uint32(48)
            add = digit << np.uint32(shift)
            contrib[key] = contrib[key] + add if key in contrib else add
    for key, a_val in sel_a.items():
        sel = jnp.where(in_low, a_val, sel_b[key])
        contrib[key] = contrib[key] + sel if key in contrib else sel
    return contrib


def build_tail_template(tail: bytes, k: int, total_len: int) -> np.ndarray:
    """Padded final block(s) as (nblocks, 16) uint32, digit bytes zeroed.

    ``tail`` is the prefix remainder (< 64 bytes); the k digit bytes follow
    it, then 0x80, zero padding, and the 64-bit message bit length.
    """
    rem = len(tail)
    msg_len = rem + k
    data = bytearray(tail) + bytes(k)  # digit positions left as 0
    data.append(0x80)
    nblocks = 1 if msg_len + 1 + 8 <= 64 else 2
    data = data.ljust(nblocks * 64 - 8, b"\x00")
    data += int(total_len * 8).to_bytes(8, "big")
    words = np.frombuffer(bytes(data), dtype=">u4").astype(np.uint32)
    return words.reshape(nblocks, 16)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


_K64 = np.asarray(SHA256_K, dtype=np.uint32)


# ------------------------------------------------------------- hoist plane
#
# The tail blocks are ALMOST entirely lane-invariant: only the k ASCII
# digit bytes at positions rem..rem+k-1 vary per lane. The AsicBoost
# observation (arxiv 1604.00575) — factor work-item-invariant SHA-256 out
# of the inner loop — applies directly:
#
# (a) rounds 0..rem//4-1 of block 0 consume only constant words (the
#     first digit byte sits in word rem//4), so the round state after
#     them is computed ONCE on the host and the device starts deeper;
# (b) schedule taps over words that never receive digit bits are
#     constant; their s0/s1 terms and additive taps are precombined on
#     the host, so the device schedule of rounds 16..31 computes only
#     the varying taps (rounds 32..63 stay rolled: for small rem every
#     tap is varying by then; the residual constant taps of large rem
#     are computed per-lane there — unhoisted, never wrong);
# (c) a tail block with NO digit bytes at all (the padding+length block
#     of a 2-block tail when the digits fit block 0) has a fully
#     constant schedule: K[t]+W[t] for all 64 rounds precombined, the
#     device runs zero schedule arithmetic for that block.
#
# The structure (which words/taps vary) depends only on (rem, k,
# nblocks) — all static under jit — so hoist_structure() is re-derived
# at trace time; only the precombined VALUES ride as jit operands.

#: Schedule tap kinds: plain additive tap or a small-sigma term.
_TAPS = ((("w", -16), ("s0", -15), ("w", -7), ("s1", -2)))


def hoist_structure(rem: int, k: int, nblocks: int, static_rounds: int = 32):
    """Static constancy analysis of the tail blocks.

    Returns one ``(varying_words, var_taps, full_const)`` triple per
    block: the initial window words carrying digit bytes, and — for
    rounds 16..``static_rounds``-1 — the subset of each round's schedule
    taps that is lane-varying (the constant rest is folded into the
    host-built ``cw`` operand). ``full_const`` marks a digit-free block
    whose entire schedule hoists (see ``build_hoist``).

    ``static_rounds`` widens the static window past the default 32 (the
    ``DBM_HOIST_DEEP`` experiment: for large ``rem`` a few taps — e.g.
    rem=60: w16/w18/w20 — stay constant past round 31, which only an
    extended static window can exploit); must be a multiple of 16 so the
    rolled remainder starts on a 16-round block boundary. The pallas peel
    kernel always analyses at the default 32 — its chip-validated SMEM
    layout fixes 16 ``cw`` scalars per block.
    """
    assert static_rounds % 16 == 0 and 32 <= static_rounds <= 64
    pos = digit_positions(rem, k)
    blocks = []
    for b in range(nblocks):
        varying = tuple(sorted({w for (bb, w, _) in pos if bb == b}))
        if not varying:
            blocks.append((varying, (), True))
            continue
        var = [w in varying for w in range(16)]
        taps = []
        for t in range(16, static_rounds):
            tv = tuple((kind, t + off) for kind, off in _TAPS
                       if var[t + off])
            var.append(bool(tv))
            taps.append(tv)
        blocks.append((varying, tuple(taps), False))
    return tuple(blocks)


@dataclass(frozen=True)
class HoistPlan:
    """Host-precomputed lane-invariant SHA-256 work for one tail template.

    Built once per midstate-cache entry (models.miner_model._plan_block)
    and threaded through every compute tier as jit operands; the
    matching static structure is re-derived from (rem, k, nblocks) by
    :func:`hoist_structure` at trace time.
    """
    wd0: int                    #: rounds of block 0 hoisted into ``deep``
    nblocks: int
    full_const: tuple           #: per block: schedule fully constant
    hoisted_rounds: int         #: == wd0 (bench counter)
    schedule_terms_hoisted: int  #: constant schedule terms folded on host
    ops: dict                   #: jit operands: deep/kw/cw (+ckw)


def build_hoist(midstate, template: np.ndarray, rem: int, k: int,
                deep_window: bool | None = None) -> HoistPlan:
    """Precompute the hoist operands for one (midstate, template) pair.

    ``ops`` holds: ``deep`` (8,) — the round state after the first
    ``rem // 4`` rounds of block 0; ``kw`` (nblocks, 16) — K[j]+W[j]
    for rounds 0..15 (digit words add their per-lane contribution ON TOP,
    exact because the digit byte positions are zero in the template);
    ``cw`` (nblocks, 16) — the constant part of each expanded word
    w[16..31]; ``ckw`` (64,) — full K+W precombination of the one
    fully-constant block, when present.

    ``deep_window`` extends the static schedule window to rounds 16..47:
    the constant terms of w[32..47] ride an extra ``cw2`` (nblocks, 16)
    operand that only the jnp tier consumes (``compress_tail_hoisted``
    keys its structure analysis off the operand's presence; the pallas
    peel layout ignores unknown keys and keeps its 16-scalar-per-block
    ``cw`` section). Default: ``DBM_HOIST_DEEP`` when set, else ON for
    CPU backends and OFF on chip. The measured verdict (ROADMAP "hoist
    rounds 32+", ISSUE 4 satellite) is lopsided per platform: on XLA:CPU
    the residual constant taps are a rounding error but the widened
    static window leaves only ONE rolled 16-round iteration, which XLA
    inlines into a straight-line 64-round chain that vectorizes ~5x
    faster than the rolled carry (rem=60: 1.25M -> 7.08M nps; rem=7:
    2.40M -> 12.19M at the bench geometry, bit-identical results) — while
    on TPU the same unrolling is the known-catastrophic live-chain spill
    from round 1 (BASELINE.md), so the chip default stays rolled.
    """
    from .sha256_host import compress_rounds, schedule_words, sigma0, sigma1

    from ..utils._env import str_env
    if deep_window is None:
        env = str_env("DBM_HOIST_DEEP", "")
        if env:
            deep_window = env == "1"
        else:
            from ..utils.config import CHIP_PLATFORMS, jax_devices_robust
            deep_window = (jax_devices_robust()[0].platform
                           not in CHIP_PLATFORMS)
    static_rounds = 48 if deep_window else 32
    nblocks = int(template.shape[0])
    struct = hoist_structure(rem, k, nblocks, static_rounds)
    wd0 = struct[0][0][0]   # first digit word of block 0 == rem // 4
    deep = compress_rounds(midstate, [int(x) for x in template[0]], 0, wd0)
    kw = np.zeros((nblocks, 16), dtype=np.uint32)
    cw = np.zeros((nblocks, static_rounds - 16), dtype=np.uint32)
    ckw = None
    terms = 0
    for b, (varying, taps, full) in enumerate(struct):
        words = [int(x) for x in template[b]]
        if full:
            sched = schedule_words(words)
            ckw = np.asarray([(SHA256_K[t] + sched[t]) & _M32
                              for t in range(64)], dtype=np.uint32)
            terms += 4 * 48   # every tap of every expanded word
            continue
        kw[b] = [(SHA256_K[j] + words[j]) & _M32 for j in range(16)]
        vals: list = words + [None] * (static_rounds - 16)
        for i, tv in enumerate(taps):
            t = 16 + i
            acc = 0
            for kind, off in _TAPS:
                if (kind, t + off) in tv:
                    continue
                v = vals[t + off]
                acc += (v if kind == "w"
                        else sigma0(v) if kind == "s0" else sigma1(v))
                terms += 1
            cw[b, i] = acc & _M32
            if not tv:
                vals[t] = int(cw[b, i])
    ops = {"deep": np.asarray(deep, dtype=np.uint32), "kw": kw,
           "cw": cw[:, :16]}
    if static_rounds > 32:
        ops["cw2"] = cw[:, 16:]
    if ckw is not None:
        ops["ckw"] = ckw
    return HoistPlan(wd0=wd0, nblocks=nblocks,
                     full_const=tuple(s[2] for s in struct),
                     hoisted_rounds=wd0, schedule_terms_hoisted=terms,
                     ops=ops)


def _sig0(x):
    return _rotr(x, 7) ^ _rotr(x, 18) ^ (x >> np.uint32(3))


def _sig1(x):
    return _rotr(x, 17) ^ _rotr(x, 19) ^ (x >> np.uint32(10))


def _compress_const_block(ff, ckw, vary_axes=()):
    """Compression of a fully-constant block: NO schedule arithmetic.

    ``ckw`` is the (64,) host-precombined K[t]+W[t] vector; the rolled
    fori carries only the 8 state tiles (vs 24 with the schedule
    window), which also cuts the loop's memory traffic by 2/3.
    """
    st = tuple(ff)
    for j in range(16):
        st = _round(*st, ckw[j])
    if vary_axes:
        st = tuple(ensure_varying(x, vary_axes) for x in st)
        ckw = ensure_varying(ckw, vary_axes)

    def body(bi, st8):
        kvec = jax.lax.dynamic_slice(ckw, (bi * 16,), (16,))
        for j in range(16):
            st8 = _round(*st8, kvec[j])
        return st8

    st = jax.lax.fori_loop(1, 4, body, st)
    return tuple(f + s for f, s in zip(ff, st))


def _compress_block_hoisted(ff, entry, wd, varying, taps, contribs, tw,
                            kwv, cwv, shape, vary_axes=()):
    """Hoist-aware compression of one digit-carrying block.

    ``ff`` is the feed-forward base (the block's true input state);
    ``entry`` the round state the device enters at round ``wd`` (block
    0: the host-extended deep midstate; later blocks: ``ff`` itself with
    ``wd == 0``). Rounds wd..15 run schedule-free off the precombined
    ``kwv``; rounds 16..15+len(taps) are static with only the varying
    taps computed per lane (constant terms ride ``cwv`` — 16 entries for
    the default window, 32 under ``DBM_HOIST_DEEP``); the remaining
    rounds stay rolled — by then the window is carried as full tiles
    either way.
    """
    st = tuple(entry)
    for j in range(wd, 16):
        kwj = kwv[j]
        if j in varying:
            kwj = kwj + contribs[j]
        st = _round(*st, kwj)
    # Lane-varying initial window values (constant ones live in cwv).
    wv = {j: tw[j] + contribs[j] for j in varying}
    for i, tv in enumerate(taps):
        t = 16 + i
        acc = cwv[i]
        for kind, tap in tv:
            x = wv[tap]
            acc = acc + (x if kind == "w"
                         else _sig0(x) if kind == "s0" else _sig1(x))
        wv[t] = acc
        st = _round(*st, acc + np.uint32(SHA256_K[t]))
    static_rounds = 16 + len(taps)
    w = [jnp.broadcast_to(jnp.asarray(wv[static_rounds - 16 + j],
                                      jnp.uint32), shape)
         for j in range(16)]
    st = [jnp.broadcast_to(jnp.asarray(x, jnp.uint32), shape) for x in st]
    if vary_axes:
        st = [ensure_varying(x, vary_axes) for x in st]
        w = [ensure_varying(x, vary_axes) for x in w]
    k64 = jnp.asarray(_K64)

    def block(i, carry):
        st, w = carry
        kvec = jax.lax.dynamic_slice(k64, (i * 16,), (16,))
        st, w = _schedule_block(st, list(w), kvec)
        return st, tuple(w)

    st, _ = jax.lax.fori_loop(static_rounds // 16, 4, block,
                              (tuple(st), tuple(w)))
    return tuple(f + s for f, s in zip(ff, st))


def compress_tail_hoisted(midstate, template, contrib, hoist_ops, *,
                          rem: int, k: int, shape, vary_axes=()):
    """Full hoisted tail compression; returns the 8 output words.

    ``contrib`` is the per-(block, word) digit-contribution dict of
    :func:`digit_contrib`; ``hoist_ops`` the operand dict of
    :func:`build_hoist` (values traced, structure re-derived here).
    Bit-identical to the plain path — the oracle-equivalence sweep in
    tests/test_hoist.py pins that across rem/k/block boundaries.
    """
    nblocks = template.shape[0]
    # The static-window width is keyed off the OPERANDS (a ``cw2`` section
    # is only built under DBM_HOIST_DEEP), so trace-time structure always
    # matches the host precompute — and a changed knob forces a retrace
    # through the changed operand shapes, never a silent mismatch.
    static_rounds = 48 if "cw2" in hoist_ops else 32
    struct = hoist_structure(rem, k, nblocks, static_rounds)
    # Coerce to jnp up front: a no-op under jit, and in eager use it keeps
    # the scalar-plane adds on jnp's wrapping uint32 instead of numpy
    # scalars (whose wraparound spams RuntimeWarnings).
    midstate = jnp.asarray(midstate, jnp.uint32)
    template = jnp.asarray(template, jnp.uint32)
    hoist_ops = {k_: jnp.asarray(v, jnp.uint32)
                 for k_, v in hoist_ops.items()}
    deep, kw, cw = hoist_ops["deep"], hoist_ops["kw"], hoist_ops["cw"]
    if static_rounds > 32:
        cw = jnp.concatenate([cw, hoist_ops["cw2"]], axis=1)
    out = None
    for b, (varying, taps, full) in enumerate(struct):
        ff = (tuple(midstate[r] for r in range(8)) if b == 0 else out)
        if full:
            out = _compress_const_block(ff, hoist_ops["ckw"],
                                        vary_axes=vary_axes)
            continue
        entry = tuple(deep[r] for r in range(8)) if b == 0 else ff
        wd = struct[0][0][0] if b == 0 else 0
        contribs = {w: contrib[(b, w)] for w in varying}
        out = _compress_block_hoisted(
            ff, entry, wd, varying, taps, contribs,
            tw=[template[b, j] for j in range(16)],
            kwv=[kw[b, j] for j in range(16)],
            cwv=[cw[b, i] for i in range(static_rounds - 16)],
            shape=shape, vary_axes=vary_axes)
    return out


def ensure_varying(x, axes):
    """Type ``x`` as device-varying over ``axes`` (no-op for axes it already
    varies over) so shard_map loop carries have uniform varying-axis types."""
    x = jnp.asarray(x)
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        # jax 0.4.x (jax.experimental.shard_map): no vma type system —
        # replication is tracked by check_rep without annotations, so
        # there is nothing to cast.
        return x
    vma = getattr(typeof(x), "vma", frozenset())
    missing = tuple(a for a in axes if a not in vma)
    if not missing:
        return x
    return jax.lax.pcast(x, missing, to="varying")


def _round(a, b, c, d, e, f, g, h, kw):
    """One SHA-256 round; ``kw`` is the precombined K[t] + W[t] term."""
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    # ch/maj in their 3-op / 4-op forms (vs the definitional 4/5): the
    # kernel is VPU-ALU-bound, so every op/round is ~0.5% end-to-end.
    ch = g ^ (e & (f ^ g))
    t1 = h + s1 + ch + kw
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & (b ^ c)) ^ (b & c)
    return t1 + s0 + maj, a, b, c, d + t1, e, f, g


def _schedule_block(st, w, kvec):
    """16 rounds with the in-place mod-16 message-schedule window:
    w[t] = w[t-16] + s0(w[t-15]) + w[t-7] + s1(w[t-2]), in-place so later
    taps see already-updated entries."""
    for j in range(16):
        s0 = (_rotr(w[(j + 1) % 16], 7) ^ _rotr(w[(j + 1) % 16], 18)
              ^ (w[(j + 1) % 16] >> np.uint32(3)))
        s1 = (_rotr(w[(j + 14) % 16], 17) ^ _rotr(w[(j + 14) % 16], 19)
              ^ (w[(j + 14) % 16] >> np.uint32(10)))
        w[j] = w[j] + s0 + w[(j + 9) % 16] + s1
        st = _round(*st, kvec[j] + w[j])
    return st, w


def _compress(state, w16, vary_axes=(), unroll: bool = False):
    """One vectorized compression. state: 8 arrays; w16: 16 arrays.

    Two lowerings of the same bit-exact math:

    - rolled (default on CPU): a ``fori_loop`` over 16-round blocks with the
      classic in-place mod-16 message-schedule window. XLA:CPU compiles the
      fully unrolled 64-round chain in minutes (a superlinear pass blows up
      on the dependence chain); the rolled form compiles in seconds.
    - unrolled (opt-in): all 64 rounds static. Measured on TPU v5e this is
      ~300x SLOWER end-to-end at large batch (the live unrolled chain spills
      through HBM), so the rolled form is the default everywhere; the
      register-resident unrolled form lives in the Pallas kernel tier
      (``sha256_pallas``) where Mosaic keeps it on-chip.

    Inside ``shard_map`` pass the mesh axes as ``vary_axes`` so the rolled
    loop carry is uniformly device-varying.
    """
    if vary_axes:
        state = tuple(ensure_varying(x, vary_axes) for x in state)
        w16 = [ensure_varying(x, vary_axes) for x in w16]

    st = tuple(state)
    w = list(w16)
    # Rounds 0-15: static, schedule window untouched.
    for j in range(16):
        st = _round(*st, np.uint32(SHA256_K[j]) + w[j])

    if unroll:
        for blk in range(1, 4):
            st, w = _schedule_block(st, w, _K64[blk * 16:(blk + 1) * 16])
    else:
        k64 = jnp.asarray(_K64)

        def block(i, carry):
            st, w = carry
            kvec = jax.lax.dynamic_slice(k64, (i * 16,), (16,))
            st, w = _schedule_block(st, list(w), kvec)
            return st, tuple(w)

        st, _ = jax.lax.fori_loop(1, 4, block, (st, tuple(w)))
    return tuple(s + v for s, v in zip(state, st))


def lex_argmin(hi, lo, idx):
    """Exact argmin over (hi, lo) uint32 pairs; lowest idx wins ties."""
    min_hi = jnp.min(hi)
    on_hi = hi == min_hi
    min_lo = jnp.min(jnp.where(on_hi, lo, _MAX_U32))
    on_both = on_hi & (lo == min_lo)
    min_idx = jnp.min(jnp.where(on_both, idx, _MAX_U32))
    return min_hi, min_lo, min_idx


@functools.partial(jax.jit, static_argnames=("rem", "k", "batch"))
def _search_chunk(midstate, template, i0, lo_i, hi_i, *, rem: int, k: int,
                  batch: int):
    """Search lanes ``i0 + [0, batch)``; valid window is [lo_i, hi_i].

    midstate: (8,) uint32 after absorbing the full prefix blocks.
    template: (nblocks, 16) uint32 padded tail with digit bytes zeroed.
    Returns (min_hi, min_lo, argmin_i) uint32 scalars; invalid lanes carry
    the sentinel (0xffffffff, 0xffffffff, 0xffffffff).
    """
    i = i0 + jnp.arange(batch, dtype=jnp.uint32)
    nblocks = template.shape[0]

    # ASCII digit contributions, placed at their static byte positions;
    # digits above the window hoisted to the scalar plane (digit_contrib).
    contrib = digit_contrib(i, rem, k, base=i0, span=batch)

    state = tuple(jnp.broadcast_to(midstate[r], i.shape) for r in range(8))
    for blk in range(nblocks):
        w16 = []
        for word in range(16):
            base = jnp.broadcast_to(template[blk, word], i.shape)
            if (blk, word) in contrib:
                base = base | contrib[(blk, word)]
            w16.append(base)
        state = _compress(state, w16)

    valid = (i >= lo_i) & (i <= hi_i)
    hi_h = jnp.where(valid, state[0], _MAX_U32)
    lo_h = jnp.where(valid, state[1], _MAX_U32)
    idx = jnp.where(valid, i, _MAX_U32)
    return lex_argmin(hi_h, lo_h, idx)


def chunk_search_fn(rem: int, k: int, batch: int):
    """Bind the static signature; returns f(midstate, template, i0, lo, hi)."""
    def run(midstate, template, i0, lo_i, hi_i):
        return _search_chunk(
            jnp.asarray(midstate, dtype=jnp.uint32),
            jnp.asarray(template, dtype=jnp.uint32),
            jnp.uint32(i0), jnp.uint32(lo_i), jnp.uint32(hi_i),
            rem=rem, k=k, batch=batch)
    return run
