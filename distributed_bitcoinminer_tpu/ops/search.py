"""Device-side chunked scan over a nonce span with an argmin carry.

One jitted dispatch covers a whole aligned 10^k block: a ``lax.fori_loop``
walks the span in ``batch``-lane steps, each step hashing its lanes and
folding into a running (hash_hi, hash_lo, index) best. Strict ``<`` keeps
the earliest index across steps, matching the Go scan's tie rule
(ref: bitcoin/miner/miner.go:54-58).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .searchop import fold_argmin, fold_until
from .sha256_host import SHA256_K
from .sha256_jnp import (_compress, compress_tail_hoisted, digit_contrib,
                         ensure_varying, lex_argmin)

_MAX_U32 = np.uint32(0xFFFFFFFF)


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= ``n`` (>= 1).

    THE quantizer for batched-dispatch row counts (ISSUE 9): the number
    of rows in a coalesced launch follows live traffic, so using it raw
    as an operand SHAPE would mint a fresh jit signature per distinct
    batch width — the same recompile-storm class as EWMA-drifted
    ``nbatches`` (PR 4). Bucketing to pow2 bounds the signature set at
    log2(max rows). The dbmlint jit-static analyzer recognizes calls to
    this helper as bounded, so call sites stay machine-checked.
    """
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


def devloop_cap(n: int) -> int:
    """Static iteration cap for a device-resident span launch (ISSUE 19):
    smallest power of two >= ``n``.

    The devloop drivers take the LIVE sub-window count as a traced
    operand — the loop bound is ``min(nsub, cap)`` — and only this cap
    as a jit static, so the signature set stays bounded at log2(max
    subs) exactly like batched-dispatch row counts, with no masked
    overscan (the loop simply stops at ``nsub``). The cap doubles as
    the bounded-iterations backstop for the ``until`` while_loop. The
    dbmlint jit-static analyzer recognizes calls to this helper as
    bounded (same contract as :func:`pow2_bucket`, which it delegates
    to).
    """
    return pow2_bucket(n)


def _hash_lanes(midstate, template, i, rem: int, k: int, vary_axes=(),
                base=None, span: int = 0, hoist=None):
    """Hash a lane vector of low-digit offsets; returns (hi, lo) uint32.

    ``base``/``span``: the scalar start and static length of the window
    ``i`` covers, enabling the high-digit hoist (see
    :func:`sha256_jnp.digit_contrib`). ``hoist`` is the optional
    lane-invariant precompute operand dict (``HoistPlan.ops``): with it,
    the compression enters at the host-extended deep midstate and skips
    the constant schedule terms (:func:`sha256_jnp.compress_tail_hoisted`);
    without it the original rolled path runs — both are bit-identical.
    """
    contrib = digit_contrib(i, rem, k, base=base, span=span)
    if hoist is not None:
        state = compress_tail_hoisted(midstate, template, contrib, hoist,
                                      rem=rem, k=k, shape=i.shape,
                                      vary_axes=vary_axes)
        return state[0], state[1]

    state = tuple(jnp.broadcast_to(midstate[r], i.shape) for r in range(8))
    for blk in range(template.shape[0]):
        w16 = []
        for word in range(16):
            base = jnp.broadcast_to(template[blk, word], i.shape)
            if (blk, word) in contrib:
                base = base | contrib[(blk, word)]
            w16.append(base)
        state = _compress(state, w16, vary_axes=vary_axes)
    return state[0], state[1]


def span_scan_body(midstate, template, i0, lo_i, hi_i, *, rem: int, k: int,
                   batch: int, nbatches: int, vary_axes=(), hoist=None):
    """Unjitted span scan: lanes ``i0 + [0, nbatches*batch)`` masked to
    [lo_i, hi_i]. Shared by the jitted single-device entry point and the
    shard_map per-device body in ``parallel/`` (which passes its mesh axis
    as ``vary_axes`` so the loop carry is typed device-varying).

    Returns (best_hi, best_lo, best_i) uint32 scalars; all-invalid spans
    return the (0xffffffff, 0xffffffff, 0xffffffff) sentinel.
    """
    lane = jnp.arange(batch, dtype=jnp.uint32)

    def step(j, best):
        base = i0 + j.astype(jnp.uint32) * np.uint32(batch)
        i = base + lane
        hi_h, lo_h = _hash_lanes(midstate, template, i, rem, k,
                                 vary_axes=vary_axes, base=base, span=batch,
                                 hoist=hoist)
        valid = (i >= lo_i) & (i <= hi_i)
        hi_h = jnp.where(valid, hi_h, _MAX_U32)
        lo_h = jnp.where(valid, lo_h, _MAX_U32)
        idx = jnp.where(valid, i, _MAX_U32)
        c_hi, c_lo, c_i = lex_argmin(hi_h, lo_h, idx)
        b_hi, b_lo, b_i = best
        # Strict less => the earlier batch keeps ties (Go first-seen-wins).
        better = (c_hi < b_hi) | ((c_hi == b_hi) & (c_lo < b_lo))
        return (jnp.where(better, c_hi, b_hi),
                jnp.where(better, c_lo, b_lo),
                jnp.where(better, c_i, b_i))

    init = (jnp.uint32(_MAX_U32),) * 3
    if vary_axes:
        init = tuple(ensure_varying(x, vary_axes) for x in init)
    if nbatches == 1:
        return step(jnp.uint32(0), init)
    return jax.lax.fori_loop(0, nbatches, step, init,
                             unroll=False)


@functools.partial(jax.jit,
                   static_argnames=("rem", "k", "batch", "nbatches"))
def search_span(midstate, template, i0, lo_i, hi_i, hoist=None, *,
                rem: int, k: int, batch: int, nbatches: int):
    """Jitted single-device span scan (see :func:`span_scan_body`)."""
    midstate = jnp.asarray(midstate, dtype=jnp.uint32)
    template = jnp.asarray(template, dtype=jnp.uint32)
    return span_scan_body(midstate, template, i0, lo_i, hi_i,
                          rem=rem, k=k, batch=batch, nbatches=nbatches,
                          hoist=hoist)


def span_until_body(midstate, template, i0, lo_i, hi_i, target_hi,
                    target_lo, *, rem: int, k: int, batch: int,
                    nbatches: int, vary_axes=(), hoist=None):
    """Unjitted difficulty-target span scan: stop at the first batch holding
    a hash below the 64-bit target (as a (hi, lo) uint32 pair).

    A ``while_loop`` walks the span in ascending lane batches and exits as
    soon as a batch contains a qualifying hash — the in-kernel early-exit of
    the difficulty-target mode. Returns uint32 scalars
    ``(found, f_idx, best_hi, best_lo, best_idx)``: the FIRST (lowest)
    qualifying nonce index when ``found`` is 1, plus the running argmin
    over all scanned lanes either way (the fallback result when the whole
    span misses the target). The qualifying HASH is deliberately not
    returned — the model layer recomputes that one value with the host
    oracle (models.miner_model._until_block), which keeps this contract
    identical to the pallas tier's and drops two per-batch reductions
    from the loop.

    Shared by the jitted single-device entry point and the shard_map
    per-device body (``parallel/mesh_search.py``), which passes its mesh
    axis as ``vary_axes``; the loop predicate is then device-varying, so
    each device early-exits independently (no collectives in the loop).
    """
    lane = jnp.arange(batch, dtype=jnp.uint32)

    def cond(carry):
        j, f_idx, _best = carry
        return (j < nbatches) & (f_idx == _MAX_U32)

    def body(carry):
        j, f_idx, best = carry
        base = i0 + j.astype(jnp.uint32) * np.uint32(batch)
        i = base + lane
        hi_h, lo_h = _hash_lanes(midstate, template, i, rem, k,
                                 vary_axes=vary_axes, base=base, span=batch,
                                 hoist=hoist)
        valid = (i >= lo_i) & (i <= hi_i)
        hi_h = jnp.where(valid, hi_h, _MAX_U32)
        lo_h = jnp.where(valid, lo_h, _MAX_U32)
        idx = jnp.where(valid, i, _MAX_U32)
        # Running argmin fallback.
        c_hi, c_lo, c_i = lex_argmin(hi_h, lo_h, idx)
        b_hi, b_lo, b_i = best
        better = (c_hi < b_hi) | ((c_hi == b_hi) & (c_lo < b_lo))
        best = (jnp.where(better, c_hi, b_hi),
                jnp.where(better, c_lo, b_lo),
                jnp.where(better, c_i, b_i))
        # First qualifying lane in this batch (lowest nonce wins).
        qual = valid & ((hi_h < target_hi)
                        | ((hi_h == target_hi) & (lo_h < target_lo)))
        q_idx = jnp.min(jnp.where(qual, i, _MAX_U32))
        return (j + 1, q_idx, best)

    init = (jnp.int32(0), jnp.uint32(_MAX_U32),
            (jnp.uint32(_MAX_U32),) * 3)
    if vary_axes:
        init = jax.tree.map(lambda x: ensure_varying(x, vary_axes), init)
    j, f_idx, best = jax.lax.while_loop(cond, body, init)
    found = (f_idx != _MAX_U32).astype(jnp.uint32)
    return found, f_idx, best[0], best[1], best[2]


@functools.partial(jax.jit,
                   static_argnames=("rem", "k", "batch", "nbatches"))
def search_span_until(midstate, template, i0, lo_i, hi_i, target_hi,
                      target_lo, hoist=None, *, rem: int, k: int,
                      batch: int, nbatches: int):
    """Jitted single-device difficulty-target scan
    (see :func:`span_until_body`)."""
    midstate = jnp.asarray(midstate, dtype=jnp.uint32)
    template = jnp.asarray(template, dtype=jnp.uint32)
    return span_until_body(midstate, template, i0, lo_i, hi_i,
                           target_hi, target_lo,
                           rem=rem, k=k, batch=batch, nbatches=nbatches,
                           hoist=hoist)


# --------------------------------------------------------------------------
# ISSUE 19 device-resident span loop (jnp tier).
#
# The stock path above runs ONE launch PER pow2 sub-window and merges the
# per-sub triples on the host. The devloop drivers below iterate every
# sub-window of a block inside a single launch with a DYNAMIC loop bound
# (``min(nsub, cap)`` — ``nsub`` is a traced operand, only the pow2
# ``cap`` is a jit static, see :func:`devloop_cap`), and fold the block's
# merged candidate straight into the searchop carry
# (:mod:`ops.searchop`). A whole span — any number of 10^k blocks —
# chains carries device-side and costs exactly one jitted launch per
# block and ONE carry fetch per span.


def devloop_scan(midstate, template, i0, lo_i, hi_i, nsub, *, rem: int,
                 k: int, batch: int, cap: int, vary_axes=(), hoist=None):
    """Dynamic-bound span scan: ``min(nsub, cap)`` sub-windows of
    ``batch`` lanes from ``i0``, masked to [lo_i, hi_i].

    Same per-step math as :func:`span_scan_body` (strict-less fold,
    earliest index keeps ties); the bound is traced, so the fori_loop
    lowers to a while_loop — no masked overscan beyond ``nsub``.
    Returns the (best_hi, best_lo, best_i) uint32 triple.
    """
    lane = jnp.arange(batch, dtype=jnp.uint32)
    bound = jnp.minimum(jnp.asarray(nsub, dtype=jnp.int32),
                        jnp.int32(cap))

    def step(j, best):
        base = i0 + j.astype(jnp.uint32) * np.uint32(batch)
        i = base + lane
        hi_h, lo_h = _hash_lanes(midstate, template, i, rem, k,
                                 vary_axes=vary_axes, base=base, span=batch,
                                 hoist=hoist)
        valid = (i >= lo_i) & (i <= hi_i)
        hi_h = jnp.where(valid, hi_h, _MAX_U32)
        lo_h = jnp.where(valid, lo_h, _MAX_U32)
        idx = jnp.where(valid, i, _MAX_U32)
        c_hi, c_lo, c_i = lex_argmin(hi_h, lo_h, idx)
        b_hi, b_lo, b_i = best
        # Strict less => the earlier batch keeps ties (Go first-seen-wins).
        better = (c_hi < b_hi) | ((c_hi == b_hi) & (c_lo < b_lo))
        return (jnp.where(better, c_hi, b_hi),
                jnp.where(better, c_lo, b_lo),
                jnp.where(better, c_i, b_i))

    init = (jnp.uint32(_MAX_U32),) * 3
    if vary_axes:
        init = tuple(ensure_varying(x, vary_axes) for x in init)
    return jax.lax.fori_loop(0, bound, step, init)


@functools.partial(jax.jit, static_argnames=("rem", "k", "batch", "cap"))
def devloop_span(midstate, template, carry, i0, lo_i, hi_i, nsub,
                 base_hi, base_lo, hoist=None, *, rem: int, k: int,
                 batch: int, cap: int):
    """Jitted single-device devloop block launch: scan ``nsub``
    sub-windows on device and fold the result into the 5-word argmin
    carry (:mod:`ops.searchop` layout — the carry holds the GLOBAL
    64-bit nonce, ``base_hi``/``base_lo`` are the block base). Returns
    the updated carry, a device value the caller threads into the next
    block's launch or fetches once per span."""
    midstate = jnp.asarray(midstate, dtype=jnp.uint32)
    template = jnp.asarray(template, dtype=jnp.uint32)
    carry = jnp.asarray(carry, dtype=jnp.uint32)
    b_hi, b_lo, b_i = devloop_scan(midstate, template, i0, lo_i, hi_i,
                                   nsub, rem=rem, k=k, batch=batch,
                                   cap=cap, hoist=hoist)
    return fold_argmin(carry, b_hi, b_lo, b_i, base_hi, base_lo)


def devloop_until_scan(midstate, template, i0, lo_i, hi_i, target_hi,
                       target_lo, nsub, found_prev, *, rem: int, k: int,
                       batch: int, cap: int, vary_axes=(), hoist=None):
    """Dynamic-bound difficulty scan with the on-device first-hit
    predicate in the while condition: exits at the first sub-window
    holding a qualifying hash, at ``nsub`` sub-windows, at the ``cap``
    backstop — or immediately when ``found_prev`` says an earlier block
    of the chain already hit (the carry passes through untouched).

    Same per-step math and first-*qualifying*-nonce semantics as
    :func:`span_until_body`; returns the same uint32
    ``(found, f_idx, best_hi, best_lo, best_idx)`` scalars.
    """
    lane = jnp.arange(batch, dtype=jnp.uint32)
    bound = jnp.minimum(jnp.asarray(nsub, dtype=jnp.int32),
                        jnp.int32(cap))
    live = jnp.asarray(found_prev, dtype=jnp.uint32) == 0

    def cond(carry):
        j, f_idx, _best = carry
        return (j < bound) & (f_idx == _MAX_U32) & live

    def body(carry):
        j, f_idx, best = carry
        base = i0 + j.astype(jnp.uint32) * np.uint32(batch)
        i = base + lane
        hi_h, lo_h = _hash_lanes(midstate, template, i, rem, k,
                                 vary_axes=vary_axes, base=base, span=batch,
                                 hoist=hoist)
        valid = (i >= lo_i) & (i <= hi_i)
        hi_h = jnp.where(valid, hi_h, _MAX_U32)
        lo_h = jnp.where(valid, lo_h, _MAX_U32)
        idx = jnp.where(valid, i, _MAX_U32)
        # Running argmin fallback.
        c_hi, c_lo, c_i = lex_argmin(hi_h, lo_h, idx)
        b_hi, b_lo, b_i = best
        better = (c_hi < b_hi) | ((c_hi == b_hi) & (c_lo < b_lo))
        best = (jnp.where(better, c_hi, b_hi),
                jnp.where(better, c_lo, b_lo),
                jnp.where(better, c_i, b_i))
        # First qualifying lane in this batch (lowest nonce wins).
        qual = valid & ((hi_h < target_hi)
                        | ((hi_h == target_hi) & (lo_h < target_lo)))
        q_idx = jnp.min(jnp.where(qual, i, _MAX_U32))
        return (j + 1, q_idx, best)

    init = (jnp.int32(0), jnp.uint32(_MAX_U32),
            (jnp.uint32(_MAX_U32),) * 3)
    if vary_axes:
        init = jax.tree.map(lambda x: ensure_varying(x, vary_axes), init)
    j, f_idx, best = jax.lax.while_loop(cond, body, init)
    found = (f_idx != _MAX_U32).astype(jnp.uint32)
    return found, f_idx, best[0], best[1], best[2]


@functools.partial(jax.jit, static_argnames=("rem", "k", "batch", "cap"))
def devloop_span_until(midstate, template, carry, i0, lo_i, hi_i,
                       target_hi, target_lo, nsub, base_hi, base_lo,
                       hoist=None, *, rem: int, k: int, batch: int,
                       cap: int):
    """Jitted single-device devloop difficulty block launch: early-exit
    scan + fold into the 8-word until carry. A chain of these across a
    span's blocks stops doing work the moment one block hits (the next
    launches see ``carry[0]`` set and fall straight through), so the
    whole span costs one fetch regardless of where the hit lands."""
    midstate = jnp.asarray(midstate, dtype=jnp.uint32)
    template = jnp.asarray(template, dtype=jnp.uint32)
    carry = jnp.asarray(carry, dtype=jnp.uint32)
    found, f_idx, b_hi, b_lo, b_i = devloop_until_scan(
        midstate, template, i0, lo_i, hi_i, target_hi, target_lo, nsub,
        carry[0], rem=rem, k=k, batch=batch, cap=cap, hoist=hoist)
    return fold_until(carry, f_idx, b_hi, b_lo, b_i, base_hi, base_lo)


def segmin_rows(hi_h, lo_h, idx, seg, num_segments: int):
    """Per-segment lexicographic (hi, lo, idx) min over row vectors.

    ``seg`` maps each row to its segment (sorted ascending by
    construction — the batch planner assigns segment ids in row order;
    padded rows point at the last slot). The lex rule matches
    :func:`sha256_jnp.lex_argmin` per segment: min hi, then min lo among
    hi-ties, then min idx among (hi, lo)-ties — lowest nonce wins ties,
    and all-sentinel segments (padding, empty windows) come out as the
    (MAX, MAX, MAX) sentinel, exactly like an all-invalid span.
    """
    seg_hi = jax.ops.segment_min(hi_h, seg, num_segments=num_segments,
                                 indices_are_sorted=True)
    on_hi = hi_h == seg_hi[seg]
    seg_lo = jax.ops.segment_min(jnp.where(on_hi, lo_h, _MAX_U32), seg,
                                 num_segments=num_segments,
                                 indices_are_sorted=True)
    on_both = on_hi & (lo_h == seg_lo[seg])
    seg_idx = jax.ops.segment_min(jnp.where(on_both, idx, _MAX_U32), seg,
                                  num_segments=num_segments,
                                  indices_are_sorted=True)
    return seg_hi, seg_lo, seg_idx


@functools.partial(jax.jit,
                   static_argnames=("rem", "k", "batch", "nbatches"))
def search_span_segmin(midstates, templates, i0s, lo_is, hi_is, seg,
                       hoists=None, *, rem: int, k: int, batch: int,
                       nbatches: int):
    """Batched multi-row span scan with a per-request SEGMENT-min
    (ISSUE 9: cross-request batched dispatch).

    One device launch scans R independent rows — each row a full
    :func:`span_scan_body` over its own ``(midstate, template, i0,
    lo_i, hi_i)``, so rows may carry DIFFERENT messages (mixed-message
    batches are a midstate/hoist-plan table lookup, the AsicBoost
    observation) — then reduces rows to per-segment lexicographic mins
    instead of one global argmin. ``seg`` maps each row to its
    (request, block) segment; the caller merges segments of the same
    request across blocks/launches on the host (strict-less, ascending
    base — the existing ``finalize`` rule).

    Static geometry: all rows share ``(rem, k, batch, nbatches)`` — the
    batch planner groups rows by exactly that key — and the row count R
    is pow2-bucketed by the caller (:func:`pow2_bucket`), so the jit
    signature set stays bounded. Padded rows carry an empty valid
    window (``lo_i > hi_i``): every lane masks to the sentinel, which
    can never win a segment min, so padding is bit-neutral.

    Returns ``(seg_hi, seg_lo, seg_idx)``, each of shape (R,); slots
    beyond the caller's live segment count hold sentinels.
    """
    midstates = jnp.asarray(midstates, dtype=jnp.uint32)
    templates = jnp.asarray(templates, dtype=jnp.uint32)

    def row(midstate, template, i0, lo_i, hi_i, hoist):
        return span_scan_body(midstate, template, i0, lo_i, hi_i,
                              rem=rem, k=k, batch=batch,
                              nbatches=nbatches, hoist=hoist)

    if hoists is None:
        hi_h, lo_h, idx = jax.vmap(
            lambda m, t, i, lo, hi: row(m, t, i, lo, hi, None))(
            midstates, templates, i0s, lo_is, hi_is)
    else:
        hi_h, lo_h, idx = jax.vmap(row)(
            midstates, templates, i0s, lo_is, hi_is, hoists)
    return segmin_rows(hi_h, lo_h, idx, seg, midstates.shape[0])
