"""Compute kernels: SHA-256 arg-min search, TPU-first.

Three tiers, all bit-identical to the host oracle
(``distributed_bitcoinminer_tpu.bitcoin.hash_op``):

- ``sha256_host``: pure-Python compression, used for midstates and tiny edges;
- ``sha256_jnp``: jitted, lane-vectorized jnp implementation;
- ``sha256_pallas``: Pallas TPU kernel with blockwise grid + fused argmin.
"""

from .sha256_host import (sha256_midstate, compress_host, compress_rounds,
                          schedule_words, SHA256_H0, SHA256_K)
from .sha256_jnp import (
    HoistPlan, build_hoist, build_tail_template, chunk_search_fn,
    hoist_structure, lex_argmin, digit_positions,
)

__all__ = [
    "sha256_midstate", "compress_host", "compress_rounds", "schedule_words",
    "SHA256_H0", "SHA256_K",
    "HoistPlan", "build_hoist", "build_tail_template", "chunk_search_fn",
    "hoist_structure", "lex_argmin", "digit_positions",
]
