"""Native (C++) host runtime pieces, loaded via ctypes.

``scan_min_native`` is the fast CPU arg-min scan (see ``sha256_scan.cpp``);
the library auto-builds with g++ on first use and everything degrades to the
pure-Python oracle when no toolchain is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

logger = logging.getLogger("dbm.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "sha256_scan.cpp")


def _lib_path() -> str:
    """ISA-tagged artifact name: ``-march=native`` code SIGILLs when a
    cached ``.so`` travels to a host with fewer ISA extensions (ADVICE
    r1/r2: the mtime-only cache key was a cross-host trap — same failure
    family as the poisoned JAX persistent cache)."""
    from ..utils.config import host_fingerprint
    return os.path.join(_DIR, f"libdbm_native-{host_fingerprint()}.so")


_LIB = _lib_path()

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # -mno-avx512f: -march=native on this image's VM advertises AVX-512,
    # but every EVEX-encoded instruction the auto-vectorizer then emits
    # traps to the hypervisor (~µs each) — measured 0.13 M nonces/s vs
    # 16 M with the flag (round 4). The SHA-NI intrinsics are SSE-encoded
    # and unaffected. Retried without the flag for toolchains that
    # reject it.
    base = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", _LIB]
    cmd = base[:2] + ["-mno-avx512f"] + base[2:]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode == 0:
            return True
        # Retry flagless ONLY for a toolchain that rejects the flag —
        # a real compile failure would just fail identically twice and
        # bury its own diagnostic (code-review r4).
        if b"mno-avx512f" in proc.stderr:
            proc = subprocess.run(base, capture_output=True, timeout=120)
            if proc.returncode == 0:
                return True
        logger.info("native build failed (%s); falling back to Python",
                    proc.stderr.decode(errors="replace")[-300:])
    except (OSError, subprocess.SubprocessError) as exc:
        logger.info("native build failed (%s); falling back to Python", exc)
    return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
            if not (hasattr(lib, "dbm_scan_min_mt")
                    and hasattr(lib, "dbm_scan_until_mt")):
                # Stale cached .so from before the MT scan existed (mtime
                # can lie after a checkout restore): rebuild once. dlclose
                # first — dlopen caches by path, so reloading without it
                # would hand back the stale handle. If the rebuild fails
                # (toolchain gone), reload the stale lib and serve the
                # single-threaded scan from it rather than dropping to the
                # Python oracle (code-review r3): scan_min_native routes
                # threads->1 when the MT symbol is absent.
                import _ctypes
                _ctypes.dlclose(lib._handle)
                _build()
                lib = ctypes.CDLL(_LIB)
        except OSError as exc:
            logger.info("native load failed (%s)", exc)
            _build_failed = True
            return None
        lib.dbm_scan_min.restype = ctypes.c_int
        lib.dbm_scan_min.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.dbm_hash.restype = ctypes.c_uint64
        lib.dbm_hash.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint64]
        if hasattr(lib, "dbm_scan_min_mt"):
            lib.dbm_scan_min_mt.restype = ctypes.c_int
            lib.dbm_scan_min_mt.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64)]
        if hasattr(lib, "dbm_scan_until"):
            lib.dbm_scan_until.restype = ctypes.c_int
            lib.dbm_scan_until.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_int)]
        if hasattr(lib, "dbm_scan_until_mt"):
            lib.dbm_scan_until_mt.restype = ctypes.c_int
            lib.dbm_scan_until_mt.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_int)]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


#: Ranges at least this long fan out over all cores (a 2^17 scan takes
#: ~10 ms single-threaded; spawn cost is noise well below that).
_MT_THRESHOLD = 1 << 17


def scan_min_native(data: str, lower: int, upper: int,
                    threads: int = 0) -> Tuple[int, int]:
    """Native arg-min scan over [lower, upper]; falls back to the Python
    oracle when the toolchain is missing.

    ``threads``: 0 = auto (all cores for ranges >= 2^17, else one);
    1 forces single-threaded; N pins the worker count. The tie rule is
    identical either way (contiguous ascending sub-ranges, first-seen
    wins). Arg-min is the target-0 special case of the until dispatch
    (target 0 never hits), keeping one copy of the threshold/threads/rc
    scaffolding — the same dereplication as ``bitcoin.hash.scan_min``
    and ``dbm_scan_min`` at their layers.
    """
    hash_value, nonce, _found = scan_until_native(data, lower, upper, 0,
                                                  threads=threads)
    return hash_value, nonce


def scan_until_native(data: str, lower: int, upper: int, target: int,
                      threads: int = 0) -> Tuple[int, int, bool]:
    """Native difficulty scan over [lower, upper]: first nonce with
    ``hash < target`` (found=True), else exact arg-min (found=False).

    ``threads`` as in :func:`scan_min_native`; the MT fan-out keeps
    first-qualifying semantics (ascending shards, lowest hitting shard
    wins, higher shards cooperatively aborted). Falls back to the Python
    oracle without a toolchain or with a stale pre-until ``.so`` kept
    alive by a vanished toolchain."""
    if lower > upper:
        raise ValueError("empty range")  # uniform across native/fallback
    lib = load()
    raw = data.encode("utf-8")
    out_hash = ctypes.c_uint64()
    out_nonce = ctypes.c_uint64()
    out_found = ctypes.c_int()
    if lib is None or not hasattr(lib, "dbm_scan_until"):
        if lib is not None and target == 0:
            # Stale pre-until .so kept alive by a vanished toolchain:
            # honor load()'s promise that arg-min scans still run native
            # (single-threaded) rather than dropping to the Python oracle
            # — scan_min_native routes through here with target 0
            # (code-review r4).
            rc = lib.dbm_scan_min(raw, len(raw), lower, upper,
                                  ctypes.byref(out_hash),
                                  ctypes.byref(out_nonce))
            if rc != 0:
                raise ValueError("empty range")
            return out_hash.value, out_nonce.value, False
        from ..bitcoin.hash import scan_until
        return scan_until(data, lower, upper, target)
    if threads == 0 and upper - lower + 1 < _MT_THRESHOLD:
        threads = 1
    if not hasattr(lib, "dbm_scan_until_mt"):
        threads = 1
    if threads == 1:
        rc = lib.dbm_scan_until(raw, len(raw), lower, upper, target,
                                ctypes.byref(out_hash),
                                ctypes.byref(out_nonce),
                                ctypes.byref(out_found))
    else:
        rc = lib.dbm_scan_until_mt(raw, len(raw), lower, upper, target,
                                   threads, ctypes.byref(out_hash),
                                   ctypes.byref(out_nonce),
                                   ctypes.byref(out_found))
    if rc != 0:
        raise ValueError("empty range")
    return out_hash.value, out_nonce.value, bool(out_found.value)


def hash_native(data: str, nonce: int) -> int:
    lib = load()
    if lib is None:
        from ..bitcoin.hash import hash_op
        return hash_op(data, nonce)
    raw = data.encode("utf-8")
    return lib.dbm_hash(raw, len(raw), nonce)
