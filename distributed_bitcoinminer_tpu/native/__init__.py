"""Native (C++) host runtime pieces, loaded via ctypes.

``scan_min_native`` is the fast CPU arg-min scan (see ``sha256_scan.cpp``);
the library auto-builds with g++ on first use and everything degrades to the
pure-Python oracle when no toolchain is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

logger = logging.getLogger("dbm.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "sha256_scan.cpp")


def _lib_path() -> str:
    """ISA-tagged artifact name: ``-march=native`` code SIGILLs when a
    cached ``.so`` travels to a host with fewer ISA extensions (ADVICE
    r1/r2: the mtime-only cache key was a cross-host trap — same failure
    family as the poisoned JAX persistent cache)."""
    from ..utils.config import host_fingerprint
    return os.path.join(_DIR, f"libdbm_native-{host_fingerprint()}.so")


_LIB = _lib_path()

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as exc:
        logger.info("native build failed (%s); falling back to Python", exc)
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as exc:
            logger.info("native load failed (%s)", exc)
            _build_failed = True
            return None
        lib.dbm_scan_min.restype = ctypes.c_int
        lib.dbm_scan_min.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.dbm_hash.restype = ctypes.c_uint64
        lib.dbm_hash.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint64]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def scan_min_native(data: str, lower: int, upper: int) -> Tuple[int, int]:
    """Native arg-min scan over [lower, upper]; falls back to the Python
    oracle when the toolchain is missing."""
    lib = load()
    if lib is None:
        from ..bitcoin.hash import scan_min
        return scan_min(data, lower, upper)
    raw = data.encode("utf-8")
    out_hash = ctypes.c_uint64()
    out_nonce = ctypes.c_uint64()
    rc = lib.dbm_scan_min(raw, len(raw), lower, upper,
                          ctypes.byref(out_hash), ctypes.byref(out_nonce))
    if rc != 0:
        raise ValueError("empty range")
    return out_hash.value, out_nonce.value


def hash_native(data: str, nonce: int) -> int:
    lib = load()
    if lib is None:
        from ..bitcoin.hash import hash_op
        return hash_op(data, nonce)
    raw = data.encode("utf-8")
    return lib.dbm_hash(raw, len(raw), nonce)
