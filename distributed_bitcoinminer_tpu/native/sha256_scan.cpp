// Native host-side SHA-256 arg-min scan.
//
// The CPU analog of the reference miner's hot loop (ref:
// bitcoin/miner/miner.go:52-59 calling bitcoin/hash.go:13-17, which leans on
// Go's assembly-accelerated crypto/sha256): hash "<data> <nonce>" for every
// nonce in [lower, upper], tracking the minimum of the big-endian uint64
// prefix with strict '<' (earliest nonce wins ties).
//
// Used by the framework as (a) the fast host-fallback miner compute for
// boxes without accelerators, (b) a golden-oracle generator for large-range
// conformance tests, and (c) the measured CPU baseline in bench.py.
//
// The prefix midstate ("<data> " absorbed once) plus an incremental decimal
// counter in the tail block avoid re-hashing the prefix and re-formatting
// the nonce per iteration.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__SHA__) && defined(__SSE4_1__)
#include <immintrin.h>
#endif

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

constexpr uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

void compress_portable(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int t = 0; t < 16; ++t) {
    w[t] = (uint32_t(block[t * 4]) << 24) | (uint32_t(block[t * 4 + 1]) << 16) |
           (uint32_t(block[t * 4 + 2]) << 8) | uint32_t(block[t * 4 + 3]);
  }
  for (int t = 16; t < 64; ++t) {
    uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (w[t - 15] >> 3);
    uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (w[t - 2] >> 10);
    w[t] = w[t - 16] + s0 + w[t - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int t = 0; t < 64; ++t) {
    uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + s1 + ch + K[t] + w[t];
    uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    h = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

#if defined(__SHA__) && defined(__SSE4_1__)
// x86 SHA-NI one-block compression (the standard Intel intrinsic sequence);
// ~10x the portable loop. Selected at build time by -march=native.
void compress_ni(uint32_t state[8], const uint8_t block[64]) {
  const __m128i SHUF = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                      0x0405060700010203ULL);
  __m128i TMP = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[0]));
  __m128i S1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[4]));
  TMP = _mm_shuffle_epi32(TMP, 0xB1);        // CDAB
  S1 = _mm_shuffle_epi32(S1, 0x1B);          // EFGH
  __m128i S0 = _mm_alignr_epi8(TMP, S1, 8);  // ABEF
  S1 = _mm_blend_epi16(S1, TMP, 0xF0);       // CDGH
  const __m128i ABEF_SAVE = S0, CDGH_SAVE = S1;

  __m128i M0 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 0)), SHUF);
  __m128i M1 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 16)), SHUF);
  __m128i M2 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 32)), SHUF);
  __m128i M3 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 48)), SHUF);
  __m128i MSG;

#define QROUND(Mc, Mp, Mn, g, do_msg2, do_msg1)                          \
  MSG = _mm_add_epi32(                                                   \
      Mc, _mm_set_epi64x(                                                \
              (uint64_t(K[4 * (g) + 3]) << 32) | K[4 * (g) + 2],         \
              (uint64_t(K[4 * (g) + 1]) << 32) | K[4 * (g)]));           \
  S1 = _mm_sha256rnds2_epu32(S1, S0, MSG);                               \
  MSG = _mm_shuffle_epi32(MSG, 0x0E);                                    \
  S0 = _mm_sha256rnds2_epu32(S0, S1, MSG);                               \
  if (do_msg2) {                                                         \
    Mn = _mm_add_epi32(Mn, _mm_alignr_epi8(Mc, Mp, 4));                  \
    Mn = _mm_sha256msg2_epu32(Mn, Mc);                                   \
  }                                                                      \
  if (do_msg1) Mp = _mm_sha256msg1_epu32(Mp, Mc);

  // msg2 (with the alignr add) produces W[16..63] at groups 3-14; msg1
  // pre-mixes the operand msg2 consumes two groups later, so it runs at
  // groups 1-12. The alignr must read Mp before msg1 rewrites it.
  QROUND(M0, M3, M1, 0, 0, 0)
  QROUND(M1, M0, M2, 1, 0, 1)
  QROUND(M2, M1, M3, 2, 0, 1)
  QROUND(M3, M2, M0, 3, 1, 1)
  QROUND(M0, M3, M1, 4, 1, 1)
  QROUND(M1, M0, M2, 5, 1, 1)
  QROUND(M2, M1, M3, 6, 1, 1)
  QROUND(M3, M2, M0, 7, 1, 1)
  QROUND(M0, M3, M1, 8, 1, 1)
  QROUND(M1, M0, M2, 9, 1, 1)
  QROUND(M2, M1, M3, 10, 1, 1)
  QROUND(M3, M2, M0, 11, 1, 1)
  QROUND(M0, M3, M1, 12, 1, 1)
  QROUND(M1, M0, M2, 13, 1, 0)
  QROUND(M2, M1, M3, 14, 1, 0)
  QROUND(M3, M2, M0, 15, 0, 0)
#undef QROUND

  S0 = _mm_add_epi32(S0, ABEF_SAVE);
  S1 = _mm_add_epi32(S1, CDGH_SAVE);
  TMP = _mm_shuffle_epi32(S0, 0x1B);         // FEBA
  S1 = _mm_shuffle_epi32(S1, 0xB1);          // DCHG
  S0 = _mm_blend_epi16(TMP, S1, 0xF0);       // DCBA
  S1 = _mm_alignr_epi8(S1, TMP, 8);          // HGFE
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[0]), S0);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[4]), S1);
}

// Two-message interleaved compression: the sha256rnds2 dependency chain
// (latency ~4 cycles, throughput ~1/cycle) leaves the unit mostly idle on
// a single chain; alternating rounds of two INDEPENDENT messages nearly
// doubles throughput. Register budget: ~8 xmm per chain = the full
// 16-register file, which is why this stops at 2-way. Wider interleaves
// were measured and rejected (round 4): 3-way/4-way prototypes benched
// 24.6-25.8 / 25.8-28.1 M hash/s vs 23.4-24.7 for 2-way on this box —
// <= 10%, within run noise, because past two chains the spilled message
// tiles give back most of the latency hiding; not worth the triple/quad
// scan-loop boundary handling.
void compress2_ni(uint32_t state_a[8], const uint8_t block_a[64],
                  uint32_t state_b[8], const uint8_t block_b[64]) {
  const __m128i SHUF = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                      0x0405060700010203ULL);
#define LOAD_STATE(st, S0, S1, SAVE0, SAVE1)                              \
  __m128i TMP##S0 = _mm_loadu_si128(                                      \
      reinterpret_cast<const __m128i*>(&(st)[0]));                        \
  __m128i S1 = _mm_loadu_si128(                                           \
      reinterpret_cast<const __m128i*>(&(st)[4]));                        \
  TMP##S0 = _mm_shuffle_epi32(TMP##S0, 0xB1);                             \
  S1 = _mm_shuffle_epi32(S1, 0x1B);                                       \
  __m128i S0 = _mm_alignr_epi8(TMP##S0, S1, 8);                           \
  S1 = _mm_blend_epi16(S1, TMP##S0, 0xF0);                                \
  const __m128i SAVE0 = S0, SAVE1 = S1;
  LOAD_STATE(state_a, A0, A1, ASAVE0, ASAVE1)
  LOAD_STATE(state_b, B0, B1, BSAVE0, BSAVE1)
#undef LOAD_STATE

#define LOAD_MSG(block, M0, M1, M2, M3)                                   \
  __m128i M0 = _mm_shuffle_epi8(_mm_loadu_si128(                          \
      reinterpret_cast<const __m128i*>((block) + 0)), SHUF);              \
  __m128i M1 = _mm_shuffle_epi8(_mm_loadu_si128(                          \
      reinterpret_cast<const __m128i*>((block) + 16)), SHUF);             \
  __m128i M2 = _mm_shuffle_epi8(_mm_loadu_si128(                          \
      reinterpret_cast<const __m128i*>((block) + 32)), SHUF);             \
  __m128i M3 = _mm_shuffle_epi8(_mm_loadu_si128(                          \
      reinterpret_cast<const __m128i*>((block) + 48)), SHUF);
  LOAD_MSG(block_a, MA0, MA1, MA2, MA3)
  LOAD_MSG(block_b, MB0, MB1, MB2, MB3)
#undef LOAD_MSG
  __m128i MSG;

  // Same group schedule as compress_ni's QROUND, issued for chain A then
  // chain B each group so the two rnds2 chains overlap in the pipeline.
#define QROUND2(S0, S1, Mc, Mp, Mn, g, do_msg2, do_msg1)                  \
  MSG = _mm_add_epi32(                                                    \
      Mc, _mm_set_epi64x(                                                 \
              (uint64_t(K[4 * (g) + 3]) << 32) | K[4 * (g) + 2],          \
              (uint64_t(K[4 * (g) + 1]) << 32) | K[4 * (g)]));            \
  S1 = _mm_sha256rnds2_epu32(S1, S0, MSG);                                \
  MSG = _mm_shuffle_epi32(MSG, 0x0E);                                     \
  S0 = _mm_sha256rnds2_epu32(S0, S1, MSG);                                \
  if (do_msg2) {                                                          \
    Mn = _mm_add_epi32(Mn, _mm_alignr_epi8(Mc, Mp, 4));                   \
    Mn = _mm_sha256msg2_epu32(Mn, Mc);                                    \
  }                                                                       \
  if (do_msg1) Mp = _mm_sha256msg1_epu32(Mp, Mc);

#define GROUP2(ca, pa, na, cb, pb, nb, g, do2, do1)                       \
  QROUND2(A0, A1, ca, pa, na, g, do2, do1)                                \
  QROUND2(B0, B1, cb, pb, nb, g, do2, do1)

  GROUP2(MA0, MA3, MA1, MB0, MB3, MB1, 0, 0, 0)
  GROUP2(MA1, MA0, MA2, MB1, MB0, MB2, 1, 0, 1)
  GROUP2(MA2, MA1, MA3, MB2, MB1, MB3, 2, 0, 1)
  GROUP2(MA3, MA2, MA0, MB3, MB2, MB0, 3, 1, 1)
  GROUP2(MA0, MA3, MA1, MB0, MB3, MB1, 4, 1, 1)
  GROUP2(MA1, MA0, MA2, MB1, MB0, MB2, 5, 1, 1)
  GROUP2(MA2, MA1, MA3, MB2, MB1, MB3, 6, 1, 1)
  GROUP2(MA3, MA2, MA0, MB3, MB2, MB0, 7, 1, 1)
  GROUP2(MA0, MA3, MA1, MB0, MB3, MB1, 8, 1, 1)
  GROUP2(MA1, MA0, MA2, MB1, MB0, MB2, 9, 1, 1)
  GROUP2(MA2, MA1, MA3, MB2, MB1, MB3, 10, 1, 1)
  GROUP2(MA3, MA2, MA0, MB3, MB2, MB0, 11, 1, 1)
  GROUP2(MA0, MA3, MA1, MB0, MB3, MB1, 12, 1, 1)
  GROUP2(MA1, MA0, MA2, MB1, MB0, MB2, 13, 1, 0)
  GROUP2(MA2, MA1, MA3, MB2, MB1, MB3, 14, 1, 0)
  GROUP2(MA3, MA2, MA0, MB3, MB2, MB0, 15, 0, 0)
#undef GROUP2
#undef QROUND2

#define STORE_STATE(st, S0, S1, SAVE0, SAVE1)                             \
  S0 = _mm_add_epi32(S0, SAVE0);                                          \
  S1 = _mm_add_epi32(S1, SAVE1);                                          \
  {                                                                       \
    __m128i T = _mm_shuffle_epi32(S0, 0x1B);                              \
    S1 = _mm_shuffle_epi32(S1, 0xB1);                                     \
    S0 = _mm_blend_epi16(T, S1, 0xF0);                                    \
    S1 = _mm_alignr_epi8(S1, T, 8);                                       \
  }                                                                       \
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&(st)[0]), S0);             \
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&(st)[4]), S1);
  STORE_STATE(state_a, A0, A1, ASAVE0, ASAVE1)
  STORE_STATE(state_b, B0, B1, BSAVE0, BSAVE1)
#undef STORE_STATE
}

inline void compress(uint32_t state[8], const uint8_t block[64]) {
  compress_ni(state, block);
}
inline void compress2(uint32_t sa[8], const uint8_t ba[64],
                      uint32_t sb[8], const uint8_t bb[64]) {
  compress2_ni(sa, ba, sb, bb);
}
#else
inline void compress(uint32_t state[8], const uint8_t block[64]) {
  compress_portable(state, block);
}
inline void compress2(uint32_t sa[8], const uint8_t ba[64],
                      uint32_t sb[8], const uint8_t bb[64]) {
  compress_portable(sa, ba);
  compress_portable(sb, bb);
}
#endif

// Build the padded tail block(s) (1 or 2 x 64 bytes); returns nblocks.
int pad_tail(uint8_t buf[128], const uint8_t* tail, int tail_len,
             uint64_t total_len) {
  std::memcpy(buf, tail, tail_len);
  buf[tail_len] = 0x80;
  int nblocks = (tail_len + 1 + 8 <= 64) ? 1 : 2;
  int padded = nblocks * 64;
  std::memset(buf + tail_len + 1, 0, padded - tail_len - 1 - 8);
  uint64_t bits = total_len * 8;
  for (int j = 0; j < 8; ++j)
    buf[padded - 1 - j] = uint8_t(bits >> (8 * j));
  return nblocks;
}

// Hash prefix-midstate + tail (tail_len < 64 + up to 20 digit bytes), return
// big-endian uint64 of digest[0:8]. total_len in bytes.
uint64_t finish(const uint32_t mid[8], const uint8_t* tail, int tail_len,
                uint64_t total_len) {
  uint32_t st[8];
  std::memcpy(st, mid, sizeof(st));
  uint8_t buf[128];
  int nblocks = pad_tail(buf, tail, tail_len, total_len);
  compress(st, buf);
  if (nblocks == 2) compress(st, buf + 64);
  return (uint64_t(st[0]) << 32) | uint64_t(st[1]);
}

// Two tails from the SAME midstate, hashed as interleaved chains (the
// scan's hot pair path). Tail lengths may differ (digit rollover inside a
// pair); unequal BLOCK counts (one message crossing the 64-byte pad
// boundary the other doesn't) fall back to two scalar finishes.
void finish2(const uint32_t mid[8],
             const uint8_t* tail_a, int len_a, uint64_t total_a,
             const uint8_t* tail_b, int len_b, uint64_t total_b,
             uint64_t* out_a, uint64_t* out_b) {
  uint8_t buf_a[128], buf_b[128];
  int na = pad_tail(buf_a, tail_a, len_a, total_a);
  int nb = pad_tail(buf_b, tail_b, len_b, total_b);
  if (na != nb) {
    *out_a = finish(mid, tail_a, len_a, total_a);
    *out_b = finish(mid, tail_b, len_b, total_b);
    return;
  }
  uint32_t sa[8], sb[8];
  std::memcpy(sa, mid, sizeof(sa));
  std::memcpy(sb, mid, sizeof(sb));
  for (int j = 0; j < na; ++j)
    compress2(sa, buf_a + 64 * j, sb, buf_b + 64 * j);
  *out_a = (uint64_t(sa[0]) << 32) | uint64_t(sa[1]);
  *out_b = (uint64_t(sb[0]) << 32) | uint64_t(sb[1]);
}

// The one scan loop behind every extern entry point. Ascending over
// [lower, upper]; stops at the FIRST nonce whose hash < target
// (*out_found = 1); otherwise tracks the exact arg-min (*out_found = 0)
// with strict-'<' earliest-nonce ties. target = 0 can never hit (no
// uint64 is < 0), so the arg-min scan is the target-0 special case.
//
// Cooperative MT abort: when min_found_shard is non-null the loop checks
// it every 4096 nonces and bails (returns 1, outputs = partial arg-min)
// once a LOWER-indexed shard has a hit — anything this shard could still
// find is beaten by that hit. Lower shards are never stopped by higher
// ones (the global first-qualifying nonce may sit late in an early
// shard). Returns 0 = completed, 1 = aborted, -1 = empty range.
int scan_until_core(const char* data, uint64_t data_len, uint64_t lower,
                    uint64_t upper, uint64_t target,
                    const std::atomic<uint64_t>* min_found_shard,
                    uint64_t my_shard, uint64_t* out_hash,
                    uint64_t* out_nonce, int* out_found) {
  if (lower > upper) return -1;

  // Absorb all complete 64-byte blocks of "<data> " once.
  uint32_t mid[8];
  std::memcpy(mid, H0, sizeof(mid));
  uint64_t prefix_len = data_len + 1;
  uint8_t block[64];
  uint64_t full = prefix_len - (prefix_len % 64);
  for (uint64_t off = 0; off < full; off += 64) {
    for (int j = 0; j < 64; ++j)
      block[j] = uint8_t(off + j < data_len ? data[off + j] : ' ');
    compress(mid, block);
  }
  int rem = int(prefix_len - full);
  uint8_t tail[64 + 24];
  for (int j = 0; j < rem; ++j)
    tail[j] = uint8_t(full + j < data_len ? data[full + j] : ' ');
  uint8_t tail2[64 + 24];
  std::memcpy(tail2, tail, rem);

  // Incremental ASCII decimal counter for the nonce digits.
  uint8_t digits[24];
  int nd = 0;
  uint64_t v = lower;
  do {
    digits[nd++] = uint8_t('0' + v % 10);
    v /= 10;
  } while (v);
  for (int i = 0; i < nd / 2; ++i) {
    uint8_t t = digits[i]; digits[i] = digits[nd - 1 - i]; digits[nd - 1 - i] = t;
  }
  // ++counter with decimal carry.
  auto incr = [&digits, &nd]() {
    int i = nd - 1;
    while (i >= 0 && digits[i] == '9') digits[i--] = '0';
    if (i < 0) {
      std::memmove(digits + 1, digits, nd);
      digits[0] = '1';
      ++nd;
    } else {
      ++digits[i];
    }
  };

  // Nonce PAIRS through the interleaved two-chain compression (finish2):
  // one sha256rnds2 chain leaves the SHA unit mostly idle on its ~4-cycle
  // latency, so two independent chains nearly double throughput. The
  // target check stays in ascending order — a hit on the first of a pair
  // returns before the second is examined — so first-qualifying and
  // earliest-tie semantics are byte-identical to the scalar loop.
  uint64_t best_hash = ~uint64_t(0);
  uint64_t best_nonce = lower;
  uint64_t n = lower, iter = 0;
  while (true) {
    if (min_found_shard && (iter++ & 2047) == 0 &&
        min_found_shard->load(std::memory_order_relaxed) < my_shard) {
      *out_hash = best_hash;
      *out_nonce = best_nonce;
      *out_found = 0;
      return 1;
    }
    std::memcpy(tail + rem, digits, nd);
    int len_a = rem + nd;
    uint64_t tot_a = prefix_len + nd;
    if (n == upper) {  // odd tail of the range: one scalar hash
      uint64_t h = finish(mid, tail, len_a, tot_a);
      if (h < target) {
        *out_hash = h;
        *out_nonce = n;
        *out_found = 1;
        return 0;
      }
      if (h < best_hash) {
        best_hash = h;
        best_nonce = n;
      }
      break;
    }
    incr();
    std::memcpy(tail2 + rem, digits, nd);
    uint64_t ha, hb;
    finish2(mid, tail, len_a, tot_a,
            tail2, rem + nd, prefix_len + nd, &ha, &hb);
    if (ha < target) {
      *out_hash = ha;
      *out_nonce = n;
      *out_found = 1;
      return 0;
    }
    if (ha < best_hash) {
      best_hash = ha;
      best_nonce = n;
    }
    if (hb < target) {
      *out_hash = hb;
      *out_nonce = n + 1;
      *out_found = 1;
      return 0;
    }
    if (hb < best_hash) {
      best_hash = hb;
      best_nonce = n + 1;
    }
    if (n + 1 == upper) break;
    incr();
    n += 2;
  }
  *out_hash = best_hash;
  *out_nonce = best_nonce;
  *out_found = 0;
  return 0;
}

}  // namespace

extern "C" {

// Difficulty scan (BASELINE config 5), single-threaded. Returns 0, or -1
// for an empty range (outputs untouched).
int dbm_scan_until(const char* data, uint64_t data_len, uint64_t lower,
                   uint64_t upper, uint64_t target, uint64_t* out_hash,
                   uint64_t* out_nonce, int* out_found) {
  return scan_until_core(data, data_len, lower, upper, target, nullptr, 0,
                         out_hash, out_nonce, out_found);
}

// Scan [lower, upper] inclusive; writes (min_hash, argmin_nonce). Returns 0,
// or -1 for an empty range (outputs untouched).
int dbm_scan_min(const char* data, uint64_t data_len, uint64_t lower,
                 uint64_t upper, uint64_t* out_hash, uint64_t* out_nonce) {
  int found;
  return scan_until_core(data, data_len, lower, upper, 0, nullptr, 0,
                         out_hash, out_nonce, &found);
}

// Multi-threaded difficulty scan: contiguous ascending shards, one per
// thread; each stops at its own first hit and publishes its shard index,
// which cooperatively aborts all HIGHER shards (scan_until_core). The
// lowest hitting shard's first hit is the globally first qualifying nonce
// (lower shards always run to completion or their own earlier hit); with
// no hit anywhere, shards merge to the exact arg-min in index order, same
// tie rule as dbm_scan_min_mt. nthreads <= 0 means hardware_concurrency.
int dbm_scan_until_mt(const char* data, uint64_t data_len, uint64_t lower,
                      uint64_t upper, uint64_t target, int nthreads,
                      uint64_t* out_hash, uint64_t* out_nonce,
                      int* out_found) {
  if (lower > upper) return -1;
  uint64_t total = upper - lower + 1;
  unsigned hw = std::thread::hardware_concurrency();
  uint64_t want = nthreads > 0 ? uint64_t(nthreads) : (hw ? hw : 1);
  if (want > total) want = total;
  if (want <= 1)
    return dbm_scan_until(data, data_len, lower, upper, target, out_hash,
                          out_nonce, out_found);

  std::vector<uint64_t> los(want), his(want);
  uint64_t per = total / want, extra = total % want, start = lower;
  for (uint64_t t = 0; t < want; ++t) {
    uint64_t len = per + (t < extra ? 1 : 0);
    los[t] = start;
    his[t] = start + len - 1;
    start += len;
  }
  std::atomic<uint64_t> min_found{~uint64_t(0)};
  std::vector<uint64_t> hashes(want), nonces(want);
  auto run_shard = [&](uint64_t t, uint64_t lo, uint64_t hi) {
    int f = 0;
    scan_until_core(data, data_len, lo, hi, target, &min_found, t,
                    &hashes[t], &nonces[t], &f);
    if (f) {
      uint64_t cur = min_found.load(std::memory_order_relaxed);
      while (t < cur &&
             !min_found.compare_exchange_weak(cur, t,
                                              std::memory_order_relaxed)) {
      }
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(want);
  uint64_t spawned = 0;
  try {
    for (uint64_t t = 0; t < want; ++t) {
      threads.emplace_back(run_shard, t, los[t], his[t]);
      ++spawned;
    }
  } catch (...) {
    // Thread spawn failed (e.g. EAGAIN under a pid limit): join what
    // started, then cover the tail on this thread as shard `spawned`
    // (same recovery as dbm_scan_min_mt; shard order stays ascending).
  }
  for (auto& th : threads) th.join();
  uint64_t covered = spawned;
  if (covered < want) {
    run_shard(covered, los[covered], upper);
    ++covered;
  }
  uint64_t win = min_found.load(std::memory_order_relaxed);
  if (win != ~uint64_t(0) && win < covered) {
    *out_hash = hashes[win];
    *out_nonce = nonces[win];
    *out_found = 1;
    return 0;
  }
  uint64_t best_hash = hashes[0], best_nonce = nonces[0];
  for (uint64_t t = 1; t < covered; ++t) {
    if (hashes[t] < best_hash) {
      best_hash = hashes[t];
      best_nonce = nonces[t];
    }
  }
  *out_hash = best_hash;
  *out_nonce = best_nonce;
  *out_found = 0;
  return 0;
}

// Single hash op (ref: bitcoin/hash.go:13-17), for spot conformance checks.
uint64_t dbm_hash(const char* data, uint64_t data_len, uint64_t nonce) {
  uint64_t h, n;
  if (dbm_scan_min(data, data_len, nonce, nonce, &h, &n) != 0) return 0;
  return h;
}

// Multi-threaded scan: contiguous sub-ranges, one per thread, merged with
// the same strict-'<' / earliest-nonce tie rule (sub-ranges ascend with the
// thread index, so merging in index order preserves first-seen-wins).
// nthreads <= 0 means hardware_concurrency.
int dbm_scan_min_mt(const char* data, uint64_t data_len, uint64_t lower,
                    uint64_t upper, int nthreads, uint64_t* out_hash,
                    uint64_t* out_nonce) {
  if (lower > upper) return -1;
  uint64_t total = upper - lower + 1;
  unsigned hw = std::thread::hardware_concurrency();
  uint64_t want = nthreads > 0 ? uint64_t(nthreads) : (hw ? hw : 1);
  if (want > total) want = total;
  if (want <= 1) return dbm_scan_min(data, data_len, lower, upper,
                                     out_hash, out_nonce);

  std::vector<uint64_t> los(want), his(want);
  uint64_t per = total / want, extra = total % want, start = lower;
  for (uint64_t t = 0; t < want; ++t) {
    uint64_t len = per + (t < extra ? 1 : 0);
    los[t] = start;
    his[t] = start + len - 1;
    start += len;
  }
  std::vector<uint64_t> hashes(want), nonces(want);
  std::vector<std::thread> threads;
  threads.reserve(want);
  uint64_t spawned = 0;
  try {
    for (uint64_t t = 0; t < want; ++t) {
      uint64_t lo = los[t], hi = his[t];
      threads.emplace_back([=, &hashes, &nonces] {
        dbm_scan_min(data, data_len, lo, hi, &hashes[t], &nonces[t]);
      });
      ++spawned;
    }
  } catch (...) {
    // Thread spawn failed (e.g. EAGAIN under a pid limit). Letting the
    // vector destroy joinable threads would std::terminate the whole
    // process; instead join what started and scan the uncovered tail on
    // this thread (sub-ranges stay ascending, so the merge rule holds).
  }
  for (auto& th : threads) th.join();
  uint64_t covered = spawned;
  if (covered < want) {
    dbm_scan_min(data, data_len, los[covered], upper,
                 &hashes[covered], &nonces[covered]);
    ++covered;
  }
  uint64_t best_hash = hashes[0], best_nonce = nonces[0];
  for (uint64_t t = 1; t < covered; ++t) {
    if (hashes[t] < best_hash) {
      best_hash = hashes[t];
      best_nonce = nonces[t];
    }
  }
  *out_hash = best_hash;
  *out_nonce = best_nonce;
  return 0;
}

}  // extern "C"
