"""Multi-host (pod-scale) wiring: one LSP miner per pod, DCN + ICI split.

Deployment shape per the north star: a whole multi-host TPU pod joins the
scheduler as ONE miner. Every host runs the same SPMD program (standard JAX
multi-controller); host 0 additionally owns the LSP client socket. Chunk
bounds arriving over LSP are host-side Python scalars; host 0 broadcasts
them to the other hosts (one tiny ``broadcast_one_to_all`` per Request),
after which every host enters the same jitted ``shard_map`` search over the
GLOBAL mesh — intra-search communication is exactly the staged-pmin merge
over ICI from ``mesh_search``, now spanning all hosts.

The reference's analog is its LSP/UDP stack (SURVEY §2, communication
backend): host<->host traffic stays on the unchanged wire protocol; the
NCCL/MPI role is played entirely by XLA collectives.

Wire-in points (VERDICT r2 task 7):

- ``apps.miner._run_miner`` calls :func:`initialize_multihost` at startup;
  non-owner hosts enter :func:`run_follower` and never touch LSP.
- The owner's searcher factory builds :class:`PodSearcher`, which
  broadcasts the job then runs the shared sharded search.
- ``tests/test_multihost.py`` drives the whole shape as 2 local CPU
  processes against a live scheduler.
"""

from __future__ import annotations

import logging
import os
from collections import OrderedDict
from typing import Optional

import jax
import numpy as np

from ..utils._env import (float_env as _float_env, int_env as _int_env,
                          str_env as _str_env)
from .mesh_search import make_mesh

logger = logging.getLogger("dbm.multihost")

#: broadcast frame layout (uint32): [opcode, data_len, lo_hi, lo_lo,
#: up_hi, up_lo, t_hi, t_lo, data_bytes...]; opcode 0 = stop, 1 = arg-min
#: search (target words ignored), 2 = difficulty search_until.
_MAX_DATA = 992
_FRAME = 8 + _MAX_DATA


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Join the JAX distributed runtime; returns True in multi-host mode.

    With no arguments, reads ``DBM_COORDINATOR`` / ``DBM_NUM_PROCS`` /
    ``DBM_PROC_ID`` and stays single-host when unset (the common case on
    one chip or one host).
    """
    coordinator_address = coordinator_address or _str_env("DBM_COORDINATOR")
    if coordinator_address is None:
        return False
    if num_processes is None:
        num_processes = _int_env("DBM_NUM_PROCS", 1)
    if process_id is None:
        process_id = _int_env("DBM_PROC_ID", 0)
    jax.distributed.initialize(coordinator_address, num_processes, process_id)
    logger.info("multihost: process %d/%d, %d global devices",
                jax.process_index(), jax.process_count(),
                len(jax.devices()))
    return True


def global_mesh():
    """1-D mesh over every device of every host (ICI+DCN per JAX layout)."""
    return make_mesh(devices=jax.devices())


def _pod_timeout_s() -> float:
    """Upper bound on one pod job (broadcast + collective search).

    ``DBM_POD_TIMEOUT_S`` (default 600 s) — generous for any real chunk
    (a v4-8 pod clears 10^11 nonces inside it) while still converting a
    wedged collective into a bounded failure.
    """
    return _float_env("DBM_POD_TIMEOUT_S", 600.0)


def bounded_pod_call(fn, timeout_s: Optional[float] = None):
    """Run one pod job with the failure-domain bound (VERDICT r3 task 7).

    A host dying mid-job leaves every OTHER host wedged inside a
    collective (broadcast or psum) that can never complete and cannot be
    cancelled from Python. The enforceable bound is process death: run
    the job in a daemon thread, and if it outlives ``DBM_POD_TIMEOUT_S``
    hard-exit. On the owner that drops its LSP connection, so the
    scheduler declares the pod-miner lost and re-executes the chunk on
    another miner (same recovery as any dead miner,
    ref: bitcoin/server/server.go:326-376); a follower simply dies with
    the pod. A *deterministic* compute error still raises symmetrically
    on every host and is handled by the callers' except paths.
    """
    import threading
    outcome: list = []

    def target():
        try:
            outcome.append(("ok", fn()))
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            outcome.append(("err", exc))

    bound = _pod_timeout_s() if timeout_s is None else timeout_s
    worker = threading.Thread(target=target, daemon=True)
    worker.start()
    worker.join(bound)
    if not outcome:
        if timeout_s is None:
            logger.error(
                "pod job exceeded DBM_POD_TIMEOUT_S=%.0fs — a peer host "
                "likely died mid-collective; exiting so this host leaves "
                "the pool and the chunk re-executes elsewhere", bound)
        else:
            logger.error(
                "no pod broadcast within DBM_POD_IDLE_TIMEOUT_S=%.0fs — "
                "the pool is idle past the configured bound (or the owner "
                "died between jobs); exiting", bound)
        os._exit(17)
    kind, value = outcome[0]
    if kind == "err":
        raise value
    return value


def is_lsp_owner() -> bool:
    """True on the one host that speaks LSP for the whole pod (host 0)."""
    return jax.process_index() == 0


def _broadcast_frame(frame: Optional[np.ndarray]) -> np.ndarray:
    """One pod-wide control broadcast; host 0 supplies the frame."""
    from jax.experimental import multihost_utils
    if frame is None:
        frame = np.zeros(_FRAME, dtype=np.uint32)
    return np.asarray(
        multihost_utils.broadcast_one_to_all(frame), dtype=np.uint32)


def broadcast_job(data: str, lower: int, upper: int,
                  target: int = 0) -> None:
    """Host 0: announce one search job to every follower host.

    ``target`` nonzero selects the difficulty mode (opcode 2): every host
    runs the same ``search_until`` host loop, whose per-sub early-exit
    decisions are made from REPLICATED collective results, so the hosts
    stay in lockstep through the early exit.
    """
    raw = data.encode("utf-8")
    if len(raw) > _MAX_DATA:
        raise ValueError(f"message too long for pod broadcast: {len(raw)}")
    frame = np.zeros(_FRAME, dtype=np.uint32)
    frame[0] = 2 if target else 1
    frame[1] = len(raw)
    frame[2], frame[3] = lower >> 32, lower & 0xFFFFFFFF
    frame[4], frame[5] = upper >> 32, upper & 0xFFFFFFFF
    frame[6], frame[7] = target >> 32, target & 0xFFFFFFFF
    frame[8:8 + len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    _broadcast_frame(frame)


def broadcast_stop() -> None:
    """Host 0: release every follower host (pod shutdown)."""
    _broadcast_frame(np.zeros(_FRAME, dtype=np.uint32))


def _receive_job():
    """Follower: block for the next control frame; None means stop.
    Returns ``(data, lower, upper, target)`` — target 0 = arg-min job."""
    frame = _broadcast_frame(None)
    if int(frame[0]) == 0:
        return None
    n = int(frame[1])
    data = bytes(frame[8:8 + n].astype(np.uint8)).decode("utf-8")
    lower = (int(frame[2]) << 32) | int(frame[3])
    upper = (int(frame[4]) << 32) | int(frame[5])
    target = 0
    if int(frame[0]) == 2:
        target = (int(frame[6]) << 32) | int(frame[7])
    return data, lower, upper, target


def _pod_searcher_cls():
    """The pod's per-host program: the ISSUE 14 mesh plane by default
    (``DBM_MESH=1`` — carry-chained spans, one host pair per span on the
    owner), the round-3 sharded model under ``DBM_MESH=0``. ONE knob
    read shared by owner and followers: the pod is lockstep SPMD, so
    both sides must lower the identical program (deployments export the
    knob identically across hosts, like every other pod knob)."""
    from ..models import MeshNonceSearcher, ShardedNonceSearcher
    return (MeshNonceSearcher if _int_env("DBM_MESH", 1) != 0
            else ShardedNonceSearcher)


class PodSearcher:
    """Owner-side searcher: broadcast the job, then run the global-mesh
    sharded search that every host executes in lockstep."""

    def __init__(self, data: str, batch: Optional[int] = None):
        self.data = data
        self.inner = _pod_searcher_cls()(
            data, batch=batch or (1 << 20), mesh=global_mesh())

    def search(self, lower: int, upper: int):
        return bounded_pod_call(lambda: (
            broadcast_job(self.data, lower, upper),
            self.inner.search(lower, upper))[1])

    def search_until(self, lower: int, upper: int, target: int):
        if not target:
            # target 0 would broadcast as opcode 1 (arg-min), desyncing the
            # owner's until program from the followers' collective
            # sequence; route it explicitly — 0 can never qualify, so the
            # arg-min with found=False is the exact same answer.
            return (*self.search(lower, upper), False)
        return bounded_pod_call(lambda: (
            broadcast_job(self.data, lower, upper, target),
            self.inner.search_until(lower, upper, target))[1])


def run_follower(batch: Optional[int] = None,
                 cache_size: Optional[int] = None) -> int:
    """Follower-host main loop: execute broadcast jobs until stop.

    Mirrors the owner's per-message searcher cache (same bound, shared
    constant) so both sides keep the same compiled signatures warm;
    returns the number of jobs executed.

    Failure domain (ADVICE r4): the in-job collectives are bounded by
    ``bounded_pod_call`` (DBM_POD_TIMEOUT_S), but the BETWEEN-jobs
    broadcast wait is unbounded by default — an idle pool legitimately
    sends nothing, so only the distributed runtime's own heartbeat
    covers an owner that dies between jobs. Deployments that want a hard
    bound there too set ``DBM_POD_IDLE_TIMEOUT_S`` (seconds): the wait
    then runs under the same bound machinery and a quiet pool kills the
    follower (exit 17) when it expires.
    """
    from ..apps.miner import MinerWorker
    if cache_size is None:
        cache_size = MinerWorker.SEARCHER_CACHE_SIZE
    searcher_cls = _pod_searcher_cls()
    searchers: OrderedDict[str, object] = OrderedDict()
    mesh = global_mesh()
    # A malformed knob falls back silently (the _env contract): a typo
    # must not crash the follower and wedge the pod.
    idle_bound = _float_env("DBM_POD_IDLE_TIMEOUT_S", 0.0)
    jobs = 0
    while True:
        job = (bounded_pod_call(_receive_job, timeout_s=idle_bound)
               if idle_bound > 0 else _receive_job())
        if job is None:
            return jobs
        data, lower, upper, target = job
        s = searchers.get(data)
        if s is None:
            s = searcher_cls(data, batch=batch or (1 << 20), mesh=mesh)
            searchers[data] = s
            while len(searchers) > cache_size:
                searchers.popitem(last=False)
        else:
            searchers.move_to_end(data)
        try:
            # Result replicated; the owner reports it. The until host loop
            # branches only on replicated values, keeping hosts in lockstep.
            # bounded_pod_call enforces the failure-domain bound: a peer
            # dying mid-collective wedges this search, and the bound
            # converts the wedge into process death (r4; was a comment-only
            # claim before).
            if target:
                bounded_pod_call(
                    lambda: s.search_until(lower, upper, target))
            else:
                bounded_pod_call(lambda: s.search(lower, upper))
        except Exception:
            # Failure symmetry (round-3 review): a deterministic compute
            # error raises on EVERY host (same program); the owner's
            # MinerWorker catches it and exits the pool, so the follower
            # must survive and rejoin the next broadcast rather than die
            # and deadlock the owner.
            logger.exception("follower search failed for %r [%d, %d]",
                             data, lower, upper)
        jobs += 1
