"""Multi-host (pod-scale) wiring: one LSP miner per pod, DCN + ICI split.

Deployment shape per the north star: a whole multi-host TPU pod joins the
scheduler as ONE miner. Every host runs the same SPMD program (standard JAX
multi-controller); host 0 additionally owns the LSP client socket. Chunk
bounds arriving over LSP are host-side Python scalars, broadcast to all
hosts out-of-band (the per-host sub-span derives deterministically from
process_index), so the device program never sees DCN — intra-search
communication is exactly the staged-pmin merge over ICI from
``mesh_search``, now spanning the global mesh.

The reference's analog is its LSP/UDP stack (SURVEY §2, communication
backend): host<->host traffic stays on the unchanged wire protocol; the
NCCL/MPI role is played entirely by XLA collectives.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from .mesh_search import make_mesh


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Join the JAX distributed runtime; returns True in multi-host mode.

    With no arguments, reads ``DBM_COORDINATOR`` / ``DBM_NUM_PROCS`` /
    ``DBM_PROC_ID`` and stays single-host when unset (the common case on
    one chip or one host).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "DBM_COORDINATOR")
    if coordinator_address is None:
        return False
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("DBM_NUM_PROCS", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("DBM_PROC_ID", "0"))
    jax.distributed.initialize(coordinator_address, num_processes, process_id)
    return True


def global_mesh():
    """1-D mesh over every device of every host (ICI+DCN per JAX layout)."""
    return make_mesh(devices=jax.devices())


def is_lsp_owner() -> bool:
    """True on the one host that speaks LSP for the whole pod (host 0)."""
    return jax.process_index() == 0
