"""Declarative partition-rule table for the mesh plane (ISSUE 14).

The mesh entry points used to declare operand placement as positional
``in_specs`` tuples hand-maintained per call site — adding one operand
(the ISSUE 14 carry, the per-device stripe windows) meant re-counting
three tuples in two functions and hoping they stayed aligned with the
argument order. This module replaces that with the fmengine idiom
(SNIPPETS.md §1, ``match_partition_rules``): operands travel as ONE
NAMED pytree, and a regex rule table maps each leaf's '/'-joined name
to its :class:`~jax.sharding.PartitionSpec`. The table is the single
declaration of how the mesh plane lands data:

- **replicated** (``P()``): the midstate, tail template, hoist
  precompute, block base, difficulty target, and the running carry —
  every device holds the same value; XLA ships it once.
- **device-sharded** (``P(AXIS)``): the per-device stripe windows
  (``i0_d`` / ``lo_d`` / ``hi_d``) — one scalar per device, the
  contiguous window that device scans.

Scalars (0-d leaves) are never partitioned, exactly like the fmengine
rule. An operand with no matching rule is a hard error: a silently
replicated sharded operand (or vice versa) is a correctness bug, not a
default.
"""

from __future__ import annotations

import re

import numpy as np
from jax.sharding import PartitionSpec as P

#: The 1-D mesh axis every rule refers to (kept in one place with the
#: rules; ``mesh_search`` re-exports it).
AXIS = "d"

#: The mesh plane's rule table: ``(name_regex, PartitionSpec)`` pairs,
#: first match wins. Names are '/'-joined paths through the operand
#: pytree (``hoist/cw`` etc. for the hoist operand dict).
MESH_PARTITION_RULES = (
    # Per-device stripe windows: one entry per device on the mesh axis.
    (r"^(i0|lo|hi)_d$", P(AXIS)),
    # Everything else the span scan consumes is replicated: the carry,
    # midstate, template, block base words, difficulty target words,
    # and every hoist precompute leaf.
    (r"^carry$", P()),
    (r"^(midstate|template)$", P()),
    (r"^base_(hi|lo)$", P()),
    (r"^target_(hi|lo)$", P()),
    (r"^hoist(/.+)?$", P()),
)


def named_tree_map(fn, tree, sep: str = "/", _prefix: str = ""):
    """Map ``fn(name, leaf)`` over a dict pytree, names '/'-joined.

    Only dicts recurse (the operand trees here are dicts of arrays /
    dicts); every other value is a leaf. Key order is preserved, so the
    result structure matches the input structure exactly — what lets
    the caller hand the result to ``shard_map`` as the in_specs pytree
    for the matching operand argument.
    """
    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            name = _prefix + k
            if isinstance(v, dict):
                out[k] = named_tree_map(fn, v, sep=sep, _prefix=name + sep)
            else:
                out[k] = fn(name, v)
        return out
    return fn(_prefix.rstrip(sep), tree)


def match_partition_rules(rules, operands: dict):
    """PartitionSpec pytree for a named operand pytree (fmengine style).

    ``rules`` is ``((regex, spec), ...)``; first match wins. 0-d /
    size-1 leaves are never partitioned (``P()``) regardless of rules —
    the fmengine scalar rule. A leaf matching no rule raises: partition
    placement is a declared contract, not a default.
    """
    def spec_for(name, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"no partition rule matches operand {name!r}")
    return named_tree_map(spec_for, operands)


def mesh_specs(operands: dict):
    """The mesh plane's specs for one operand dict (rule table above)."""
    return match_partition_rules(MESH_PARTITION_RULES, operands)


def device_windows(lo_i: int, hi_i: int, n_devices: int,
                   batch: int):
    """Per-core stripe windows: cut the valid lane window ``[lo_i,
    hi_i]`` into ``n_devices`` CONTIGUOUS ascending equal-ish windows
    (the scheduler's stripe-plan shape, applied inside one miner), and
    align each device's scan start down to its batch boundary.

    Returns ``(i0_d, lo_d, hi_d, nbatches)`` — three ``(n,)`` uint32
    arrays plus the per-device step count that covers the WIDEST
    aligned window (every device runs the same static step count;
    narrower/empty windows mask). Why this beats the round-1-style
    fixed per-device spans with a global window: a window occupying the
    tail of its 10^k block left the leading devices hashing fully
    MASKED lanes (masked lanes still burn compute) — even windows keep
    every core's VALID work balanced within one lane-batch.

    Trailing devices of a narrow window get an EMPTY window
    (``lo > hi``): every lane masks to the sentinel, which never wins
    the merge.
    """
    span = hi_i - lo_i + 1
    if span <= 0:
        raise ValueError("empty window")
    per = -(-span // n_devices)           # ceil: lanes per device
    i0_d = np.zeros(n_devices, dtype=np.uint32)
    lo_d = np.ones(n_devices, dtype=np.uint32)
    hi_d = np.zeros(n_devices, dtype=np.uint32)   # lo>hi == empty
    steps = 1
    for d in range(n_devices):
        lo = lo_i + d * per
        if lo > hi_i:
            continue                      # empty window, stays masked
        hi = min(lo + per - 1, hi_i)
        i0 = (lo // batch) * batch        # aligned scan start
        lo_d[d] = lo
        hi_d[d] = hi
        i0_d[d] = i0
        steps = max(steps, -(-(hi - i0 + 1) // batch))
    return i0_d, lo_d, hi_d, steps


def pow2_subs(nbatches: int) -> list:
    """Descending-pow2 decomposition of a step count: ``(offset_steps,
    pow2_steps)`` pairs covering exactly ``nbatches`` steps. Same
    rationale as ``NonceSearcher._sub_dispatches`` — the step count is
    a static jit argument, so it must stay within the bounded pow2
    value set or every odd-sized window mints a fresh compile."""
    subs = []
    off = 0
    n = nbatches
    while n > 0:
        p = 1 << (n.bit_length() - 1)
        subs.append((off, p))
        off += p
        n -= p
    return subs
