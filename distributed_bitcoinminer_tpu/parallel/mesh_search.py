"""Mesh-sharded nonce search: shard_map over a 1-D device mesh.

This is the on-device half of the reference scheduler's data parallelism
(ref: bitcoin/server/server.go:165-205 splits a range across LSP miners; here
the same split happens *inside* one miner, across TPU cores, with the merge as
an ICI collective instead of host messaging).

Design (TPU-first):

- The "sequence" axis of this framework is the nonce range. A block of
  ``10^k`` lanes is cut into ``n_devices`` contiguous, disjoint spans; each
  device scans its span with the shared (replicated) midstate + tail
  template via the same ``span_scan_body`` used single-device.
- The merge is an exact lexicographic (hash_hi, hash_lo, index) arg-min over
  the mesh axis, computed on device as three staged ``pmin`` collectives
  over scalars riding ICI (bandwidth-free), yielding a replicated triple.
  Ties resolve to the lowest index, which is the lowest nonce, matching the
  Go scan's first-seen-wins strict ``<`` (ref: bitcoin/miner/miner.go:54-58).
- Everything is static-shaped; one compilation per
  (rem, k, batch, nbatches, mesh) signature.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.search import span_scan_body
from ..ops.sha256_jnp import ensure_varying

_MAX_U32 = np.uint32(0xFFFFFFFF)

AXIS = "d"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "nbatches", "tier"))
def sharded_search_span(midstate, template, i0_d, lo_i, hi_i, *, mesh: Mesh,
                        rem: int, k: int, batch: int, nbatches: int,
                        tier: str = "jnp"):
    """Scan ``n`` disjoint spans, one per device, and merge on device.

    midstate: (8,) uint32 — replicated.
    template: (nblocks, 16) uint32 — replicated.
    i0_d: (n,) uint32 — per-device span start lane (device d scans
        ``i0_d[d] + [0, nbatches*batch)``).
    lo_i, hi_i: uint32 scalars — the block's global valid lane window;
        lanes outside it contribute the 0xffffffff sentinel.
    tier: per-device kernel — ``jnp`` (rolled span scan) or ``pallas``
        (unrolled Mosaic kernel; the collective merge is identical).

    Returns replicated (best_hi, best_lo, best_i) uint32 scalars.
    """
    midstate = jnp.asarray(midstate, dtype=jnp.uint32)
    template = jnp.asarray(template, dtype=jnp.uint32)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(AXIS), P(), P()),
        out_specs=(P(), P(), P()))
    def body(midstate, template, i0, lo_i, hi_i):
        total = batch * nbatches
        from ..models.miner_model import _PALLAS_STEP, pallas_interpret_mode
        # The pallas tier is honored only on real TPU: inside this jitted
        # shard_map body interpret mode cannot run eagerly, and XLA:CPU
        # compiling the unrolled 64-round chain blows up (minutes). Off-TPU
        # the body falls back to the bit-identical rolled jnp scan.
        if tier == "pallas" and not pallas_interpret_mode():
            from ..ops.sha256_pallas import pallas_search_span
            rows = max(1, min(total, _PALLAS_STEP) // 128)
            per_step = rows * 128
            # Ceil, not floor: overscan lanes are masked in-kernel
            # (same round-3 fix as miner_model.search_block).
            hi_h, lo_h, idx = pallas_search_span(
                midstate, template, i0[0], lo_i, hi_i,
                rem=rem, k=k, rows=rows, nsteps=-(-total // per_step),
                interpret=False)
            hi_h, lo_h, idx = (ensure_varying(x, (AXIS,))
                               for x in (hi_h, lo_h, idx))
        else:
            hi_h, lo_h, idx = span_scan_body(
                midstate, template, i0[0], lo_i, hi_i,
                rem=rem, k=k, batch=batch, nbatches=nbatches,
                vary_axes=(AXIS,))
        # Cross-device exact lexicographic argmin as three staged pmin
        # collectives over scalars (replication-invariant outputs, so the
        # merged triple is provably identical on every device).
        min_hi = jax.lax.pmin(hi_h, AXIS)
        lo_m = jnp.where(hi_h == min_hi, lo_h, _MAX_U32)
        min_lo = jax.lax.pmin(lo_m, AXIS)
        idx_m = jnp.where((hi_h == min_hi) & (lo_h == min_lo), idx, _MAX_U32)
        min_idx = jax.lax.pmin(idx_m, AXIS)
        return min_hi, min_lo, min_idx

    return body(midstate, template, jnp.asarray(i0_d, dtype=jnp.uint32),
                jnp.uint32(lo_i), jnp.uint32(hi_i))


def device_spans(i0: int, n_devices: int, batch: int, nbatches: int) -> np.ndarray:
    """Per-device span starts for a contiguous split from lane ``i0``."""
    per = batch * nbatches
    return (np.uint32(i0) +
            np.arange(n_devices, dtype=np.uint32) * np.uint32(per))
