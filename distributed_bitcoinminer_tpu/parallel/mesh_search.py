"""Mesh-sharded nonce search: shard_map over a 1-D device mesh.

This is the on-device half of the reference scheduler's data parallelism
(ref: bitcoin/server/server.go:165-205 splits a range across LSP miners; here
the same split happens *inside* one miner, across TPU cores, with the merge as
an ICI collective instead of host messaging).

Design (TPU-first):

- The "sequence" axis of this framework is the nonce range. A block of
  ``10^k`` lanes is cut into ``n_devices`` contiguous, disjoint spans; each
  device scans its span with the shared (replicated) midstate + tail
  template via the same ``span_scan_body`` used single-device.
- The merge is an exact lexicographic (hash_hi, hash_lo, index) arg-min over
  the mesh axis, computed on device as three staged ``pmin`` collectives
  over scalars riding ICI (bandwidth-free), yielding a replicated triple.
  Ties resolve to the lowest index, which is the lowest nonce, matching the
  Go scan's first-seen-wins strict ``<`` (ref: bitcoin/miner/miner.go:54-58).
- Everything is static-shaped; one compilation per
  (rem, k, batch, nbatches, mesh) signature.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map as _shard_map
    _SHARD_MAP_KW = {}
except ImportError:  # jax 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map
    # 0.4.x check_rep has no replication rule for while_loop (the until
    # tier's per-device early-exit loop). Disabling it is sound here: the
    # staged pmin/pmax merges make every output replicated by
    # construction, which is exactly what the P() out_specs declare.
    _SHARD_MAP_KW = {"check_rep": False}
from jax.sharding import Mesh, PartitionSpec as P


def shard_map(f, **kw):
    """Version-portable ``jax.shard_map`` (see _SHARD_MAP_KW above)."""
    return _shard_map(f, **kw, **_SHARD_MAP_KW)

from ..ops.search import span_scan_body, span_until_body

_MAX_U32 = np.uint32(0xFFFFFFFF)

AXIS = "d"


def _pmin_lex_argmin(b_hi, b_lo, b_idx):
    """Exact lexicographic (hash_hi, hash_lo, index) argmin across the mesh
    axis as three staged ``pmin`` collectives over scalars (replication-
    invariant outputs, so the merged triple is provably identical on every
    device). Ties resolve to the lowest index = lowest nonce, matching the
    Go scan's first-seen-wins strict ``<`` (ref: miner.go:54-58)."""
    min_hi = jax.lax.pmin(b_hi, AXIS)
    lo_m = jnp.where(b_hi == min_hi, b_lo, _MAX_U32)
    min_lo = jax.lax.pmin(lo_m, AXIS)
    idx_m = jnp.where((b_hi == min_hi) & (b_lo == min_lo), b_idx, _MAX_U32)
    return min_hi, min_lo, jax.lax.pmin(idx_m, AXIS)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "nbatches", "tier"))
def sharded_search_span(midstate, template, i0_d, lo_i, hi_i, hoist=None, *,
                        mesh: Mesh, rem: int, k: int, batch: int,
                        nbatches: int, tier: str = "jnp"):
    """Scan ``n`` disjoint spans, one per device, and merge on device.

    midstate: (8,) uint32 — replicated.
    template: (nblocks, 16) uint32 — replicated.
    i0_d: (n,) uint32 — per-device span start lane (device d scans
        ``i0_d[d] + [0, nbatches*batch)``).
    lo_i, hi_i: uint32 scalars — the block's global valid lane window;
        lanes outside it contribute the 0xffffffff sentinel.
    hoist: optional lane-invariant precompute operand dict
        (``sha256_jnp.HoistPlan.ops``) — replicated like the midstate it
        extends; None keeps the original entry path.
    tier: per-device kernel — ``jnp`` (rolled span scan) or ``pallas``
        (unrolled Mosaic kernel; the collective merge is identical).

    Returns replicated (best_hi, best_lo, best_i) uint32 scalars.
    """
    midstate = jnp.asarray(midstate, dtype=jnp.uint32)
    template = jnp.asarray(template, dtype=jnp.uint32)
    hoist_in = () if hoist is None else (hoist,)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(AXIS), P(), P()) + ((P(),) if hoist_in
                                                 else ()),
        out_specs=(P(), P(), P()))
    def body(midstate, template, i0, lo_i, hi_i, *hoist_in):
        hoist = hoist_in[0] if hoist_in else None
        # The pallas tier runs everywhere since round 3: through Mosaic on
        # the chip, through the Mosaic TPU simulator (InterpretParams) on
        # the CPU test mesh — the wrapper derives interpret mode from the
        # MESH devices' platform, not the default backend (which this
        # image's sitecustomize can pin to the axon TPU plugin even when
        # the mesh in play is the virtual CPU one). The out
        # ShapeDtypeStructs carry vma=(AXIS,) so shard_map's varying-axis
        # checker accepts the varying span starts.
        if tier == "pallas":
            from ..ops.sha256_pallas import pallas_argmin
            hi_h, lo_h, idx = pallas_argmin(
                midstate, template, i0[0], lo_i, hi_i,
                rem=rem, k=k, total=batch * nbatches,
                platform=mesh.devices.flat[0].platform, vma=(AXIS,),
                hoist=hoist)
        else:
            hi_h, lo_h, idx = span_scan_body(
                midstate, template, i0[0], lo_i, hi_i,
                rem=rem, k=k, batch=batch, nbatches=nbatches,
                vary_axes=(AXIS,), hoist=hoist)
        return _pmin_lex_argmin(hi_h, lo_h, idx)

    return body(midstate, template, jnp.asarray(i0_d, dtype=jnp.uint32),
                jnp.uint32(lo_i), jnp.uint32(hi_i), *hoist_in)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "nbatches", "tier"))
def sharded_search_span_until(midstate, template, i0_d, lo_i, hi_i,
                              target_hi, target_lo, hoist=None, *,
                              mesh: Mesh, rem: int, k: int, batch: int,
                              nbatches: int, tier: str = "jnp"):
    """Difficulty-target scan over ``n`` disjoint per-device spans.

    Each device scans its own contiguous span — the jnp tier with the
    early-exiting :func:`span_until_body` (the ``while_loop`` predicate is
    device-varying, so a device stops at ITS first qualifying batch
    independently; no collectives ride inside the loop), the pallas tier
    with the Mosaic kernel's qualifying-index accumulator plus its
    per-grid-step SMEM found-flag skip (r4): a device that hits early
    spends ~one step of compute on the rest of its span. The merge
    preserves the first-qualifying-nonce rule globally: spans are
    contiguous and disjoint and each device's hit is the minimal
    qualifying nonce of its span, so the global first hit is the ``pmin``
    of the per-device hit indices; the fallback argmin merges exactly
    like :func:`sharded_search_span`.

    Returns replicated uint32 scalars
    ``(found, f_idx, best_hi, best_lo, best_idx)`` with the same contract
    as :func:`ops.search.search_span_until` (the qualifying HASH is
    recomputed by the model layer from the host oracle when ``found`` —
    models.miner_model._until_block).
    """
    midstate = jnp.asarray(midstate, dtype=jnp.uint32)
    template = jnp.asarray(template, dtype=jnp.uint32)
    hoist_in = () if hoist is None else (hoist,)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(AXIS), P(), P(), P(), P()) + (
            (P(),) if hoist_in else ()),
        out_specs=(P(),) * 5)
    def body(midstate, template, i0, lo_i, hi_i, t_hi, t_lo, *hoist_in):
        hoist = hoist_in[0] if hoist_in else None
        if tier == "pallas":
            from ..ops.sha256_pallas import pallas_until
            found, f_idx, b_hi, b_lo, b_idx = pallas_until(
                midstate, template, i0[0], lo_i, hi_i, t_hi, t_lo,
                rem=rem, k=k, total=batch * nbatches,
                platform=mesh.devices.flat[0].platform, vma=(AXIS,),
                hoist=hoist)
        else:
            found, f_idx, b_hi, b_lo, b_idx = span_until_body(
                midstate, template, i0[0], lo_i, hi_i, t_hi, t_lo,
                rem=rem, k=k, batch=batch, nbatches=nbatches,
                vary_axes=(AXIS,), hoist=hoist)
        # First qualifying nonce globally = min of per-device first hits
        # (disjoint ascending spans; non-hit devices carry the MAX
        # sentinel).
        g_idx = jax.lax.pmin(f_idx, AXIS)
        g_found = jax.lax.pmax(found, AXIS)
        # Fallback exact argmin across devices (used only when no device
        # hit, in which case every device scanned its full span).
        return g_found, g_idx, *_pmin_lex_argmin(b_hi, b_lo, b_idx)

    return body(midstate, template, jnp.asarray(i0_d, dtype=jnp.uint32),
                jnp.uint32(lo_i), jnp.uint32(hi_i),
                jnp.uint32(target_hi), jnp.uint32(target_lo), *hoist_in)


def device_spans(i0: int, n_devices: int, batch: int, nbatches: int) -> np.ndarray:
    """Per-device span starts for a contiguous split from lane ``i0``."""
    per = batch * nbatches
    return (np.uint32(i0) +
            np.arange(n_devices, dtype=np.uint32) * np.uint32(per))
