"""Mesh-sharded nonce search: shard_map over a 1-D device mesh.

This is the on-device half of the reference scheduler's data parallelism
(ref: bitcoin/server/server.go:165-205 splits a range across LSP miners; here
the same split happens *inside* one miner, across TPU cores, with the merge as
an ICI collective instead of host messaging).

Design (TPU-first):

- The "sequence" axis of this framework is the nonce range. A block of
  ``10^k`` lanes is cut into ``n_devices`` contiguous, disjoint spans; each
  device scans its span with the shared (replicated) midstate + tail
  template via the same ``span_scan_body`` used single-device.
- The merge is an exact lexicographic (hash_hi, hash_lo, index) arg-min over
  the mesh axis, computed on device as three staged ``pmin`` collectives
  over scalars riding ICI (bandwidth-free), yielding a replicated triple.
  Ties resolve to the lowest index, which is the lowest nonce, matching the
  Go scan's first-seen-wins strict ``<`` (ref: bitcoin/miner/miner.go:54-58).
- Everything is static-shaped; one compilation per
  (rem, k, batch, nbatches, mesh) signature.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map as _shard_map
    _SHARD_MAP_KW = {}
except ImportError:  # jax 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map
    # 0.4.x check_rep has no replication rule for while_loop (the until
    # tier's per-device early-exit loop). Disabling it is sound here: the
    # staged pmin/pmax merges make every output replicated by
    # construction, which is exactly what the P() out_specs declare.
    _SHARD_MAP_KW = {"check_rep": False}
from jax.sharding import Mesh, PartitionSpec as P


def shard_map(f, **kw):
    """Version-portable ``jax.shard_map`` (see _SHARD_MAP_KW above)."""
    return _shard_map(f, **kw, **_SHARD_MAP_KW)

from ..ops import searchop
from ..ops.search import (devloop_scan, devloop_until_scan, span_scan_body,
                          span_until_body)
from .partition import AXIS, device_windows, mesh_specs, pow2_subs

_MAX_U32 = np.uint32(0xFFFFFFFF)

__all__ = ["AXIS", "make_mesh", "device_spans", "device_windows",
           "pow2_subs", "sharded_search_span", "sharded_search_span_until",
           "mesh_search_span", "mesh_search_span_until",
           "mesh_devloop_span", "mesh_devloop_span_until",
           "mesh_carry_init", "mesh_until_carry_init"]


def _pmin_lex_argmin(b_hi, b_lo, b_idx):
    """Exact lexicographic (hash_hi, hash_lo, index) argmin across the mesh
    axis as three staged ``pmin`` collectives over scalars (replication-
    invariant outputs, so the merged triple is provably identical on every
    device). Ties resolve to the lowest index = lowest nonce, matching the
    Go scan's first-seen-wins strict ``<`` (ref: miner.go:54-58)."""
    min_hi = jax.lax.pmin(b_hi, AXIS)
    lo_m = jnp.where(b_hi == min_hi, b_lo, _MAX_U32)
    min_lo = jax.lax.pmin(lo_m, AXIS)
    idx_m = jnp.where((b_hi == min_hi) & (b_lo == min_lo), b_idx, _MAX_U32)
    return min_hi, min_lo, jax.lax.pmin(idx_m, AXIS)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "nbatches", "tier"))
def sharded_search_span(midstate, template, i0_d, lo_i, hi_i, hoist=None, *,
                        mesh: Mesh, rem: int, k: int, batch: int,
                        nbatches: int, tier: str = "jnp"):
    """Scan ``n`` disjoint spans, one per device, and merge on device.

    midstate: (8,) uint32 — replicated.
    template: (nblocks, 16) uint32 — replicated.
    i0_d: (n,) uint32 — per-device span start lane (device d scans
        ``i0_d[d] + [0, nbatches*batch)``).
    lo_i, hi_i: uint32 scalars — the block's global valid lane window;
        lanes outside it contribute the 0xffffffff sentinel.
    hoist: optional lane-invariant precompute operand dict
        (``sha256_jnp.HoistPlan.ops``) — replicated like the midstate it
        extends; None keeps the original entry path.
    tier: per-device kernel — ``jnp`` (rolled span scan) or ``pallas``
        (unrolled Mosaic kernel; the collective merge is identical).

    Returns replicated (best_hi, best_lo, best_i) uint32 scalars.
    """
    operands = {"midstate": jnp.asarray(midstate, dtype=jnp.uint32),
                "template": jnp.asarray(template, dtype=jnp.uint32),
                "i0_d": jnp.asarray(i0_d, dtype=jnp.uint32),
                "lo_i": jnp.uint32(lo_i), "hi_i": jnp.uint32(hi_i)}
    if hoist is not None:
        operands["hoist"] = hoist

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(mesh_specs(operands),), out_specs=(P(), P(), P()))
    def body(ops):
        hoist = ops.get("hoist")
        # The pallas tier runs everywhere since round 3: through Mosaic on
        # the chip, through the Mosaic TPU simulator (InterpretParams) on
        # the CPU test mesh — the wrapper derives interpret mode from the
        # MESH devices' platform, not the default backend (which this
        # image's sitecustomize can pin to the axon TPU plugin even when
        # the mesh in play is the virtual CPU one). The out
        # ShapeDtypeStructs carry vma=(AXIS,) so shard_map's varying-axis
        # checker accepts the varying span starts.
        if tier == "pallas":
            from ..ops.sha256_pallas import pallas_argmin
            hi_h, lo_h, idx = pallas_argmin(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_i"], ops["hi_i"],
                rem=rem, k=k, total=batch * nbatches,
                platform=mesh.devices.flat[0].platform, vma=(AXIS,),
                hoist=hoist)
        else:
            hi_h, lo_h, idx = span_scan_body(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_i"], ops["hi_i"],
                rem=rem, k=k, batch=batch, nbatches=nbatches,
                vary_axes=(AXIS,), hoist=hoist)
        return _pmin_lex_argmin(hi_h, lo_h, idx)

    return body(operands)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "nbatches", "tier"))
def sharded_search_span_until(midstate, template, i0_d, lo_i, hi_i,
                              target_hi, target_lo, hoist=None, *,
                              mesh: Mesh, rem: int, k: int, batch: int,
                              nbatches: int, tier: str = "jnp"):
    """Difficulty-target scan over ``n`` disjoint per-device spans.

    Each device scans its own contiguous span — the jnp tier with the
    early-exiting :func:`span_until_body` (the ``while_loop`` predicate is
    device-varying, so a device stops at ITS first qualifying batch
    independently; no collectives ride inside the loop), the pallas tier
    with the Mosaic kernel's qualifying-index accumulator plus its
    per-grid-step SMEM found-flag skip (r4): a device that hits early
    spends ~one step of compute on the rest of its span. The merge
    preserves the first-qualifying-nonce rule globally: spans are
    contiguous and disjoint and each device's hit is the minimal
    qualifying nonce of its span, so the global first hit is the ``pmin``
    of the per-device hit indices; the fallback argmin merges exactly
    like :func:`sharded_search_span`.

    Returns replicated uint32 scalars
    ``(found, f_idx, best_hi, best_lo, best_idx)`` with the same contract
    as :func:`ops.search.search_span_until` (the qualifying HASH is
    recomputed by the model layer from the host oracle when ``found`` —
    models.miner_model._until_block).
    """
    operands = {"midstate": jnp.asarray(midstate, dtype=jnp.uint32),
                "template": jnp.asarray(template, dtype=jnp.uint32),
                "i0_d": jnp.asarray(i0_d, dtype=jnp.uint32),
                "lo_i": jnp.uint32(lo_i), "hi_i": jnp.uint32(hi_i),
                "target_hi": jnp.uint32(target_hi),
                "target_lo": jnp.uint32(target_lo)}
    if hoist is not None:
        operands["hoist"] = hoist

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(mesh_specs(operands),), out_specs=(P(),) * 5)
    def body(ops):
        hoist = ops.get("hoist")
        if tier == "pallas":
            from ..ops.sha256_pallas import pallas_until
            found, f_idx, b_hi, b_lo, b_idx = pallas_until(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_i"], ops["hi_i"], ops["target_hi"],
                ops["target_lo"],
                rem=rem, k=k, total=batch * nbatches,
                platform=mesh.devices.flat[0].platform, vma=(AXIS,),
                hoist=hoist)
        else:
            found, f_idx, b_hi, b_lo, b_idx = span_until_body(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_i"], ops["hi_i"], ops["target_hi"],
                ops["target_lo"],
                rem=rem, k=k, batch=batch, nbatches=nbatches,
                vary_axes=(AXIS,), hoist=hoist)
        # First qualifying nonce globally = min of per-device first hits
        # (disjoint ascending spans; non-hit devices carry the MAX
        # sentinel).
        g_idx = jax.lax.pmin(f_idx, AXIS)
        g_found = jax.lax.pmax(found, AXIS)
        # Fallback exact argmin across devices (used only when no device
        # hit, in which case every device scanned its full span).
        return g_found, g_idx, *_pmin_lex_argmin(b_hi, b_lo, b_idx)

    return body(operands)


def device_spans(i0: int, n_devices: int, batch: int, nbatches: int) -> np.ndarray:
    """Per-device span starts for a contiguous split from lane ``i0``."""
    per = batch * nbatches
    return (np.uint32(i0) +
            np.arange(n_devices, dtype=np.uint32) * np.uint32(per))


# --------------------------------------------------------------------------
# ISSUE 14 mesh plane: carry-chained whole-span dispatch.
#
# The round-3 entries above return one replicated triple PER SUB-DISPATCH;
# a whole chunk's pow2 sub-dispatches (and its several 10^k blocks) then
# merge on the HOST — one device fetch per partial. The carry-chained
# entries below keep the running best ON DEVICE: each launch folds its
# mesh-merged candidate into a replicated carry vector it received as an
# operand, so a whole-mesh SPAN — however many blocks and pow2 subs it
# decomposes into — sends exactly ONE (hash, nonce) result to the host,
# fetched once at finalize (models/sharded.MeshNonceSearcher). The carry
# holds the GLOBAL 64-bit nonce (block base folded in on device), so the
# chain crosses block boundaries.
#
# Merge rule: full lexicographic strict-less on (hash, nonce) among seen
# candidates — exactly "the minimal hash, earliest nonce on ties", which
# is what finalize's ascending strict-less-on-hash walk computes. The
# full lex (not hash-only) matters here because the per-core stripe
# windows interleave lane coverage across chained subs: device 0's
# second sub covers LOWER nonces than device 1's first, so chain order
# is not nonce order and the tie-break must be explicit.

# The carry codec + fold semiring moved behind the SearchOp seam in
# ops/searchop.py (ISSUE 19) — one copy shared by this mesh plane and
# the single-device devloop drivers. The names below stay importable
# from here (the PR 14 surface) and are byte-identical delegations.
CARRY_WORDS = searchop.CARRY_WORDS
UNTIL_CARRY_WORDS = searchop.UNTIL_CARRY_WORDS
mesh_carry_init = searchop.carry_init
mesh_until_carry_init = searchop.until_carry_init
_lex_less = searchop.lex_less
_global_nonce = searchop.global_nonce
_fold_argmin = searchop.fold_argmin


def _scan_windows(ops, *, mesh, rem, k, batch, nbatches, tier):
    """Shared per-device window scan of the carry-chained bodies."""
    hoist = ops.get("hoist")
    if tier == "pallas":
        from ..ops.sha256_pallas import pallas_argmin
        return pallas_argmin(
            ops["midstate"], ops["template"], ops["i0_d"][0],
            ops["lo_d"][0], ops["hi_d"][0],
            rem=rem, k=k, total=batch * nbatches,
            platform=mesh.devices.flat[0].platform, vma=(AXIS,),
            hoist=hoist)
    return span_scan_body(
        ops["midstate"], ops["template"], ops["i0_d"][0],
        ops["lo_d"][0], ops["hi_d"][0],
        rem=rem, k=k, batch=batch, nbatches=nbatches,
        vary_axes=(AXIS,), hoist=hoist)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "nbatches", "tier"))
def mesh_search_span(operands, *, mesh: Mesh, rem: int, k: int,
                     batch: int, nbatches: int, tier: str = "jnp"):
    """One carry-chained whole-mesh launch over per-core stripe windows.

    ``operands`` is the NAMED pytree the partition-rule table places
    (``parallel/partition.py``): ``carry`` (5-word running best,
    replicated), ``midstate``/``template``/``base_hi``/``base_lo``/
    optional ``hoist`` (replicated), and the per-device stripe windows
    ``i0_d``/``lo_d``/``hi_d`` (device-sharded). Returns the UPDATED
    replicated carry — a device value the caller threads into the next
    launch (or fetches once per span).
    """
    specs = mesh_specs(operands)

    @functools.partial(shard_map, mesh=mesh, in_specs=(specs,),
                       out_specs=P())
    def body(ops):
        hi_h, lo_h, idx = _scan_windows(
            ops, mesh=mesh, rem=rem, k=k, batch=batch,
            nbatches=nbatches, tier=tier)
        m_hi, m_lo, m_idx = _pmin_lex_argmin(hi_h, lo_h, idx)
        return _fold_argmin(ops["carry"], m_hi, m_lo, m_idx,
                            ops["base_hi"], ops["base_lo"])

    return body(operands)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "nbatches", "tier"))
def mesh_search_span_until(operands, *, mesh: Mesh, rem: int, k: int,
                           batch: int, nbatches: int, tier: str = "jnp"):
    """Carry-chained difficulty launch: like :func:`mesh_search_span`
    plus the first-hit plane. ``operands`` additionally carries
    ``target_hi``/``target_lo`` (replicated) and the 8-word until carry.

    First-hit merge: the globally first qualifying nonce is the MINIMUM
    qualifying nonce — each device's until body reports its window's
    first hit, the mesh ``pmin`` takes the lowest lane, and the carry
    keeps the lex-lower 64-bit qualifying nonce across chained launches
    (chain order is not nonce order under the interleaved stripe
    windows, so the min — not first-write-wins — is the correct rule).
    The argmin fallback folds exactly like :func:`mesh_search_span`.
    """
    specs = mesh_specs(operands)

    @functools.partial(shard_map, mesh=mesh, in_specs=(specs,),
                       out_specs=P())
    def body(ops):
        hoist = ops.get("hoist")
        if tier == "pallas":
            from ..ops.sha256_pallas import pallas_until
            found, f_idx, b_hi, b_lo, b_idx = pallas_until(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_d"][0], ops["hi_d"][0],
                ops["target_hi"], ops["target_lo"],
                rem=rem, k=k, total=batch * nbatches,
                platform=mesh.devices.flat[0].platform, vma=(AXIS,),
                hoist=hoist)
        else:
            found, f_idx, b_hi, b_lo, b_idx = span_until_body(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_d"][0], ops["hi_d"][0],
                ops["target_hi"], ops["target_lo"],
                rem=rem, k=k, batch=batch, nbatches=nbatches,
                vary_axes=(AXIS,), hoist=hoist)
        # First-hit plane: min qualifying lane across the mesh
        # (disjoint ascending spans), then the lex-min qualifying
        # 64-bit nonce across the chain plus the argmin fallback — the
        # searchop fold (bit-identical to the PR 14 inline version).
        g_idx = jax.lax.pmin(f_idx, AXIS)
        m_hi, m_lo, m_idx = _pmin_lex_argmin(b_hi, b_lo, b_idx)
        return searchop.fold_until(ops["carry"], g_idx, m_hi, m_lo,
                                   m_idx, ops["base_hi"], ops["base_lo"])

    return body(operands)


# --------------------------------------------------------------------------
# ISSUE 19 devloop plane: whole-mesh span as ONE launch per block.
#
# The PR 14 entries above still run one launch per pow2 sub-window
# (carry-chained, so the host fetch already amortizes to one per span).
# The devloop entries fold the sub-window chain INTO the launch: each
# device walks all ``nsub`` stripe sub-windows of its block share with
# the dynamic-bound device loop (ops/search.devloop_scan — ``nsub`` is a
# traced replicated operand, only the pow2 ``cap`` is a jit static), so
# a whole-mesh span costs one launch per block instead of one per sub,
# and still exactly one carry fetch per span.


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "cap", "tier"))
def mesh_devloop_span(operands, *, mesh: Mesh, rem: int, k: int,
                      batch: int, cap: int, tier: str = "jnp"):
    """Device-resident whole-block mesh launch (argmin op).

    ``operands`` is the PR 14 named pytree plus ``nsub`` — the live
    per-device sub-window count (0-d, replicated; the partition-rule
    table places scalars as replicated automatically). Per-core stripe
    windows ``i0_d``/``lo_d``/``hi_d`` are device-sharded exactly as in
    :func:`mesh_search_span`; each device walks its contiguous window
    in ascending ``batch``-lane steps — the same lane->device
    assignment and scan order the chained pow2-sub plan produced, so
    results are bit-identical to the stock chain. Returns the updated
    replicated carry.
    """
    specs = mesh_specs(operands)

    @functools.partial(shard_map, mesh=mesh, in_specs=(specs,),
                       out_specs=P())
    def body(ops):
        hoist = ops.get("hoist")
        if tier == "pallas":
            from ..ops.sha256_pallas import pallas_devloop_scan
            hi_h, lo_h, idx = pallas_devloop_scan(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_d"][0], ops["hi_d"][0], ops["nsub"],
                rem=rem, k=k, batch=batch, cap=cap,
                platform=mesh.devices.flat[0].platform, vma=(AXIS,),
                hoist=hoist)
        else:
            hi_h, lo_h, idx = devloop_scan(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_d"][0], ops["hi_d"][0], ops["nsub"],
                rem=rem, k=k, batch=batch, cap=cap,
                vary_axes=(AXIS,), hoist=hoist)
        m_hi, m_lo, m_idx = _pmin_lex_argmin(hi_h, lo_h, idx)
        return searchop.fold_argmin(ops["carry"], m_hi, m_lo, m_idx,
                                    ops["base_hi"], ops["base_lo"])

    return body(operands)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "rem", "k", "batch", "cap", "tier"))
def mesh_devloop_span_until(operands, *, mesh: Mesh, rem: int, k: int,
                            batch: int, cap: int, tier: str = "jnp"):
    """Device-resident whole-block mesh difficulty launch.

    Each device runs the early-exiting dynamic-bound loop over its own
    stripe sub-windows (the while predicate is device-varying — a
    device stops at ITS first qualifying sub independently, and an
    already-found carry short-circuits the whole loop, so chained block
    launches after a hit cost ~no device time). Per-device windows are
    contiguous, disjoint and scanned ascending, so each device's
    ``f_idx`` is the minimal qualifying lane of its window and the
    global first hit is the mesh ``pmin`` — the same exact
    first-*qualifying*-nonce rule as :func:`mesh_search_span_until`.
    Returns the updated replicated 8-word carry.
    """
    specs = mesh_specs(operands)

    @functools.partial(shard_map, mesh=mesh, in_specs=(specs,),
                       out_specs=P())
    def body(ops):
        hoist = ops.get("hoist")
        found_prev = ops["carry"][0]
        if tier == "pallas":
            from ..ops.sha256_pallas import pallas_devloop_until_scan
            found, f_idx, b_hi, b_lo, b_idx = pallas_devloop_until_scan(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_d"][0], ops["hi_d"][0],
                ops["target_hi"], ops["target_lo"], ops["nsub"],
                found_prev, rem=rem, k=k, batch=batch, cap=cap,
                platform=mesh.devices.flat[0].platform, vma=(AXIS,),
                hoist=hoist)
        else:
            found, f_idx, b_hi, b_lo, b_idx = devloop_until_scan(
                ops["midstate"], ops["template"], ops["i0_d"][0],
                ops["lo_d"][0], ops["hi_d"][0],
                ops["target_hi"], ops["target_lo"], ops["nsub"],
                found_prev, rem=rem, k=k, batch=batch, cap=cap,
                vary_axes=(AXIS,), hoist=hoist)
        g_idx = jax.lax.pmin(f_idx, AXIS)
        m_hi, m_lo, m_idx = _pmin_lex_argmin(b_hi, b_lo, b_idx)
        return searchop.fold_until(ops["carry"], g_idx, m_hi, m_lo,
                                   m_idx, ops["base_hi"], ops["base_lo"])

    return body(operands)
