"""Parallelism layer: device meshes, sharded span search, collective merge.

The reference's only compute parallelism is data parallelism over the nonce
range (ref: bitcoin/server/server.go:165-205). Here that axis is sharded at
two nested levels: across LSP-registered miners (scheduler, unchanged
protocol) and across TPU cores inside one miner via ``shard_map`` over a 1-D
``jax.sharding.Mesh`` with a staged-pmin lexicographic-min merge on ICI.
Since ISSUE 14, operand placement is declared by the partition-rule table
(``partition.py``, fmengine style) and the mesh plane chains a replicated
on-device carry through every launch so one whole-mesh span crosses the
host as exactly one (hash, nonce) pair.
"""

from .mesh_search import (AXIS, device_spans, make_mesh, mesh_carry_init,
                          mesh_search_span, mesh_search_span_until,
                          mesh_until_carry_init, sharded_search_span,
                          sharded_search_span_until)
from .multihost import (PodSearcher, broadcast_job, broadcast_stop,
                        global_mesh, initialize_multihost, is_lsp_owner,
                        run_follower)
from .partition import (MESH_PARTITION_RULES, device_windows,
                        match_partition_rules, mesh_specs, pow2_subs)

__all__ = ["AXIS", "device_spans", "make_mesh", "sharded_search_span",
           "sharded_search_span_until",
           "mesh_search_span", "mesh_search_span_until",
           "mesh_carry_init", "mesh_until_carry_init",
           "MESH_PARTITION_RULES", "match_partition_rules", "mesh_specs",
           "device_windows", "pow2_subs",
           "PodSearcher", "broadcast_job", "broadcast_stop",
           "global_mesh", "initialize_multihost", "is_lsp_owner",
           "run_follower"]
