"""Parallelism layer: device meshes, sharded span search, collective merge.

The reference's only compute parallelism is data parallelism over the nonce
range (ref: bitcoin/server/server.go:165-205). Here that axis is sharded at
two nested levels: across LSP-registered miners (scheduler, unchanged
protocol) and across TPU cores inside one miner via ``shard_map`` over a 1-D
``jax.sharding.Mesh`` with a staged-pmin lexicographic-min merge on ICI.
"""

from .mesh_search import (AXIS, device_spans, make_mesh, sharded_search_span,
                          sharded_search_span_until)
from .multihost import (PodSearcher, broadcast_job, broadcast_stop,
                        global_mesh, initialize_multihost, is_lsp_owner,
                        run_follower)

__all__ = ["AXIS", "device_spans", "make_mesh", "sharded_search_span",
           "sharded_search_span_until",
           "PodSearcher", "broadcast_job", "broadcast_stop",
           "global_mesh", "initialize_multihost", "is_lsp_owner",
           "run_follower"]
