"""Analyzer: DBM_* knob hygiene (knob-hygiene).

The knob surface is ~50 environment variables grown over six PRs; the
recurring rot (ISSUE 7 motivation) is threefold and each part is a
check here:

1. **Routing.** Every ``DBM_*`` read must go through the helpers in
   ``utils/_env.py`` (``int_env`` / ``float_env`` / ``str_env``) — one
   grep target for the whole surface, one place for read semantics
   (malformed values fall back silently). Direct ``os.environ.get`` /
   ``os.environ[...]`` / ``os.getenv`` reads of ``DBM_*`` keys anywhere
   except ``utils/_env.py`` and ``utils/config.py`` are findings.
   Writes (``os.environ["DBM_X"] = ...``, ``pop``, ``setdefault``,
   child-process env dicts) are not reads and are not flagged.

2. **Docstring sync.** The read knob set (collected from the ``*_env``
   helper calls across the package, ``bench.py``, ``scripts/*.py``, and
   ``DBM_*`` tokens in ``scripts/*.sh``) must match the knob catalog in
   the ``utils/config.py`` module docstring: every read knob documented,
   no orphaned doc entries. A ``*_env`` call whose knob name is not a
   string literal defeats the collection and is flagged.

3. **README sync.** Same two-way check against ``README.md`` — the knob
   tables operators actually read. Family references like
   ``DBM_LEASE_*`` count as covering nothing by themselves (each knob
   must appear exactly somewhere) but are not orphans as long as at
   least one real knob carries the prefix.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Tuple

from .core import Finding, SourceFile, scope_map, str_const

NAME = "knob-hygiene"

ALLOWED_READERS = (
    "distributed_bitcoinminer_tpu/utils/_env.py",
    "distributed_bitcoinminer_tpu/utils/config.py",
)
ENV_HELPERS = ("int_env", "float_env", "str_env")
_TOKEN_RE = re.compile(r"DBM_[A-Z0-9_]+")


def _is_environ(node: ast.AST) -> bool:
    """``os.environ`` (or bare ``environ``)."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _direct_reads(tree: ast.AST):
    """(lineno, knob) for each direct environment READ of a DBM_* key."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            # os.environ.get("DBM_X"...) / os.getenv("DBM_X"...)
            is_get = (isinstance(func, ast.Attribute)
                      and func.attr == "get" and _is_environ(func.value))
            is_getenv = (isinstance(func, ast.Attribute)
                         and func.attr == "getenv")
            if (is_get or is_getenv) and node.args:
                key = str_const(node.args[0])
                if key is not None and key.startswith("DBM_"):
                    yield node.lineno, key
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and _is_environ(node.value):
            key = str_const(node.slice)
            if key is not None and key.startswith("DBM_"):
                yield node.lineno, key
        elif isinstance(node, ast.Compare) and node.ops and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                node.comparators and _is_environ(node.comparators[0]):
            key = str_const(node.left)
            if key is not None and key.startswith("DBM_"):
                yield node.lineno, key


def _helper_reads(tree: ast.AST):
    """(node, knob_or_None) per ``*_env`` helper call; None = computed."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if not fname.endswith(ENV_HELPERS):
            continue
        if not node.args:
            continue
        key = str_const(node.args[0])
        if key is None:
            yield node, None
        elif key.startswith("DBM_"):
            yield node, key


def _doc_tokens(text: str) -> List[str]:
    return _TOKEN_RE.findall(text)


def _coverage(tokens: List[str], knobs: set) -> Tuple[set, List[str]]:
    """(documented_knobs, orphan_tokens) for one document's tokens.

    A token matching a knob exactly documents it. A token that matches
    no knob but is a PREFIX of one (family shorthand like ``DBM_LEASE_``
    from ``DBM_LEASE_*``) is not an orphan, but documents nothing.
    """
    documented, orphans = set(), []
    for tok in tokens:
        if tok in knobs:
            documented.add(tok)
        elif any(k.startswith(tok) for k in knobs):
            continue
        else:
            orphans.append(tok)
    return documented, sorted(set(orphans))


def analyze(files: List[SourceFile], repo: str) -> List[Finding]:
    out: List[Finding] = []
    knobs: Dict[str, str] = {}     # knob -> first file that reads it

    for f in files:
        if f.rel.endswith(".sh"):
            for tok in _doc_tokens(f.text):
                knobs.setdefault(tok, f.rel)
            continue
        if f.tree is None:
            continue
        scopes = None
        for node, key in _helper_reads(f.tree):
            if key is None:
                if scopes is None:
                    scopes = scope_map(f.tree)
                scope = scopes.get(id(node)) or "<module>"
                out.append(Finding(
                    NAME, f.rel, node.lineno,
                    f"{NAME}:{f.rel}:computed-knob:{scope}",
                    "env helper called with a computed knob name; the "
                    "knob surface must be greppable (string literal)"))
            else:
                knobs.setdefault(key, f.rel)
        for lineno, key in _direct_reads(f.tree):
            knobs.setdefault(key, f.rel)
            if f.rel in ALLOWED_READERS:
                continue
            out.append(Finding(
                NAME, f.rel, lineno,
                f"{NAME}:{f.rel}:direct-read:{key}",
                f"direct environment read of {key}; route it through "
                f"utils/_env.py (int_env/float_env/str_env) so the knob "
                f"surface stays greppable and malformed values fall "
                f"back silently"))

    # Docstring + README sync (repo-level facts; fixture runs pass a repo
    # without these files and skip the checks).
    config_rel = "distributed_bitcoinminer_tpu/utils/config.py"
    config = next((f for f in files if f.rel == config_rel), None)
    if config is not None and config.tree is not None and knobs:
        doc = ast.get_docstring(config.tree) or ""
        documented, orphans = _coverage(_doc_tokens(doc), set(knobs))
        for knob in sorted(set(knobs) - documented):
            out.append(Finding(
                NAME, config_rel, 1, f"{NAME}:config-doc:{knob}",
                f"knob {knob} (read in {knobs[knob]}) is not documented "
                f"in the utils/config.py module docstring"))
        for tok in orphans:
            out.append(Finding(
                NAME, config_rel, 1, f"{NAME}:config-orphan:{tok}",
                f"utils/config.py docstring documents {tok}, which "
                f"nothing reads — stale doc entry"))

    readme = os.path.join(repo, "README.md")
    if os.path.exists(readme) and knobs and config is not None:
        with open(readme, encoding="utf-8", errors="replace") as fh:
            text = fh.read()
        documented, orphans = _coverage(_doc_tokens(text), set(knobs))
        for knob in sorted(set(knobs) - documented):
            out.append(Finding(
                NAME, "README.md", 1, f"{NAME}:readme-doc:{knob}",
                f"knob {knob} (read in {knobs[knob]}) does not appear "
                f"anywhere in README.md — add it to a knob table"))
        for tok in orphans:
            out.append(Finding(
                NAME, "README.md", 1, f"{NAME}:readme-orphan:{tok}",
                f"README.md mentions {tok}, which nothing reads — "
                f"stale doc entry"))
    return out
