"""Analyzer: allocation churn on marked hot paths (hotpath-alloc).

The bug class (ISSUE 17): the transport datapath runs per packet —
millions of times a second at production rates — and its fast paths
(``lsp/wire.py``'s codec, the core's receive path) were specifically
rebuilt to avoid the json/base64 module round-trips and per-call dict
churn the stock codec pays. A later "harmless" edit that reintroduces
``json.dumps`` or a dict literal into one of those functions silently
costs the 2x the bench gate was built on — and nothing structural stops
it, because the slow idioms are perfectly correct.

Rule: inside any function whose ``def`` is marked with a
``# dbmlint: hotpath`` comment (on the def line or the line directly
above it), flag

- calls to ``json.dumps`` / ``json.loads``,
- calls into the ``base64`` module (``base64.b64encode`` etc. —
  ``binascii`` is the sanctioned zero-copy primitive),
- dict and list display literals (``{...}`` / ``[...]``), each an
  allocation per packet; comprehensions feeding them are flagged via
  the display node they build.

Scope: ``lsp/`` only — the marker is a per-function opt-in, so the
analyzer stays silent everywhere a function isn't explicitly declared
hot. Nested ``def``/``lambda`` bodies inside a marked function are NOT
exempt: code defined on the hot path runs on the hot path. Knob-off
fallback branches that delegate to the stock codec (``Message.to_json``
/ ``from_json``) are method calls, not module calls, so they pass —
by design, the slow path lives in ``message.py``, unmarked.

Suppress a deliberate exception with ``# dbmlint: ok[hotpath-alloc]``
and the argument why the allocation is off the per-packet path.
"""

from __future__ import annotations

import ast
import re
from typing import List

from .core import Finding, SourceFile, dotted, scope_map

NAME = "hotpath-alloc"

SCOPE_PREFIX = "distributed_bitcoinminer_tpu/lsp/"

_MARK_RE = re.compile(r"#\s*dbmlint:\s*hotpath\b")

#: Exact dotted call targets that are never acceptable per packet.
BANNED_DOTTED = {"json.dumps", "json.loads"}
#: Module prefix: any call into base64 (the C-level binascii functions
#: are the fast alternative the wire codec uses).
BANNED_PREFIX = "base64."


def _marked_functions(f: SourceFile) -> List[ast.AST]:
    """FunctionDefs whose header carries (or directly follows) the
    ``# dbmlint: hotpath`` marker."""
    marks = {i for i, ln in enumerate(f.lines, 1) if _MARK_RE.search(ln)}
    if not marks:
        return []
    out = []
    for node in ast.walk(f.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        header = min([node.lineno] +
                     [d.lineno for d in node.decorator_list])
        if header in marks or header - 1 in marks:
            out.append(node)
    return out


def _violations(fn: ast.AST):
    """(node, code, what) for each banned construct in the function."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in BANNED_DOTTED:
                yield node, name, f"call to {name}"
            elif name.startswith(BANNED_PREFIX):
                yield (node, name,
                       f"call to {name} (use binascii primitives)")
        elif isinstance(node, ast.Dict):
            yield node, "dict-literal", "dict literal"
        elif isinstance(node, ast.List):
            yield node, "list-literal", "list literal"


def analyze(files: List[SourceFile], repo: str) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if f.tree is None or not f.rel.startswith(SCOPE_PREFIX):
            continue
        marked = _marked_functions(f)
        if not marked:
            continue
        scopes = scope_map(f.tree)
        for fn in marked:
            fn_scope = scopes.get(id(fn)) or "<module>"
            seen_codes = {}
            for node, code, what in _violations(fn):
                # One finding per (function, construct kind): stable
                # identity without line numbers, and a second dict
                # literal in the same function is the same defect.
                n = seen_codes.setdefault(code, node)
                if n is not node:
                    continue
                out.append(Finding(
                    NAME, f.rel, node.lineno,
                    f"{NAME}:{f.rel}:{fn_scope}:{code}",
                    f"{what} inside hotpath-marked function "
                    f"{fn_scope}(): this code runs per packet — use the "
                    f"wire codec's allocation-free idioms, or move the "
                    f"work off the datapath"))
    return out
