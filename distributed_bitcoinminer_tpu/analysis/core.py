"""dbmlint core: source loading, finding identity, baseline mechanics.

Design constraints:

1. **No JAX, no imports of the analyzed code.** Everything is ``ast`` +
   text, so the tier-1 lint leg runs in seconds on a box where backend
   init takes minutes (or hangs — the exact failure mode analyzer #1
   exists to catch).
2. **Stable finding identity.** A finding's ``key`` carries no line
   number — baselines must survive unrelated edits above a finding —
   only (analyzer, file, enclosing symbol, short code). Line numbers
   ride along for display.
3. **Monotonic baseline.** New keys fail the run; keys that disappear
   are flushed by ``--update-baseline``; growing the baseline requires
   an explicit ``--force`` (the escape hatch for deliberately deferred
   findings, which should be rare — prefer a ``# dbmlint: ok[...]``
   suppression WITH a justification at the site).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: Suppression marker: ``# dbmlint: ok[analyzer] why`` (analyzer optional:
#: a bare ``# dbmlint: ok`` suppresses every analyzer on that line).
_OK_RE = re.compile(r"#\s*dbmlint:\s*ok(?:\[([a-z-]+)\])?")


@dataclass(frozen=True)
class Finding:
    analyzer: str
    path: str          # repo-relative, forward slashes
    line: int
    key: str           # stable identity (no line number)
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.analyzer}] {self.message}"


@dataclass
class SourceFile:
    """One analyzed file: text + (for .py) its parsed AST."""
    path: str                    # absolute
    rel: str                     # repo-relative, forward slashes
    text: str
    tree: Optional[ast.AST] = None
    _ok_lines: Optional[Dict[int, Optional[str]]] = field(
        default=None, repr=False)

    @property
    def lines(self) -> List[str]:
        return self.text.splitlines()

    def suppressed(self, line: int, analyzer: str) -> bool:
        """True when ``line`` carries a matching ``# dbmlint: ok`` marker."""
        if self._ok_lines is None:
            table: Dict[int, Optional[str]] = {}
            for i, ln in enumerate(self.lines, 1):
                m = _OK_RE.search(ln)
                if m:
                    table[i] = m.group(1)
            self._ok_lines = table
        if line not in self._ok_lines:
            return False
        which = self._ok_lines[line]
        return which is None or which == analyzer


PACKAGE = "distributed_bitcoinminer_tpu"

#: Files the knob analyzer scans beyond the package (readers of DBM_*
#: knobs that live at the repo level). Shell scripts are text-scanned.
EXTRA_PY = ("bench.py",)
EXTRA_DIRS = ("scripts",)
SHELL_GLOB_DIRS = ("scripts",)


def load_files(repo: str) -> List[SourceFile]:
    """Every analyzed source file, parsed. Syntax errors become findings
    at run time rather than crashes (a lint gate must report, not die)."""
    out: List[SourceFile] = []
    roots = [os.path.join(repo, PACKAGE)]
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(_load_py(repo, os.path.join(dirpath, name)))
    for rel in EXTRA_PY:
        path = os.path.join(repo, rel)
        if os.path.exists(path):
            out.append(_load_py(repo, path))
    for d in EXTRA_DIRS:
        droot = os.path.join(repo, d)
        if os.path.isdir(droot):
            for name in sorted(os.listdir(droot)):
                if name.endswith(".py"):
                    out.append(_load_py(repo, os.path.join(droot, name)))
    for d in SHELL_GLOB_DIRS:
        droot = os.path.join(repo, d)
        if os.path.isdir(droot):
            for name in sorted(os.listdir(droot)):
                if name.endswith(".sh"):
                    path = os.path.join(droot, name)
                    out.append(SourceFile(
                        path=path, rel=_rel(repo, path),
                        text=_read(path), tree=None))
    return out


def _rel(repo: str, path: str) -> str:
    return os.path.relpath(path, repo).replace(os.sep, "/")


def _read(path: str) -> str:
    with open(path, encoding="utf-8", errors="replace") as f:
        return f.read()


def _load_py(repo: str, path: str) -> SourceFile:
    text = _read(path)
    try:
        tree = ast.parse(text)
    except SyntaxError:
        tree = None
    return SourceFile(path=path, rel=_rel(repo, path), text=text, tree=tree)


def _analyzers():
    # Imported inside the function: the analyzer modules import
    # Finding/SourceFile from THIS module, so the catalog can only be
    # built once core's classes exist (the call at module bottom runs
    # after every definition above it).
    from . import (cardinality, hotpathalloc, jitstatic, knobs,
                   lockdiscipline, loopblock, threadstate)
    return {
        "loop-block": loopblock.analyze,
        "cardinality": cardinality.analyze,
        "knob-hygiene": knobs.analyze,
        "jit-static": jitstatic.analyze,
        "thread-state": threadstate.analyze,
        "lock-discipline": lockdiscipline.analyze,
        "hotpath-alloc": hotpathalloc.analyze,
    }


def run_repo(repo: str, only: Optional[str] = None) -> List[Finding]:
    """Run every analyzer (or ``only``) over the repo; suppressions and
    syntax-error findings applied here, sorted stably."""
    files = load_files(repo)
    findings: List[Finding] = []
    for f in files:
        if f.rel.endswith(".py") and f.tree is None:
            findings.append(Finding(
                "parse", f.rel, 1, f"parse:{f.rel}",
                "file does not parse; analyzers skipped it"))
    for name, fn in ANALYZERS.items():
        if only is not None and name != only:
            continue
        findings.extend(fn(files, repo))
    by_file = {f.rel: f for f in files}
    kept = []
    seen = set()
    for fd in findings:
        src = by_file.get(fd.path)
        if src is not None and src.suppressed(fd.line, fd.analyzer):
            continue
        if fd.key in seen:
            continue
        seen.add(fd.key)
        kept.append(fd)
    kept.sort(key=lambda fd: (fd.path, fd.line, fd.analyzer, fd.key))
    return kept


def run_source(analyzer: str, source: str,
               rel: str = "distributed_bitcoinminer_tpu/apps/_fixture.py",
               repo: str = ".") -> List[Finding]:
    """Run ONE analyzer over an in-memory snippet (fixture tests).

    ``rel`` places the snippet inside the tree (analyzers scope by
    path); suppression comments apply like anywhere else.
    """
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return [Finding("parse", rel, 1, f"parse:{rel}", "does not parse")]
    f = SourceFile(path=rel, rel=rel, text=source, tree=tree)
    found = ANALYZERS[analyzer]([f], repo)
    return [fd for fd in found if not f.suppressed(fd.line, fd.analyzer)]


# ----------------------------------------------------------------- baseline

def baseline_path(repo: str) -> str:
    return os.path.join(repo, PACKAGE, "analysis", "baseline.json")


def load_baseline(path: str) -> Dict[str, str]:
    """key -> message of the checked-in accepted findings."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return dict(data.get("findings", {}))


def save_baseline(path: str, findings: List[Finding]) -> None:
    payload = {
        "comment": "dbmlint accepted-findings baseline. New findings "
                   "FAIL the lint; this file may only shrink "
                   "(--update-baseline flushes fixed entries; growing "
                   "it needs --force).",
        "findings": {f.key: f.message for f in
                     sorted(findings, key=lambda f: f.key)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def compare(findings: List[Finding], baseline: Dict[str, str]):
    """(new, known, stale_keys): findings not in the baseline, findings
    covered by it, and baseline keys that no longer fire."""
    keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    known = [f for f in findings if f.key in baseline]
    stale = sorted(k for k in baseline if k not in keys)
    return new, known, stale


# ------------------------------------------------------------ AST helpers

def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target (``a.b.c`` -> "a.b.c")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(dotted(node.func) + "()")
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def scope_map(tree: ast.AST) -> Dict[int, str]:
    """``id(node) -> dotted enclosing scope`` ("Cls.meth"; "" = module).

    Finding keys for sites with no better identity (computed metric or
    knob names) key on the enclosing scope instead of the line number,
    honoring the stable-identity contract (design constraint #2)."""
    out: Dict[int, str] = {}

    def visit(node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            s = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                s = f"{scope}.{child.name}" if scope else child.name
            out[id(child)] = s
            visit(child, s)

    visit(tree, "")
    return out


#: Analyzer name -> callable(files, repo) — the public catalog.
ANALYZERS = _analyzers()
