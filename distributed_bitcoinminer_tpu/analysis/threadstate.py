"""Analyzer: cross-thread attribute ownership (thread-state).

The bug class: the scheduler and miner are asyncio actors whose compute
hops to worker threads (``asyncio.to_thread``, executors). An attribute
mutated from BOTH domains is a data race unless something serializes it
— and "something" must be on record, or the next PR breaks it silently.

Scope: the classes named in :data:`CLASSES` (the stack's stateful
actors). Per class:

1. seed the THREAD side with every method handed to a thread dispatcher
   (``asyncio.to_thread(self.m, ...)``, ``executor.submit(self.m)``,
   ``Thread(target=self.m)``, ``run_in_executor(None, self.m)``) and
   close over same-class ``self.m()`` calls;
2. collect per-method ``self.<attr>`` WRITES (assignment, aug-assign,
   subscript stores, and mutating method calls — append/pop/update/…)
   and READS; ``__init__`` is construction-time and belongs to neither
   domain;
3. an attribute written on the thread side and touched on the loop side
   (or vice versa) must either appear in the class's ``THREAD_SHARED``
   ownership table (``{"attr": "why this is serialized"}`` — the
   machine-checked design record) or be accessed under a ``with
   self.<...lock...>:`` block.

The runtime complement (``utils/sanitize.py``, ``DBM_SANITIZE=1``)
asserts the same ownership dynamically; this analyzer keeps the table
honest at review time.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .core import Finding, SourceFile, dotted

NAME = "thread-state"

#: class name -> file suffix it lives in (scope filter).
CLASSES = {
    "Scheduler": "apps/scheduler.py",
    "QosPlane": "apps/qos.py",
    "MinerWorker": "apps/miner.py",
}

THREAD_DISPATCHERS = ("to_thread", "submit", "run_in_executor")
MUTATORS = {
    "append", "extend", "insert", "pop", "popitem", "remove", "clear",
    "update", "setdefault", "add", "discard", "move_to_end", "appendleft",
    "popleft", "inc", "observe",
}


def _self_method_ref(node: ast.expr):
    """'m' when ``node`` is ``self.m``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _thread_seeds(cls: ast.ClassDef) -> Set[str]:
    seeds: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func)
        if not fname.split(".")[-1] in THREAD_DISPATCHERS and \
                not fname.endswith("Thread"):
            continue
        candidates = list(node.args)
        for kw in node.keywords:
            if kw.arg == "target":
                candidates.append(kw.value)
        for arg in candidates:
            m = _self_method_ref(arg)
            if m is not None:
                seeds.add(m)
    return seeds


def _method_calls(fn: ast.AST) -> Set[str]:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            m = _self_method_ref(node.func)
            if m is not None:
                out.add(m)
    return out


def _attr_accesses(fn: ast.AST):
    """(writes, reads) of ``self.<attr>`` in ``fn``; a write via a
    mutating method call or subscript store counts as a write. Accesses
    inside ``with self.<...lock...>`` blocks are excluded (serialized)."""
    writes: Dict[str, int] = {}
    reads: Dict[str, int] = {}

    def locked(with_node: ast.With) -> bool:
        for item in with_node.items:
            name = dotted(item.context_expr).lower()
            if "lock" in name:
                return True
        return False

    def visit(node):
        if isinstance(node, ast.With) and locked(node):
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                base = tgt
                if isinstance(base, ast.Subscript):
                    base = base.value
                attr = _self_method_ref(base)
                if attr is not None:
                    writes[attr] = getattr(tgt, "lineno", node.lineno)
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in MUTATORS:
                attr = _self_method_ref(func.value)
                if attr is not None:
                    writes[attr] = node.lineno
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and isinstance(node.ctx, ast.Load):
            reads.setdefault(node.attr, node.lineno)
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in getattr(fn, "body", []):
        visit(stmt)
    return writes, reads


def _ownership_table(cls: ast.ClassDef) -> Set[str]:
    """Keys of a class-level ``THREAD_SHARED = {...}`` dict."""
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "THREAD_SHARED" \
                        and isinstance(node.value, ast.Dict):
                    return {k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)}
    return set()


def analyze(files: List[SourceFile], repo: str) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if f.tree is None:
            continue
        for cls in ast.walk(f.tree):
            if not isinstance(cls, ast.ClassDef) or \
                    cls.name not in CLASSES:
                continue
            if not f.rel.endswith(CLASSES[cls.name]) and \
                    "fixture" not in f.rel:
                continue
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            # Transitive closure of thread-side methods.
            thread_side = set()
            frontier = {m for m in _thread_seeds(cls) if m in methods}
            while frontier:
                m = frontier.pop()
                if m in thread_side:
                    continue
                thread_side.add(m)
                frontier |= {c for c in _method_calls(methods[m])
                             if c in methods and c not in thread_side}
            if not thread_side:
                continue
            loop_side = {m for m in methods
                         if m not in thread_side and m != "__init__"}
            table = _ownership_table(cls)
            t_writes: Dict[str, int] = {}
            t_reads: Dict[str, int] = {}
            l_writes: Dict[str, int] = {}
            l_reads: Dict[str, int] = {}
            for m in thread_side:
                w, r = _attr_accesses(methods[m])
                for a, ln in w.items():
                    t_writes.setdefault(a, ln)
                for a, ln in r.items():
                    t_reads.setdefault(a, ln)
            for m in loop_side:
                w, r = _attr_accesses(methods[m])
                for a, ln in w.items():
                    l_writes.setdefault(a, ln)
                for a, ln in r.items():
                    l_reads.setdefault(a, ln)
            # A race needs a WRITE on one side and any touch on the
            # other: thread-written + loop-touched, or loop-written +
            # thread-read (the "vice versa" direction — a torn read off
            # the owning thread is just as much a race).
            shared = {}
            for attr, ln in t_writes.items():
                if attr in l_writes or attr in l_reads:
                    shared[attr] = ln
            for attr, ln in t_reads.items():
                if attr in l_writes:
                    shared.setdefault(attr, ln)
            for attr, ln in sorted(shared.items()):
                if attr in table:
                    continue
                out.append(Finding(
                    NAME, f.rel, ln,
                    f"{NAME}:{f.rel}:{cls.name}:{attr}",
                    f"{cls.name}.{attr} is touched from both worker-"
                    f"thread and event-loop method(s) with a write on "
                    f"at least one side; declare it in "
                    f"{cls.name}.THREAD_SHARED with the serialization "
                    f"argument, or guard both sides with a lock"))
    return out
