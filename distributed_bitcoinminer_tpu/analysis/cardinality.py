"""Analyzer: metric label cardinality (cardinality).

The bug class (PR 3 review): a label value derived from an unbounded id
space — conn ids under reconnect churn, job ids, tenant ids — grows one
series per entity until the registry's ``max_series`` bound collapses
REAL traffic into the overflow series. The registry bounds memory, but a
site that churns through the bound is still broken observability.

Rule, per call of ``<registry>.counter/gauge/histogram/ewma`` with label
kwargs (every kwarg except the metric-shape ones ``tau_s``/``buckets``):

- a **literal** label value is bounded by construction — fine;
- a value that is the target of an enclosing comprehension iterating a
  **literal tuple/list** is bounded by that tuple — fine (the
  ``{k: reg.counter("name", outcome=k) for k in ("ok", "exhausted")}``
  hoisted-handle idiom);
- a **dynamic** label value makes the site a per-entity series: the SAME
  module must also contain a ``.remove("<metric>", ...)`` retirement
  call for that metric name (the conn-drop / tenant-GC path), or the
  site needs a ``# dbmlint: ok[cardinality] <why bounded>`` suppression
  stating the boundedness argument (e.g. backoff levels are capped by
  the transport's max-backoff knob).

The metric NAME must be a string literal — a computed name defeats both
this check and snapshot diffing, and is flagged outright.

Trace-track extension (ISSUE 10): the Perfetto exporter's per-miner /
per-tenant tracks (``utils/trace.TrackSet``) are labeled entities with
the exact same churn failure mode, so ``.track("name", miner=conn_id)``
sites obey the identical rule — a dynamic label needs a same-module
``.retire("name", ...)`` retirement path (miner drop / tenant GC) or a
suppression with the boundedness argument.

Rollup-source extension (ISSUE 18): the cluster rollup plane keeps
per-source series under a ``proc`` label (one value per publishing
process — unbounded under miner-agent churn, since agents key by pid).
``.proc_series("family", proc=key)`` sites (``apps/rollup.SourceSet``)
obey the same rule with their own retirement method: the module must
also ``.retire_proc("family", ...)`` where a source dies (fence,
long-stale expiry), so churned publishers cycle bound slots instead of
exhausting them.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, SourceFile, scope_map, str_const

NAME = "cardinality"

SCOPE_PREFIX = "distributed_bitcoinminer_tpu/"
REGISTRY_METHODS = {"counter", "gauge", "histogram", "ewma", "track",
                    "proc_series"}
SHAPE_KWARGS = {"tau_s", "buckets"}
#: Which retirement method covers which registration method: metric
#: series retire via ``Registry.remove``, export tracks (ISSUE 10) via
#: ``TrackSet.retire``, rollup per-source series (ISSUE 18) via
#: ``SourceSet.retire_proc`` — a ``.remove`` cannot vouch for a
#: ``.track`` or ``.proc_series`` site or vice versa.
RETIREMENT_FOR = {"counter": "remove", "gauge": "remove",
                  "histogram": "remove", "ewma": "remove",
                  "track": "retire", "proc_series": "retire_proc"}


def _removed_names(tree: ast.AST) -> dict:
    """``retire-method -> {metric names}`` passed to any
    ``.remove("name", ...)`` / ``.retire("name", ...)`` in the file."""
    out: dict = {m: set() for m in set(RETIREMENT_FOR.values())}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in out and node.args:
            name = str_const(node.args[0])
            if name is not None:
                out[node.func.attr].add(name)
    return out


def _comprehension_bounded(tree: ast.AST):
    """call-node id -> names bounded by a literal-iterating enclosing
    comprehension (``for k in ("a", "b")`` makes ``k`` a bounded label
    inside that comprehension's body)."""
    out = {}
    comps = [n for n in ast.walk(tree)
             if isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp))]
    for comp in comps:
        bounded = set()
        for gen in comp.generators:
            if isinstance(gen.iter, (ast.Tuple, ast.List)) and \
                    all(isinstance(el, ast.Constant)
                        for el in gen.iter.elts) and \
                    isinstance(gen.target, ast.Name):
                bounded.add(gen.target.id)
        if not bounded:
            continue
        for sub in ast.walk(comp):
            if isinstance(sub, ast.Call):
                out.setdefault(id(sub), set()).update(bounded)
    return out


def analyze(files: List[SourceFile], repo: str) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if f.tree is None or not f.rel.startswith(SCOPE_PREFIX):
            continue
        removed = _removed_names(f.tree)
        comp_bounded = _comprehension_bounded(f.tree)
        scopes = None
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in REGISTRY_METHODS):
                continue
            labels = [kw for kw in node.keywords
                      if kw.arg is not None and kw.arg not in SHAPE_KWARGS]
            if not labels:
                continue
            metric = str_const(node.args[0]) if node.args else None
            if metric is None:
                if scopes is None:
                    scopes = scope_map(f.tree)
                scope = scopes.get(id(node)) or "<module>"
                out.append(Finding(
                    NAME, f.rel, node.lineno,
                    f"{NAME}:{f.rel}:computed-name:"
                    f"{node.func.attr}:{scope}",
                    f"labeled .{node.func.attr}() call with a computed "
                    f"metric name; name must be a string literal so the "
                    f"retirement path (and snapshot diffs) can be "
                    f"checked"))
                continue
            bounded_here = comp_bounded.get(id(node), set())
            dynamic = [kw.arg for kw in labels
                       if str_const(kw.value) is None
                       and not (isinstance(kw.value, ast.Name)
                                and kw.value.id in bounded_here)]
            if not dynamic:
                continue
            retire_via = RETIREMENT_FOR[node.func.attr]
            if metric in removed[retire_via]:
                continue   # per-entity series with a retirement path
            out.append(Finding(
                NAME, f.rel, node.lineno,
                f"{NAME}:{f.rel}:{metric}:{'/'.join(sorted(dynamic))}",
                f"metric {metric!r} takes dynamic label(s) "
                f"{sorted(dynamic)} with no .{retire_via}({metric!r}, "
                f"...) retirement path in this module — entity churn "
                f"will exhaust the series bound; retire the series "
                f"where the entity dies, or suppress with the "
                f"boundedness argument"))
    return out
