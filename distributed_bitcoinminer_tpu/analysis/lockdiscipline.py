"""Analyzer: lock discipline in the asyncio actors (lock-discipline).

Two bug shapes, both invisible to tests until the scheduler is under
real concurrency (which is exactly when they fire — dbmcheck's
deterministic explorer motivates pinning them statically too):

1. **A synchronous (threading) lock held across an ``await``.** A
   coroutine that does ``with self._lock: ... await ...`` parks while
   HOLDING the lock; any worker thread that then touches the same lock
   blocks — and if that worker is the one whose completion the
   coroutine awaits, the process deadlocks. (``async with`` over an
   asyncio lock is the correct shape: it suspends, never blocks the
   loop.) Any ``with``-statement whose context expression looks like a
   lock and whose DIRECT body contains an ``await`` / ``async for`` /
   ``async with`` is flagged.

2. **A blocking call under ANY lock.** Whether the lock is a threading
   or an asyncio one, running the loop-block analyzer's blocking
   surface (subprocess, JAX forcing, searcher construction/scan,
   ``time.sleep``) while holding it turns one slow call into a convoy:
   every other acquirer — event loop or worker thread — queues behind
   minutes of backend init. Flagged in both ``with`` and ``async
   with`` bodies.

Scope: ``apps/`` and ``lsp/`` (the asyncio actors), like loop-block.

What counts as a lock (curated, AST-level): a context expression whose
dotted name's last segment IS ``lock``/``mutex``/``cond``/``condition``
or ends in the ``_``-separated word (``state_lock`` yes,
``datablock`` no; leading underscores stripped; case-insensitive;
with or without a trailing ``()`` acquire-style call), or any name
bound — anywhere in the same file — from ``threading.Lock()`` /
``RLock`` / ``Condition`` / ``Semaphore`` / ``BoundedSemaphore`` or
their ``asyncio`` analogs.
Suppressions (``# dbmlint: ok[lock-discipline] why``) must state the
boundedness argument — why the critical section cannot convoy.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, SourceFile, dotted, scope_map
from .loopblock import _blocking_reason

NAME = "lock-discipline"

SCOPE_PREFIXES = (
    "distributed_bitcoinminer_tpu/apps/",
    "distributed_bitcoinminer_tpu/lsp/",
)

#: Constructor names whose assignment target becomes a known lock.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

_NAME_HINTS = ("lock", "mutex", "cond", "condition")


def _lock_names(tree: ast.AST) -> Set[str]:
    """Dotted names assigned from a lock constructor anywhere in the
    file (``self._m = threading.Lock()`` -> "self._m")."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign):   # x: Lock = Lock()
            targets = [node.target]
            value = node.value
        else:
            continue
        if not isinstance(value, ast.Call):
            continue
        ctor = dotted(value.func)
        if ctor.split(".")[-1] not in _LOCK_CTORS:
            continue
        for target in targets:
            if isinstance(target, (ast.Name, ast.Attribute)):
                out.add(dotted(target))
    return out


def _is_lock_expr(expr: ast.AST, known: Set[str]) -> bool:
    """Heuristic: the context expression of a with-statement is a lock."""
    if isinstance(expr, ast.Call):
        # `with x.acquire():`-style or `with Lock():` inline.
        inner = dotted(expr.func)
        if inner.split(".")[-1] in _LOCK_CTORS:
            return True
        expr = expr.func
    name = dotted(expr)
    if name in known:
        return True
    # Word-boundary matching only: `state_lock`, `_lock`, `cond` — NOT
    # `datablock`/`prev_block` (a bare endswith would class any
    # identifier merely ending in "lock" as a lock and flood the
    # analyzer with false findings).
    last = name.split(".")[-1].lower().lstrip("_")
    return any(last == h or last.endswith("_" + h) for h in _NAME_HINTS)


def _direct_body(nodes):
    """Walk statements without descending into nested function/lambda
    definitions (their bodies execute elsewhere)."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def _scan_with(node, is_async: bool, known: Set[str], f: SourceFile,
               scope: str, out: List[Finding]) -> None:
    lock_items = [item for item in node.items
                  if _is_lock_expr(item.context_expr, known)]
    if not lock_items:
        return
    lock_name = dotted(lock_items[0].context_expr)
    for sub in _direct_body(node.body):
        if not is_async and isinstance(
                sub, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            out.append(Finding(
                NAME, f.rel, sub.lineno,
                f"{NAME}:{f.rel}:{scope}:{lock_name}:await",
                f"sync lock {lock_name} held across an await in "
                f"{scope}: the coroutine parks holding it and any "
                f"worker thread acquiring it blocks (deadlock shape) "
                f"— use an asyncio lock (async with) or release "
                f"before awaiting"))
        if isinstance(sub, ast.Call):
            reason = _blocking_reason(sub)
            if reason is not None:
                out.append(Finding(
                    NAME, f.rel, sub.lineno,
                    f"{NAME}:{f.rel}:{scope}:{lock_name}:"
                    f"{dotted(sub.func)}",
                    f"blocking {reason} under lock {lock_name} in "
                    f"{scope}: one slow call convoys every other "
                    f"acquirer — move it outside the critical "
                    f"section"))


def analyze(files: List[SourceFile], repo: str) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if f.tree is None or not f.rel.startswith(SCOPE_PREFIXES):
            continue
        known = _lock_names(f.tree)
        scopes = scope_map(f.tree)
        for node in ast.walk(f.tree):
            if isinstance(node, ast.With):
                _scan_with(node, False, known, f,
                           scopes.get(id(node), "<module>"), out)
            elif isinstance(node, ast.AsyncWith):
                _scan_with(node, True, known, f,
                           scopes.get(id(node), "<module>"), out)
    return out
