"""dbmcheck exploration engine: random walks, bounded DFS, shrinking.

Three exploration modes over one scenario:

- **Random walk** (``run_walks``): N seeds, each a fully deterministic
  (population, schedule) sample — the workhorse; distinct schedules are
  counted by hashing the executed step-label sequence.
- **Bounded exhaustive DFS** (``run_dfs``): systematic enumeration of
  the choice tree for SMALL scopes — the scenario's constants are
  pinned to one seed, the first ``depth`` choice points branch over
  every alternative (beyond them the FIFO default 0), and prefixes are
  re-executed from scratch (schedules are cheap and deterministic, so
  replay-based DFS needs no forking).
- **Replay** (``replay``): re-execute one SEED SPEC exactly — either a
  random-walk seed (``rw:<seed>``) or a shrunk explicit choice trace
  (``tr:<seed>:<c.c.c>``). The spec a failure prints IS its repro.

**Shrinking**: a failing random walk is first re-run through its
recorded choice trace (same schedule, explicit form), then minimized:
every choice is greedily replaced by the FIFO default 0 and the trace
truncated to the last non-default choice — each candidate re-executed,
kept only if it still fails. The result is the minimal-preemption
repro trace, the loom/Shuttle shape of "the race in three context
switches".
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

from .scenario import ScheduleResult, execute
from .scenarios import ALL

__all__ = ["run_walks", "run_dfs", "replay", "shrink", "format_spec",
           "parse_spec", "ExploreStats"]


class ExploreStats:
    """Per-scenario exploration tally."""

    def __init__(self, scenario: str):
        self.scenario = scenario
        self.explored = 0
        self.distinct: set = set()
        self.failures: List[ScheduleResult] = []
        self.elapsed_s = 0.0

    def record(self, result: ScheduleResult) -> None:
        self.explored += 1
        self.distinct.add(result.schedule_key())
        if result.failed:
            self.failures.append(result)

    def summary(self) -> dict:
        return {
            "scenario": self.scenario,
            "explored": self.explored,
            "distinct": len(self.distinct),
            "violations": len(self.failures),
            "elapsed_s": round(self.elapsed_s, 2),
        }


def _usage_error(msg: str):
    """Usage-shaped failure: exit 2, never 1 (the CLI contract reserves
    1 for a real invariant violation — a typo'd spec must not page)."""
    print(msg, file=sys.stderr)
    raise SystemExit(2)


def _scenario(name: str):
    try:
        return ALL[name]()
    except KeyError:
        _usage_error(f"unknown scenario {name!r}; known: {sorted(ALL)}")


def format_spec(result: ScheduleResult, shrunk: bool = False) -> str:
    """The replayable seed spec of one executed schedule. A result that
    was produced from an explicit choice trace (DFS, replay, shrink)
    always formats as ``tr:`` — its ``rw:`` seed would replay a
    DIFFERENT (random-walk) schedule."""
    if shrunk or result.explicit:
        choices = ".".join(str(c) for c in result.choices)
        return f"{result.scenario}:tr:{result.seed}:{choices}"
    return f"{result.scenario}:rw:{result.seed}"


def parse_spec(spec: str):
    """``(scenario, seed, choices_or_None)`` from a printed seed spec."""
    parts = spec.split(":")
    if len(parts) >= 3 and parts[1] == "rw":
        return parts[0], int(parts[2]), None
    if len(parts) >= 3 and parts[1] == "tr":
        choices = []
        if len(parts) > 3 and parts[3]:
            choices = [int(c) for c in parts[3].split(".")]
        return parts[0], int(parts[2]), choices
    _usage_error(f"malformed seed spec {spec!r} (want "
                 f"scenario:rw:<seed> or scenario:tr:<seed>:<c.c.c>)")


def replay(spec: str) -> ScheduleResult:
    name, seed, choices = parse_spec(spec)
    return execute(_scenario(name), seed, choices=choices)


def run_walks(name: str, seeds: int, seed0: int = 0,
              budget_s: Optional[float] = None,
              stats: Optional[ExploreStats] = None) -> ExploreStats:
    """``seeds`` random-walk schedules of one scenario (stopping early
    on budget exhaustion — the tier-1 leg is wall-bounded)."""
    st = stats if stats is not None else ExploreStats(name)
    t0 = time.perf_counter()
    for i in range(seeds):
        if budget_s is not None and time.perf_counter() - t0 > budget_s:
            break
        st.record(execute(_scenario(name), seed0 + i))
    st.elapsed_s += time.perf_counter() - t0
    return st


def run_dfs(name: str, seed: int = 0, depth: int = 6, limit: int = 200,
            budget_s: Optional[float] = None,
            stats: Optional[ExploreStats] = None) -> ExploreStats:
    """Bounded exhaustive DFS over the first ``depth`` choice points.

    Classic replay-based state-space walk: run a prefix of forced
    choices (0 beyond it), read how many alternatives each choice point
    actually had, and push every unexplored sibling of the first
    ``depth`` points. ``limit`` caps total schedules."""
    st = stats if stats is not None else ExploreStats(f"{name}[dfs]")
    t0 = time.perf_counter()
    seen_prefix: set = set()
    stack: List[List[int]] = [[]]
    ran = 0
    while stack and ran < limit:
        if budget_s is not None and time.perf_counter() - t0 > budget_s:
            break
        prefix = stack.pop()
        key = tuple(prefix)
        if key in seen_prefix:
            continue
        seen_prefix.add(key)
        result = execute(_scenario(name), seed, choices=prefix)
        ran += 1
        st.record(result)
        # Expand: for each choice point within bounds, the siblings of
        # the choice actually taken. Later points first (LIFO -> DFS).
        for pos in range(min(len(result.trace), depth) - 1, -1, -1):
            n_alt, taken = result.trace[pos]
            if pos < len(prefix):
                continue   # already forced; siblings queued elsewhere
            for alt in range(n_alt):
                if alt != taken:
                    stack.append(result.choices[:pos] + [alt])
    st.elapsed_s += time.perf_counter() - t0
    return st


def shrink(result: ScheduleResult, max_runs: int = 400) -> ScheduleResult:
    """Minimal-preemption repro of a failing schedule.

    Greedy: replay with the explicit trace; then left-to-right set each
    non-default choice to 0, keeping the change iff the violation
    persists; finally truncate trailing defaults (TracePicker pads with
    0). Every candidate is a full deterministic re-execution."""
    scen, seed = result.scenario, result.seed
    best = execute(_scenario(scen), seed, choices=result.choices)
    if not best.failed:
        # The trace replay no longer fails (should not happen — same
        # choices, same rng): fall back to the original result.
        return result
    runs = 0
    changed = True
    while changed and runs < max_runs:
        changed = False
        pos = 0
        # Bound re-read from the CURRENT best every iteration: a kept
        # candidate may have fewer choice points than the trace the
        # pass started from (zeroing one choice can cut whole task
        # chains), so a range frozen on the original length would walk
        # off the shorter trace.
        while pos < len(best.choices) and runs < max_runs:
            choices = list(best.choices)
            if choices[pos] != 0:
                cand = choices[:pos] + [0] + choices[pos + 1:]
                trial = execute(_scenario(scen), seed, choices=cand)
                runs += 1
                if trial.failed:
                    best = trial
                    changed = True
            pos += 1
    # Truncate trailing zeros: TracePicker's fallback supplies them.
    choices = list(best.choices)
    while choices and choices[-1] == 0:
        choices.pop()
    trial = execute(_scenario(scen), seed, choices=choices)
    if trial.failed:
        best = trial
        best.choices = choices   # canonical short form
    return best


def explore_scenarios(names: List[str], seeds: int, seed0: int,
                      budget_s: float, dfs_limit: int = 0,
                      dfs_depth: int = 6) -> Dict[str, ExploreStats]:
    """The tier-1 composition: random walks (plus an optional DFS pass)
    over each scenario, sharing one wall budget."""
    t0 = time.perf_counter()
    out: Dict[str, ExploreStats] = {}
    for name in names:
        remaining = budget_s - (time.perf_counter() - t0)
        if remaining <= 0:
            out[name] = ExploreStats(name)
            continue
        st = run_walks(name, seeds, seed0=seed0,
                       budget_s=remaining * 0.85 if dfs_limit else
                       remaining)
        if dfs_limit > 0:
            remaining = budget_s - (time.perf_counter() - t0)
            if remaining > 0:
                run_dfs(name, seed=seed0, depth=dfs_depth,
                        limit=dfs_limit, budget_s=remaining, stats=st)
        out[name] = st
    return out
