"""dbmcheck scenario catalog (ISSUE 8).

Four REAL control-plane scenarios — the tier-1 leg explores these — and
two KNOWN-BAD fixtures (deliberately racy mini-schedulers the explorer
must be able to catch; they pin the checker's own sensitivity and are
never part of the gate's clean-run requirement).

Every scenario draws its constants (ranges, delays, which miner wedges)
from the seed's ``Random`` stream, so each seed is both a schedule AND a
slightly different population — a random walk covers timing races the
pure step-ordering branching cannot reach (e.g. a lease expiring one
tick before vs after a Result lands).

Run-time randomness (per-chunk delays, fake compute costs) is drawn
from PER-ACTOR child streams forked off the scenario stream at build
time (:func:`_fork`), never from the shared stream: a shared stream's
draw ORDER would follow the explored schedule, so a shrink/DFS
perturbation of one choice would silently re-roll every later actor's
timing — conflating ordering changes with population changes. With
per-actor streams, an actor's k-th draw depends only on its own k,
which is what makes shrinking converge on the ordering change alone.
(Exact-spec replay is bit-exact either way.)
"""

from __future__ import annotations

import asyncio
import os
import random

from ...apps.scheduler import Scheduler
from ...utils._env import str_env
from ...bitcoin.hash import hash_op
from ...bitcoin.message import (Message, MsgType, new_join, new_request,
                                new_result)
from ...lsp.errors import LspError
from ...utils.config import (CacheParams, CoalesceParams, LeaseParams,
                             QosParams, StripeParams)
from .scenario import Ctx, Req, Scenario, oracle_min

__all__ = ["SCENARIOS", "FIXTURES", "ALL"]

_DATA = ("alpha", "bravo", "charlie", "delta")


def _fork(rng: random.Random) -> random.Random:
    """A child stream forked from ``rng`` at build time (see module
    docstring: run-time draws must come from per-actor streams)."""
    return random.Random(rng.getrandbits(64))


def _make_sched(ctx: Ctx, lease: LeaseParams, qos: QosParams,
                stripe: StripeParams = None,
                coalesce: CoalesceParams = None,
                adapt=None, verify=None, audit_rng=None) -> Scheduler:
    # clock=ctx.loop.time: the admission buckets — and the ISSUE 13
    # adapt controllers — must tick on the VIRTUAL clock (they capture
    # their clock at construction, before the time.monotonic patch
    # could reach them). ``verify``/``audit_rng`` (ISSUE 16): the
    # byzantine family turns the verification tier on with a seeded
    # audit stream (the _fork discipline — audit coin flips and
    # subwindow draws come from a per-scheduler child stream, never
    # global RNG state); everyone else runs the stock default.
    from ...utils.config import AdaptParams, VerifyParams
    sched = Scheduler(
        ctx.server, lease=lease, cache=CacheParams(),
        stripe=stripe if stripe is not None
        else StripeParams(enabled=False), qos=qos,
        coalesce=coalesce if coalesce is not None
        else CoalesceParams(enabled=False),
        adapt=adapt if adapt is not None
        else AdaptParams(enabled=False), clock=ctx.loop.time,
        verify=verify if verify is not None else VerifyParams(),
        audit_rng=audit_rng)
    ctx.sched = sched
    ctx.spawn(sched.run())
    return sched


async def _warm_rates(ctx: Ctx, n_miners: int, rate: float) -> None:
    """Wait for every miner to join, then pin the throughput EWMAs —
    the striping/QoS-chunking planes need a warm pool, and warming via
    real traffic would couple the scenario's shape to its schedule."""
    while ctx.sched is None or len(ctx.sched.miners) < n_miners:
        await asyncio.sleep(0.01)
    for m in ctx.sched.miners:
        m.rate_ewma = rate
    ctx.sched._pool_rate = rate


# ------------------------------------------------------------ lease_reissue

class LeaseReissue(Scenario):
    """A wedged miner's lease blows mid-request; the chunk is
    speculatively re-issued and first-Result-wins dedup must keep the
    merge exactly-once — raced against parked-chunk recovery, client
    drops, and quarantine. Stock FIFO path (QoS off), so the reference
    one-in-flight reply order is asserted globally."""

    name = "lease_reissue"

    def build(self, ctx: Ctx) -> None:
        rng = ctx.rng
        _make_sched(ctx, lease=LeaseParams(
            grace_s=0.4, factor=4.0, floor_s=0.3, tick_s=0.05,
            quarantine_after=rng.choice((1, 2)), ewma_alpha=0.3,
            queue_alarm_s=30.0), qos=QosParams(enabled=False))
        # One miner may misbehave: WEDGE (reads forever, never answers
        # — pure lease blow) or go SLOW (answers after its lease blew —
        # the first-Result-wins dedup race, dup_results > 0).
        bad = rng.choice((None, 0, 1, 2))
        slow = rng.random() < 0.5
        for i in range(3):
            kw = {}
            mrng = _fork(rng)
            if bad == i and not slow:
                kw["wedge_after"] = rng.choice((0, 1))
            if bad == i and slow:
                kw["delay_fn"] = \
                    lambda size, r=mrng: r.uniform(0.8, 2.0)
            else:
                kw.setdefault(
                    "delay_fn",
                    lambda size, r=mrng: r.uniform(0.02, 0.25))
            ctx.add_miner(f"m{i}", **kw)
        reqs = []
        for j in range(rng.choice((2, 3))):
            # Unique cache keys (the "#j" suffix): a duplicate would
            # legitimately replay from the ResultCache at arrival and
            # overtake the FIFO, which the global-FIFO check below
            # deliberately does not model.
            reqs.append(Req(f"{rng.choice(_DATA)}#{j}", 0,
                            rng.choice((59, 119, 199)),
                            pre_delay=rng.uniform(0.0, 0.3)))
        ctx.add_client("c0", reqs)
        if rng.random() < 0.5:
            # A second client that drops right after sending: the
            # cancel path must free the pool without corrupting c0.
            ctx.add_client("c1", [Req(f"{rng.choice(_DATA)}#x", 0, 99,
                                      pre_delay=rng.uniform(0.0, 0.4),
                                      close_after=True)])

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_global_fifo(ctx)
        out += self.check_accounting(ctx)
        return out


# ---------------------------------------------------------------- qos_shed

class QosShed(Scenario):
    """The fair-share plane under contention: a chunked elephant
    interleaving with mice from two other tenants, token-bucket
    admission (virtual-clock bucket) and oldest-first overload shedding
    both able to fire. Every surviving request must merge exactly-once
    oracle-exact in per-tenant order; shed tenants must see their conn
    die and nothing else corrupt; the grant accounting must return to
    zero."""

    name = "qos_shed"

    def build(self, ctx: Ctx) -> None:
        rng = ctx.rng
        sched = _make_sched(ctx, lease=LeaseParams(
            grace_s=5.0, factor=4.0, floor_s=2.0, tick_s=0.1,
            queue_alarm_s=30.0), qos=QosParams(
            enabled=True, chunk_s=0.2, max_chunks=32, depth=2,
            wholesale_s=0.5, max_queued=rng.choice((3, 4)),
            rate=rng.choice((0.0, 0.5)), burst=2.0))
        for i in range(2):
            ctx.add_miner(
                f"m{i}",
                delay_fn=lambda size, r=_fork(rng):
                    size / 1000.0 * r.uniform(0.8, 1.2))
        ctx.spawn(_warm_rates(ctx, 2, 1000.0))
        # Tenant 1: the elephant (estimated 1s > wholesale_s 0.5 at the
        # warmed 2x1000 nps pool -> chunked activation, ~10 chunks).
        ctx.add_client("elephant", [
            Req(rng.choice(_DATA), 0, 1999, pre_delay=0.5)])
        # Tenants 2 + 3: mice trains; pre-delays land them against the
        # elephant's grant stream (and sometimes over the admission
        # burst of 2, or the max_queued bound).
        for t, n in (("mice_a", 3), ("mice_b", 2)):
            reqs = [Req(rng.choice(_DATA), 0, rng.choice((99, 149)),
                        pre_delay=0.5 + rng.uniform(0.0, 1.5))
                    for _ in range(n)]
            ctx.add_client(t, reqs)
        self.sched = sched

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        # Shed bookkeeping: a script that saw its conn die must be
        # matched by at least one counted shed (and vice versa a shed
        # count with no dead conn would mean we closed nobody).
        shed_conns = sum(1 for c in ctx.clients if c.shed)
        shed_count = ctx.sched.stats["qos_shed"]
        if shed_conns and not shed_count:
            out.append(f"{shed_conns} client conn(s) died without any "
                       f"counted QoS shed")
        if shed_count and not shed_conns and \
                not any(c.dropped for c in ctx.clients):
            out.append(f"qos_shed counted {shed_count} but no client "
                       f"conn died")
        return out


# ------------------------------------------------------- pipelined_dispatch

class _FakeSearcher:
    """Two-phase (dispatch/finalize) oracle searcher for the REAL
    MinerWorker pipeline: compute cost is charged to the virtual clock
    inside the executor step (the loop thread is blocked, so the jump
    is atomic), sized so the scheduler's stripe planner produces
    multi-chunk shares."""

    def __init__(self, data: str, ctx: Ctx, rng: random.Random,
                 rate: float = 4000.0):
        self.data = data
        self.ctx = ctx
        self.rng = rng          # per-searcher stream (module docstring)
        self.rate = rate

    def _charge(self, size: int, frac: float = 1.0) -> None:
        self.ctx.loop.advance(
            size / self.rate * frac * self.rng.uniform(0.7, 1.3))

    def search(self, lower: int, upper: int):
        self._charge(upper - lower + 1)
        return oracle_min(self.data, lower, upper)

    def search_until(self, lower: int, upper: int, target: int):
        from .scenario import oracle_until
        self._charge(upper - lower + 1)
        return oracle_until(self.data, lower, upper, target)

    def dispatch(self, lower: int, upper: int):
        self._charge(upper - lower + 1, frac=0.2)   # async enqueue cost
        return (lower, upper)

    def finalize(self, handle, lower: int):
        lo, up = handle
        self._charge(up - lo + 1, frac=0.8)         # force cost
        return oracle_min(self.data, lo, up)

    def dispatch_batch(self, entries):
        """Batched-dispatch contract (ISSUE 9): one 'launch' for many
        jobs, charged as a single compute interval — the coalesced
        shape the batched_dispatch scenario drives through the REAL
        miner executor."""
        for _s, lo, up in entries:
            if lo > up:
                raise ValueError("empty range")
        self._charge(sum(up - lo + 1 for _s, lo, up in entries),
                     frac=0.2)
        return [(s.data, lo, up) for s, lo, up in entries]

    def finalize_batch(self, handle):
        self._charge(sum(up - lo + 1 for _d, lo, up in handle), frac=0.8)
        return [oracle_min(d, lo, up) for d, lo, up in handle]


class PipelinedDispatch(Scenario):
    """The REAL miner-side dispatch pipeline (apps/miner.MinerWorker,
    reader task + overlapped two-phase executor + to_thread hops) under
    the REAL striping scheduler: Results must still land strictly in
    request order per miner, and every merge stays exactly-once."""

    name = "pipelined_dispatch"

    def build(self, ctx: Ctx) -> None:
        from ...apps.miner import MinerWorker
        rng = ctx.rng
        _make_sched(ctx, lease=LeaseParams(
            grace_s=5.0, factor=4.0, floor_s=2.0, tick_s=0.1,
            queue_alarm_s=30.0), qos=QosParams(enabled=False),
            stripe=StripeParams(enabled=True, chunk_s=0.1, depth=4))
        self.workers = []
        for i in range(2):
            chan = ctx.server.connect()
            wrng = _fork(rng)
            worker = MinerWorker(
                f"det:{i}",
                searcher_factory=lambda data, batch=None, r=wrng:
                    _FakeSearcher(data, ctx, _fork(r)),
                pipeline=True, pipeline_depth=rng.choice((2, 4)))
            worker.client = chan
            chan.write(new_join().to_json())
            ctx.spawn(worker.run())
            self.workers.append((worker, chan))
        ctx.spawn(_warm_rates(ctx, 2, 4000.0))
        reqs = []
        for j in range(rng.choice((2, 3))):
            reqs.append(Req(f"{rng.choice(_DATA)}#{j}", 0,
                            rng.choice((799, 1199, 1599)),
                            pre_delay=0.5 + 0.2 * j))
        ctx.add_client("c0", reqs)

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_global_fifo(ctx)
        out += self.check_accounting(ctx)
        # In-order pipeline contract: each miner's k-th Result answers
        # its k-th Request (oracle-checked — a pipelined executor that
        # let chunk k+1 overtake chunk k would mismatch here).
        for worker, chan in self.workers:
            asked = [Message.from_json(p)
                     for p in ctx.server.sent_to(chan.conn_id)]
            asked = [m for m in asked if m.type == MsgType.REQUEST]
            answered = [Message.from_json(p) for p in chan.sent]
            answered = [m for m in answered if m.type == MsgType.RESULT]
            for k, rep in enumerate(answered):
                if k >= len(asked):
                    out.append(f"miner conn {chan.conn_id}: more "
                               f"Results than Requests")
                    break
                req = asked[k]
                h, n = oracle_min(req.data, req.lower, req.upper)
                if (rep.hash, rep.nonce) != (h, n):
                    out.append(
                        f"miner conn {chan.conn_id}: Result #{k} "
                        f"({rep.hash}, {rep.nonce}) does not answer "
                        f"Request #{k} [{req.lower}, {req.upper}] "
                        f"(oracle ({h}, {n})) — pipeline reordered "
                        f"Results")
        return out


# ------------------------------------------------------- batched_dispatch

class BatchedDispatch(Scenario):
    """Cross-request batched dispatch (ISSUE 9) under the REAL
    scheduler/QoS and REAL coalescing MinerWorkers: a chunked elephant
    plus mice trains from two other tenants, the scheduler's coalescing
    window stacking small grants on one miner, and the miner executor
    draining them into shared batched launches. Every reply must stay
    exactly-once oracle-exact in per-tenant order, the grant accounting
    must balance, and each miner's k-th Result must answer its k-th
    Request — a coalescer that scattered batch results out of drain
    order, or attributed them to the wrong request, fails here."""

    name = "batched_dispatch"

    def build(self, ctx: Ctx) -> None:
        from ...apps.miner import MinerWorker
        rng = ctx.rng
        lanes = rng.choice((3, 4, 8))
        _make_sched(ctx, lease=LeaseParams(
            grace_s=5.0, factor=4.0, floor_s=2.0, tick_s=0.1,
            queue_alarm_s=30.0), qos=QosParams(
            enabled=True, chunk_s=0.2, max_chunks=32, depth=2,
            wholesale_s=0.5),
            coalesce=CoalesceParams(
                enabled=True, lanes=lanes,
                small_s=rng.choice((0.1, 0.25))))
        self.workers = []
        for i in range(2):
            chan = ctx.server.connect()
            wrng = _fork(rng)
            worker = MinerWorker(
                f"det:{i}",
                searcher_factory=lambda data, batch=None, r=wrng:
                    _FakeSearcher(data, ctx, _fork(r)),
                pipeline=True, pipeline_depth=8,
                coalesce=True, coalesce_lanes=lanes,
                coalesce_max=1 << 20)
            worker.client = chan
            chan.write(new_join().to_json())
            ctx.spawn(worker.run())
            self.workers.append((worker, chan))
        ctx.spawn(_warm_rates(ctx, 2, 4000.0))
        # Tenant 1: a chunked elephant (est 2s > wholesale 0.5s at the
        # warmed 2 x 4000 nps pool) whose grant stream the mice must
        # interleave — and sometimes share windows — with.
        ctx.add_client("elephant", [
            Req(rng.choice(_DATA), 0, rng.choice((7999, 11999)),
                pre_delay=0.5)])
        # Tenants 2 + 3: mice trains of small argmin requests (each one
        # QoS chunk, each coalescible at the warmed rate) landing while
        # the elephant is mid-grant.
        for t, n in (("mice_a", rng.choice((2, 3))), ("mice_b", 2)):
            reqs = [Req(f"{rng.choice(_DATA)}#{t}{j}", 0,
                        rng.choice((99, 199, 399)),
                        pre_delay=0.6 + rng.uniform(0.0, 1.0))
                    for j in range(n)]
            ctx.add_client(t, reqs)

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        # In-order coalesced scatter: each miner's k-th Result answers
        # its k-th Request, oracle-exact (same contract as the
        # pipelined_dispatch scenario — a batch written out of drain
        # order, or mis-scattered across requests, mismatches here).
        for worker, chan in self.workers:
            asked = [Message.from_json(p)
                     for p in ctx.server.sent_to(chan.conn_id)]
            asked = [m for m in asked if m.type == MsgType.REQUEST]
            answered = [Message.from_json(p) for p in chan.sent]
            answered = [m for m in answered if m.type == MsgType.RESULT]
            for k, rep in enumerate(answered):
                if k >= len(asked):
                    out.append(f"miner conn {chan.conn_id}: more "
                               f"Results than Requests")
                    break
                req = asked[k]
                h, n = oracle_min(req.data, req.lower, req.upper)
                if (rep.hash, rep.nonce) != (h, n):
                    out.append(
                        f"miner conn {chan.conn_id}: Result #{k} "
                        f"({rep.hash}, {rep.nonce}) does not answer "
                        f"Request #{k} [{req.lower}, {req.upper}] "
                        f"(oracle ({h}, {n})) — coalescer broke the "
                        f"in-order scatter")
        return out


# ------------------------------------------------------- difficulty_prefix

class DifficultyPrefix(Scenario):
    """Difficulty (first-hit) merges under re-issue and stock-miner
    degradation: the prefix-release rule must hand back the globally
    FIRST qualifying nonce when every miner speaks the extension, and
    at-least-a-qualifying nonce when a stock miner weakened the merge —
    never a non-qualifying or fabricated one."""

    name = "difficulty_prefix"

    def build(self, ctx: Ctx) -> None:
        rng = ctx.rng
        _make_sched(ctx, lease=LeaseParams(
            grace_s=0.5, factor=4.0, floor_s=0.3, tick_s=0.05,
            quarantine_after=2, queue_alarm_s=30.0),
            qos=QosParams(enabled=False))
        self.has_stock = rng.random() < 0.5
        wedged = rng.choice((None, 0, 1, 2))
        for i in range(3):
            kw = {}
            if wedged == i:
                kw["wedge_after"] = rng.choice((0, 1))
            if self.has_stock and i == 2:
                kw["stock"] = True
            ctx.add_miner(
                f"m{i}",
                delay_fn=lambda size, r=_fork(rng): r.uniform(0.02, 0.2),
                **kw)
        reqs = []
        for _j in range(rng.choice((1, 2))):
            data = f"{rng.choice(_DATA)}#{_j}"
            upper = rng.choice((149, 209))
            if rng.random() < 0.25:
                target = 1          # unreachable: no-hit arg-min path
            else:
                q = rng.randrange(0, upper + 2)
                target = hash_op(data, q) + 1   # q qualifies by def.
            reqs.append(Req(data, 0, upper, target=target,
                            pre_delay=rng.uniform(0.0, 0.3)))
        ctx.add_client("c0", reqs)

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx, weak_ok=self.has_stock)
        out += self.check_global_fifo(ctx)
        out += self.check_accounting(ctx)
        return out


# ------------------------------------------------------------ plane_split

class PlaneSplit(Scenario):
    """The ISSUE 11 tenant/miner plane split under ONE combined storm:
    a chunked elephant, mice trains, striping, the coalescing window,
    a misbehaving (wedged or slow) miner driving the lease plane, and
    an optional mid-storm client drop — every grant crosses the
    tenant→miner interface, every Result crosses complete, and every
    blown lease crosses lease-event, with the full invariant pack
    (exactly-once oracle-exact per-tenant replies, accounting balance,
    span closure, sanitizer silence) proving the split preserved the
    monolith's semantics."""

    name = "plane_split"

    def build(self, ctx: Ctx) -> None:
        rng = ctx.rng
        _make_sched(ctx, lease=LeaseParams(
            grace_s=1.2, factor=4.0, floor_s=0.8, tick_s=0.1,
            quarantine_after=rng.choice((1, 2)), queue_alarm_s=30.0),
            qos=QosParams(
                enabled=True, chunk_s=0.2, max_chunks=16, depth=2,
                wholesale_s=0.5),
            stripe=StripeParams(enabled=True, chunk_s=0.3, depth=3),
            coalesce=CoalesceParams(enabled=True,
                                    lanes=rng.choice((3, 4)),
                                    small_s=0.25))
        bad = rng.choice((None, 0, 1, 2))
        slow = rng.random() < 0.5
        for i in range(3):
            kw = {}
            mrng = _fork(rng)
            if bad == i and not slow:
                kw["wedge_after"] = rng.choice((0, 1))
            elif bad == i and slow:
                kw["delay_fn"] = \
                    lambda size, r=mrng: r.uniform(1.5, 3.0)
            else:
                kw["delay_fn"] = lambda size, r=mrng: \
                    size / 1000.0 * r.uniform(0.8, 1.2)
            ctx.add_miner(f"m{i}", **kw)
        ctx.spawn(_warm_rates(ctx, 3, 1000.0))
        # Tenant 1: elephant (est ~0.7s > wholesale 0.5 at the warmed
        # 3x1000 nps pool -> chunked activation across the pool slice).
        ctx.add_client("elephant", [
            Req(rng.choice(_DATA), 0, 1999, pre_delay=0.5)])
        # Tenants 2+3: mice trains landing against the elephant's
        # grants (coalescible at the warmed rate).
        for t, n in (("mice_a", 2), ("mice_b", rng.choice((1, 2)))):
            reqs = [Req(f"{rng.choice(_DATA)}#{t}{j}", 0,
                        rng.choice((99, 199)),
                        pre_delay=0.5 + rng.uniform(0.0, 1.2))
                    for j in range(n)]
            ctx.add_client(t, reqs)
        if rng.random() < 0.4:
            # A client that drops right after sending: the cancel path
            # must free both planes without corrupting the others.
            ctx.add_client("dropper", [
                Req(f"{rng.choice(_DATA)}#d", 0, 149,
                    pre_delay=rng.uniform(0.3, 1.0), close_after=True)])

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        return out


# -------------------------------------------------------- replica_takeover

class ReplicaTakeover(Scenario):
    """ISSUE 11 replica sharding: a 2-replica :class:`~...apps.replicas.
    ReplicaSet` over ONE detnet transport, tenants consistent-hashed
    across the replicas and miners sliced between them — then one
    replica is KILLED at a seed-drawn virtual time, possibly
    mid-request. Lease takeover must re-serve the dead replica's queued
    and in-flight requests EXACTLY ONCE, oracle-exact, through the
    survivors (adopted miners' stale answers popping in order), with
    accounting balanced and every live trace closed at quiescence."""

    name = "replica_takeover"

    def build(self, ctx: Ctx) -> None:
        from ...apps.replicas import ReplicaSet
        from ...utils.config import CacheParams as _Cache
        rng = ctx.rng
        rs = ReplicaSet(
            ctx.server, 2,
            lease=LeaseParams(grace_s=5.0, factor=4.0, floor_s=2.0,
                              tick_s=0.1, queue_alarm_s=30.0),
            cache=_Cache(),
            qos=QosParams(enabled=True, chunk_s=0.3, max_chunks=8,
                          depth=2, wholesale_s=0.5),
            stripe=StripeParams(enabled=False),
            coalesce=CoalesceParams(enabled=False),
            clock=ctx.loop.time)
        ctx.sched = rs
        ctx.spawn(rs.run())
        for i in range(3):
            ctx.add_miner(
                f"m{i}",
                delay_fn=lambda size, r=_fork(rng):
                    size / 1000.0 * r.uniform(0.8, 1.2))

        async def warm():
            import asyncio as _a
            while sum(len(s.miners) for s in rs.replicas.values()) < 3:
                await _a.sleep(0.01)
            for sched in rs.replicas.values():
                for m in sched.miners:
                    m.rate_ewma = 1000.0
                sched._pool_rate = 1000.0
        ctx.spawn(warm())

        victim = rng.choice((0, 1))
        kill_at = rng.uniform(0.6, 2.5)

        async def killer():
            import asyncio as _a
            await _a.sleep(kill_at)
            if victim in rs.live and len(rs.live) > 1:
                rs.kill(victim)
        ctx.spawn(killer())

        # Several tenants so BOTH replicas own some: an elephant that
        # may be chunked-in-flight when the kill lands, plus mice.
        ctx.add_client("elephant", [
            Req(rng.choice(_DATA), 0, rng.choice((1499, 1999)),
                pre_delay=0.4)])
        for t, n in (("mice_a", 2), ("mice_b", 2)):
            reqs = [Req(f"{rng.choice(_DATA)}#{t}{j}", 0,
                        rng.choice((99, 199)),
                        pre_delay=0.3 + rng.uniform(0.0, 1.5))
                    for j in range(n)]
            ctx.add_client(t, reqs)

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        return out


# -------------------------------------------------------- adaptive_control

class AdaptiveControl(Scenario):
    """The self-tuning control plane (ISSUE 13) under the explorer: a
    REAL scheduler with the chunk/window/admission controllers mounted
    on the VIRTUAL clock, a chunked elephant + mice trains, and miners
    whose service rate DRIFTS mid-schedule (a seed-drawn step change —
    the adversarial input a static knob cannot follow). Invariants on
    top of the generic pack: every controller value stays inside its
    hard floor/ceiling at every recorded point, and no post-transient
    oscillation exceeds a bounded peak/trough amplitude
    (:func:`~....apps.adapt.oscillation_ratio`) — an unstable loop
    (self-amplifying sawtooth, limit cycle wider than one
    multiplicative step + dead-band) fails here; starvation fails the
    generic liveness/reply pack."""

    name = "adaptive_control"

    #: Peak/trough bound per post-transient swing: one multiplicative
    #: step (x2 at mul=0.5) compounded with the dead-band and one
    #: ratio-capped probe, doubled for headroom. ONE swing over the
    #: bound is tolerated per history — a congestion episode (anchored
    #: multiplicative descent + the recovery ramp back toward open) is
    #: exactly that shape — but TWO is a limit cycle: a loop swinging
    #: wide repeatedly is fighting its own measurement, which is what
    #: this scenario exists to catch (and did: the pre-settle-tick
    #: chunk controller's EWMA-lag cascade produced wide swings in
    #: BOTH directions).
    AMPLITUDE_BOUND = 5.0

    def build(self, ctx: Ctx) -> None:
        from ...utils.config import AdaptParams
        rng = ctx.rng
        adapt = AdaptParams(
            enabled=True, tick_s=0.2, band=0.25,
            force_s=rng.choice((0.3, 0.5)),
            rate0=rng.choice((0.0, 20.0)))
        _make_sched(ctx, lease=LeaseParams(
            grace_s=5.0, factor=4.0, floor_s=2.0, tick_s=0.1,
            queue_alarm_s=30.0), qos=QosParams(
            enabled=True, chunk_s=0.2, max_chunks=32, depth=2,
            wholesale_s=0.5, max_queued=rng.choice((4, 6))),
            coalesce=CoalesceParams(enabled=True,
                                    lanes=rng.choice((3, 4)),
                                    small_s=0.25),
            adapt=adapt)
        # Miners whose rate steps mid-schedule: the drift the
        # controllers exist to track. The mutable cell is flipped by a
        # timer at a seed-drawn virtual time.
        self.rate_cell = {"rate": 1000.0}
        drift_at = rng.uniform(0.8, 2.0)
        drift_to = rng.choice((400.0, 2500.0))

        async def drift():
            await asyncio.sleep(drift_at)
            self.rate_cell["rate"] = drift_to
        ctx.spawn(drift())
        for i in range(2):
            ctx.add_miner(
                f"m{i}",
                delay_fn=lambda size, r=_fork(rng), cell=self.rate_cell:
                    size / cell["rate"] * r.uniform(0.8, 1.2))
        ctx.spawn(_warm_rates(ctx, 2, 1000.0))
        # Elephant (chunked at the warmed 2x1000 nps pool) + mice
        # trains — the population whose interleavings drive every
        # controller: chunk pops feed the sizing loop, small arrivals
        # the window loop, queue age the admission loop.
        ctx.add_client("elephant", [
            Req(rng.choice(_DATA), 0, 1999, pre_delay=0.5)])
        for t, n in (("mice_a", rng.choice((2, 3))), ("mice_b", 2)):
            reqs = [Req(f"{rng.choice(_DATA)}#{t}{j}", 0,
                        rng.choice((99, 149)),
                        pre_delay=0.5 + rng.uniform(0.0, 1.5))
                    for j in range(n)]
            ctx.add_client(t, reqs)

    def check(self, ctx: Ctx):
        from ...apps.adapt import oscillation_ratios
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        plane = ctx.sched.adapt_plane
        if plane is None:
            return out + ["adaptive_control ran without an adapt plane"]
        for name, (floor, ceil, hist) in plane.histories().items():
            for _t, v in hist:
                if not (floor - 1e-9 <= v <= ceil + 1e-9):
                    out.append(
                        f"adapt {name}: value {v} escaped its clamps "
                        f"[{floor}, {ceil}]")
                    break
            wide = [r for r in oscillation_ratios(hist)
                    if r > self.AMPLITUDE_BOUND]
            if len(wide) >= 2:
                out.append(
                    f"adapt {name}: {len(wide)} swings exceed the "
                    f"{self.AMPLITUDE_BOUND}x amplitude bound (worst "
                    f"{max(wide):.2f}x — limit cycle, not one "
                    f"congestion episode; history tail "
                    f"{[round(v, 4) for _t, v in hist[-8:]]})")
        return out


# ------------------------------------------------------------ wide_miner


class WideMiner(Scenario):
    """ISSUE 14 heterogeneous pool: one 100x rate-skewed "mesh" miner
    (joins with the rate-hint JOIN — the scheduler seeds its EWMA from
    the wire, no artificial pin) next to two slow host-tier miners,
    under the REAL scheduler with QoS chunking, striping, and leases
    all live on the virtual clock. A chunked elephant plus mice trains
    drive grants across the skewed pool.

    Invariants on top of the generic pack (exactly-once oracle-exact
    replies, accounting balance, span closure):

    - **No blown-lease storm from rate skew**: every lease is sized
      from the answering miner's OWN rate (hint-seeded for the fast
      miner, measured for the slow ones), so honest miners at 100x
      different speeds must blow ZERO leases however the schedule
      interleaves.
    - **Plans stay inside clamps**: total Requests written to miners
      is bounded by the chunk-plan cap + stripe depth per request — a
      hint- or skew-driven mis-sizing that shatters a request into a
      chunk storm fails here.
    - **Rate-aware placement**: the fast miner ends the storm having
      been granted at least as many nonces as either slow miner —
      share follows the rate EWMAs through the existing DRR/capacity
      planes, with no tier-aware code anywhere.
    """

    name = "wide_miner"

    FAST_RATE = 100_000.0
    SLOW_RATE = 1_000.0

    def build(self, ctx: Ctx) -> None:
        rng = ctx.rng
        sched = _make_sched(ctx, lease=LeaseParams(
            grace_s=5.0, factor=4.0, floor_s=2.0, tick_s=0.1,
            queue_alarm_s=30.0), qos=QosParams(
            enabled=True, chunk_s=0.2, max_chunks=32, depth=2,
            wholesale_s=0.5),
            stripe=StripeParams(enabled=True, chunk_s=0.3, depth=3))
        # m0: the wide miner — 100x the host tier, EWMA seeded from its
        # JOIN rate hint (the wire path under test). m1/m2: host tier.
        self.fast = ctx.add_miner(
            "m0", rate_hint=self.FAST_RATE,
            delay_fn=lambda size, r=_fork(rng):
                size / self.FAST_RATE * r.uniform(0.8, 1.2))
        self.slow = [ctx.add_miner(
            f"m{i}",
            delay_fn=lambda size, r=_fork(rng):
                size / self.SLOW_RATE * r.uniform(0.8, 1.2))
            for i in (1, 2)]

        async def warm():
            # Slow miners warm to their measured tier; the POOL rate is
            # pinned at the slow tier (the hint may have seeded it when
            # the fast miner joined an empty pool) so elephant chunk
            # plans are sized for the majority tier — the fast miner's
            # PER-MINER hint is what the skew-handling must ride.
            while ctx.sched is None or len(ctx.sched.miners) < 3:
                await asyncio.sleep(0.01)
            ctx.sched.miner_plane.pin_rates(self.SLOW_RATE)
        ctx.spawn(warm())
        # Elephant: chunked at the pinned 3x-slow-tier pool estimate
        # (8000 > wholesale_s * rate * n = 1500).
        self.n_requests = 1
        ctx.add_client("elephant", [
            Req(rng.choice(_DATA), 0, rng.choice((7999, 9999)),
                pre_delay=0.5)])
        for t, n in (("mice_a", rng.choice((2, 3))), ("mice_b", 2)):
            reqs = [Req(f"{rng.choice(_DATA)}#{t}{j}", 0,
                        rng.choice((99, 149)),
                        pre_delay=0.5 + rng.uniform(0.0, 1.5))
                    for j in range(n)]
            self.n_requests += n
            ctx.add_client(t, reqs)

    def _granted_nonces(self, ctx: Ctx, conn_id: int) -> int:
        total = 0
        for payload in ctx.server.sent_to(conn_id):
            msg = Message.from_json(payload)
            if msg.type == MsgType.REQUEST:
                total += msg.upper - msg.lower + 1
        return total

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        stats = ctx.sched.stats
        if stats["leases_blown"]:
            out.append(
                f"rate skew blew {stats['leases_blown']} lease(s) — "
                f"per-miner rate sizing (hint-seeded for the wide "
                f"miner) must keep honest miners inside their leases")
        # Chunk/stripe plans inside clamps: per request at most
        # max_chunks QoS chunks OR stripe.depth chunks per miner share,
        # plus nothing re-issued (leases never blow here).
        n_req = sum(1 for conn in
                    [self.fast.chan.conn_id]
                    + [m.chan.conn_id for m in self.slow]
                    for payload in ctx.server.sent_to(conn)
                    if Message.from_json(payload).type == MsgType.REQUEST)
        bound = self.n_requests * max(32, 3 * 3)
        if n_req > bound:
            out.append(f"chunk storm: {n_req} miner Requests for "
                       f"{self.n_requests} client requests "
                       f"(clamp bound {bound})")
        fast_n = self._granted_nonces(ctx, self.fast.chan.conn_id)
        for m in self.slow:
            slow_n = self._granted_nonces(ctx, m.chan.conn_id)
            if fast_n < slow_n:
                out.append(
                    f"rate-aware placement inverted: 100x miner got "
                    f"{fast_n} nonces, slow miner {m.name} got {slow_n}")
        return out


# --------------------------------------------------------- replayed_storm

#: Parsed captures by path (the explorer re-executes a scenario
#: thousands of times; the capture file is parsed ONCE per process).
_REPLAY_CAPS: dict = {}


def _replay_capture(fixture: str = "replay_fixture.jsonl",
                    env_override: bool = True):
    """The capture a replayed scenario replays: ``DBM_CHECK_CAPTURE``
    (the tier-1 replay leg points it at the storm it just captured;
    honored only when ``env_override``), or the checked-in ``fixture``
    — ``replay_fixture.jsonl`` is a real mice-stampede run captured on
    the detnet harness, ``replay_transport_fixture.jsonl`` a
    transport-bound ``loadharness --procs`` storm over real UDP
    sockets (ISSUE 17)."""
    path = (str_env("DBM_CHECK_CAPTURE", "") if env_override
            else "") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), fixture)
    cap = _REPLAY_CAPS.get(path)
    if cap is None:
        from ...apps.capture import load_capture
        cap = _REPLAY_CAPS[path] = load_capture(path)
    return cap


class ReplayedStorm(Scenario):
    """Interleaving exploration over MEASURED traffic (ISSUE 15): a
    workload capture converts into a scripted population — per-tenant
    arrival pacing and geometry mix from the capture's ``req`` records,
    the miner pool's relative rate skew from its ``pool`` snapshots —
    and the full invariant pack (exactly-once oracle-exact replies,
    accounting balance, span closure, liveness) runs over every
    explored schedule. The seed draws WHICH window of the capture
    replays (tenant subset + offset), jitters the pool, and may wedge
    one miner, so scenario diversity grows from real traffic shapes
    instead of hand-written scripts. Geometry is clamped to
    oracle-checkable sizes (ranges ≤ 512 nonces, vtime-compressed
    arrivals) — the capture drives the SHAPE; the oracle needs the
    scale bounded."""

    name = "replayed_storm"

    #: Clamps keeping one schedule's host-oracle work bounded whatever
    #: capture DBM_CHECK_CAPTURE points at.
    MAX_TENANTS = 8
    MAX_REQS_PER_TENANT = 3
    MAX_NONCES = 512
    MAX_WINDOW_VTIME = 2.5

    #: Which checked-in capture drives the shape, and whether the
    #: ``DBM_CHECK_CAPTURE`` override applies (the tier-1 replay leg
    #: retargets only the base scenario at its fresh capture).
    FIXTURE = "replay_fixture.jsonl"
    ENV_OVERRIDE = True

    def build(self, ctx: Ctx) -> None:
        from ...apps.capture import replay_plan
        rng = ctx.rng
        cap = _replay_capture(self.FIXTURE, self.ENV_OVERRIDE)
        plan = replay_plan(cap)
        n_ten = rng.randint(4, self.MAX_TENANTS)
        if len(plan) > n_ten:
            at = rng.randrange(0, len(plan) - n_ten + 1)
            window = plan[at:at + n_ten]
        else:
            window = plan
        t_lo = min(p["start"] for p in window)
        dur = max((p["start"] - t_lo)
                  + (p["reqs"][min(len(p["reqs"]),
                                   self.MAX_REQS_PER_TENANT) - 1][0]
                     if p["reqs"] else 0.0)
                  for p in window)
        scale = (min(1.0, self.MAX_WINDOW_VTIME / dur)
                 if dur > 0 else 1.0)
        _make_sched(ctx, lease=LeaseParams(
            grace_s=0.8, factor=4.0, floor_s=0.5, tick_s=0.05,
            quarantine_after=2, queue_alarm_s=30.0),
            qos=QosParams(enabled=True, chunk_s=0.2, max_chunks=16,
                          depth=2, wholesale_s=0.5, max_queued=64))
        # Pool: captured rate EWMAs keep their RELATIVE skew, mapped
        # onto the ~1000-nps virtual-time scale the other scenarios
        # use; one miner may wedge (the capture's reissue events say
        # real traffic saw re-issues too — the shape must survive one
        # here).
        rates = cap.pool_rates() or [1000.0, 1000.0]
        med = sorted(rates)[len(rates) // 2]
        n_m = min(3, max(2, len(rates)))
        wedged = rng.random() < 0.3
        bad = rng.randrange(n_m) if wedged else None
        for i in range(n_m):
            rel = max(0.25, min(4.0, rates[i % len(rates)] / med))
            vrate = 1000.0 * rel
            kw = {}
            mrng = _fork(rng)
            if bad == i:
                kw["wedge_after"] = rng.choice((0, 1))
            else:
                kw["delay_fn"] = (lambda size, r=mrng, v=vrate:
                                  size / v * r.uniform(0.8, 1.2))
            ctx.add_miner(f"m{i}", **kw)
        ctx.spawn(_warm_rates(ctx, n_m, 1000.0))
        for ti, p in enumerate(window):
            reqs = []
            prev = 0.0
            offsets = [p["start"] - t_lo + dt for dt, _n, _m, _d
                       in p["reqs"][:self.MAX_REQS_PER_TENANT]]
            for i, (dt, n, mode, _dc) in enumerate(
                    p["reqs"][:self.MAX_REQS_PER_TENANT]):
                at = offsets[i] * scale
                reqs.append(Req(
                    f"{rng.choice(_DATA)}#{ti}.{i}", 0,
                    min(max(1, n), self.MAX_NONCES) - 1,
                    target=1 if mode == "diff" else 0,
                    pre_delay=max(0.0, at - prev)))
                prev = at
            ctx.add_client(f"t{ti}", reqs)

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        return out


class ReplayedTransportStorm(ReplayedStorm):
    """ISSUE 17: the ``replayed_storm`` machinery over a TRANSPORT-BOUND
    capture — a ``loadharness --procs`` storm recorded with
    ``DBM_CAPTURE=1`` on the real multi-process topology (router +
    replica processes + fake miner agents over real localhost UDP at
    the batched-syscall datapath's admitted/s ceiling), checked in as
    ``replay_transport_fixture.jsonl``. The detnet replay keeps the
    measured arrival pacing and burst shape of traffic that saturated
    the REAL wire, so interleaving exploration covers the burst
    patterns the mmsg datapath actually produces (deep recv bursts,
    ack flushes at pump exit) rather than hand-scripted pacing. The
    fixture is pinned (no ``DBM_CHECK_CAPTURE`` override): the tier-1
    replay leg retargets the base scenario, while this one always
    explores the checked-in transport storm."""

    name = "replayed_transport_storm"
    FIXTURE = "replay_transport_fixture.jsonl"
    ENV_OVERRIDE = False


# -------------------------------------------------------- health_takeover

class _ProcView:
    """Merged invariant view over the model's replica schedulers (the
    harness reads ``ctx.sched._inflight/queue/qos_plane/traces``)."""

    def __init__(self, scheds):
        self._scheds = scheds

    @property
    def _inflight(self):
        out = {}
        for s in self._scheds:
            out.update(s._inflight)
        return out

    @property
    def queue(self):
        return [r for s in self._scheds for r in s.queue]

    @property
    def qos_plane(self):
        from ...apps.replicas import _MergedQos
        return _MergedQos(self._scheds)

    @property
    def traces(self):
        from ...apps.replicas import _MergedTraces
        return _MergedTraces(self._scheds)


class HealthTakeover(Scenario):
    """ISSUE 12: the multi-process failure model run IN-PROCESS on the
    virtual clock — the same :mod:`...apps.health` detection/fencing
    code the real router executes, against two REAL schedulers on two
    DetServers (one socket per replica, like one socket per process).

    One replica is PARTITIONED at a seed-drawn virtual time: its beat
    seq freezes at the router (missed-beat detection fires — no kill
    hook anywhere) while it KEEPS SERVING its existing conns — the
    gray-failure/fencing case. The router declares it dead, bumps the
    fencing epoch, and re-rings; ring-aware model clients re-resolve on
    conn death/timeout and resubmit to the survivor; the rejoining
    model miner re-attaches like the process miner agent. When the
    partition heals, the victim observes its own fence and simulates
    process exit (every conn of its server drops). Invariants: every
    client gets EXACTLY ONE oracle-exact reply however the schedule
    interleaves detection, late victim Results, and resubmission;
    accounting and spans drain to zero on BOTH replicas."""

    name = "health_takeover"

    def build(self, ctx: Ctx) -> None:
        from ...apps.health import BeatMonitor, RouterState, router_tick
        from ...apps.health import Beat
        from ...lspnet.detnet import DetServer
        from ...utils.config import CacheParams
        rng = ctx.rng
        beat_s = 0.2
        lease = LeaseParams(grace_s=5.0, factor=4.0, floor_s=2.0,
                            tick_s=0.1, queue_alarm_s=30.0)
        qos = QosParams(enabled=True, chunk_s=0.3, max_chunks=8,
                        depth=2, wholesale_s=0.5)
        # One DetServer per replica — one socket per process.
        servers = [ctx.server, DetServer()]
        scheds = []
        for rid in range(2):
            sched = Scheduler(
                servers[rid], lease=lease, cache=CacheParams(),
                stripe=StripeParams(enabled=False), qos=qos,
                coalesce=CoalesceParams(enabled=False),
                clock=ctx.loop.time)
            scheds.append(sched)
            ctx.spawn(sched.run())

            async def sweeps(s=sched):
                while True:
                    await asyncio.sleep(s.lease.tick_s)
                    s.sweep()
            ctx.spawn(sweeps())
        ctx.sched = _ProcView(scheds)
        self.scheds = scheds

        # ---- model health plane on the virtual clock ----
        state = RouterState(BeatMonitor(beat_s, 2))
        membership = state.membership
        self.membership = membership
        bus: dict = {}                  # rid -> latest Beat
        seqs = [0, 0]
        self.victim = victim = rng.choice((0, 1))
        part_at = rng.uniform(0.4, 1.6)
        heal_at = part_at + rng.uniform(1.2, 2.5)
        self.partitioned = False
        self.exited = [False, False]

        def simulate_exit(rid: int) -> None:
            # Process death: every conn of this replica's server drops
            # (clients resubmit elsewhere, the miner rejoins), queued
            # and in-flight state cancels through the normal drop path.
            if self.exited[rid]:
                return
            self.exited[rid] = True
            for conn_id in list(servers[rid]._chans):
                servers[rid].close_conn(conn_id)
                scheds[rid]._on_drop(conn_id)

        async def replica_beats(rid: int) -> None:
            inc = f"r{rid}"
            while True:
                cut = (rid == victim and self.partitioned)
                if not cut:
                    if membership.is_fenced(rid, inc):
                        simulate_exit(rid)
                        return
                    seqs[rid] += 1
                    bus[rid] = Beat(
                        rid=rid, incarnation=inc, seq=seqs[rid],
                        port=rid, serving=True,
                        miners=len(scheds[rid].miners),
                        queue_depth=len(scheds[rid].queue),
                        epoch_seen=membership.epoch)
                await asyncio.sleep(beat_s)

        async def router() -> None:
            while True:
                router_tick(state, list(bus.values()), ctx.loop.time())
                await asyncio.sleep(beat_s / 2)

        async def partition_timer() -> None:
            await asyncio.sleep(part_at)
            self.partitioned = True
            await asyncio.sleep(max(0.05, heal_at - part_at))
            self.partitioned = False

        for rid in range(2):
            ctx.spawn(replica_beats(rid))
        ctx.spawn(router())
        ctx.spawn(partition_timer())

        # ---- rejoining miners (the process miner agent, modeled) ----
        mrngs = [_fork(rng) for _ in range(2)]

        async def miner_agent(idx: int) -> None:
            mrng = mrngs[idx]
            while True:
                live = sorted(membership.live)
                if not live:
                    await asyncio.sleep(0.1)
                    continue
                rid = live[idx % len(live)]
                chan = servers[rid].connect()
                chan.write(new_join().to_json())
                try:
                    while True:
                        payload = await chan.read()
                        msg = Message.from_json(payload)
                        if msg.type != MsgType.REQUEST:
                            continue
                        await asyncio.sleep(
                            (msg.upper - msg.lower + 1) / 1000.0
                            * mrng.uniform(0.8, 1.2))
                        from .scenario import oracle_min
                        h, n = oracle_min(msg.data, msg.lower, msg.upper)
                        chan.write(new_result(h, n).to_json())
                except Exception:   # noqa: BLE001 — conn died: rejoin
                    await asyncio.sleep(0.1)

        for i in range(2):
            ctx.spawn(miner_agent(i))

        async def warm() -> None:
            while any(not s.miners for s in scheds):
                await asyncio.sleep(0.05)
            for s in scheds:
                for m in s.miners:
                    m.rate_ewma = 1000.0
                s._pool_rate = 1000.0
        ctx.spawn(warm())

        # ---- ring-aware clients (the replica-aware retry plane) ----
        from ...apps.replicas import HashRing

        class RingClient:
            def __init__(self, name, requests):
                self.name = name
                self.requests = requests
                self.replies: list = []
                self.shed = False
                self.dropped = False

            @staticmethod
            async def _read_or_none(chan):
                # A coroutine handed to wait_for becomes its own task;
                # it must finish with a VALUE (the drain-phase audit
                # flags any task finishing with an exception, even a
                # consumed one).
                try:
                    return await chan.read()
                except LspError:
                    return None

            async def run(self) -> None:
                for req in self.requests:
                    if req.pre_delay > 0:
                        await asyncio.sleep(req.pre_delay)
                    while True:
                        live = sorted(membership.live)
                        if not live:
                            await asyncio.sleep(0.2)
                            continue
                        rid = HashRing(live).owner(self.name)
                        chan = servers[rid].connect()
                        payload = None
                        try:
                            chan.write(new_request(
                                req.data, req.lower, req.upper,
                                req.target).to_json())
                            payload = await asyncio.wait_for(
                                self._read_or_none(chan), 4.0)
                        except (LspError, asyncio.TimeoutError):
                            payload = None
                        if payload is not None:
                            msg = Message.from_json(payload)
                            if msg.type == MsgType.RESULT:
                                self.replies.append(msg)
                                await chan.close()
                                break
                        # Abandon THIS conn before any resubmission —
                        # the exactly-once contract of the retry plane.
                        await chan.close()
                        await asyncio.sleep(0.2)

        n_mice = rng.choice((2, 3))
        specs = [("elephant", Req(rng.choice(_DATA), 0,
                                  rng.choice((1499, 1999)),
                                  pre_delay=0.4))]
        for j in range(n_mice):
            specs.append((f"mouse{j}",
                          Req(f"{rng.choice(_DATA)}#{j}", 0,
                              rng.choice((99, 199)),
                              pre_delay=0.3 + rng.uniform(0.0, 1.8))))
        for name, req in specs:
            c = RingClient(name, [req])
            ctx.clients.append(c)
            ctx.spawn(c.run(), client=True)

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        # Detection really fired off missed beats in schedules where the
        # partition window outlived the monitor window before heal.
        m = self.membership
        if self.exited[self.victim] and self.victim not in m.fenced:
            out.append("victim simulated exit without being fenced")
        return out


# --------------------------------------------------------------- federation

class Federation(Scenario):
    """ISSUE 20: the two-level scheduler tree run IN-PROCESS on the
    virtual clock — a REAL parent scheduler fronted by two REAL
    :class:`~...apps.gateway.GatewayMiner` actors, each re-sharding its
    grants through a stock inner scheduler on its own DetServer (one
    socket per child cluster, like one socket per process). The parent
    sees nothing but two miners speaking the stock wire: JOINs carry
    pool-summed rate hints over the Rate extension, grants come back as
    merged Results in grant order, and difficulty targets ride through
    both tiers (child miners honor the until extension, so the inner
    merge is strong and the gateway's target echo is truthful).

    Mid-schedule, child cluster 0 FAILS at a seed-drawn virtual time
    (every conn of its inner server dies — miners and bridge alike; the
    inner scheduler itself keeps running, modeling a fenced/empty child
    pool). The gateway reconnects its bridge, resubmits unanswered
    grants in order, finds the pool empty, and the orphan watchdog
    closes its parent conn: ONE drop + blown lease(s) at the parent,
    recovered by the stock re-issue plane granting to the surviving
    gateway. Invariants: every tenant gets EXACTLY ONE oracle-exact
    reply however the schedule interleaves grants, inner re-sharding,
    the failure, and re-issue; accounting and spans drain to zero on
    ALL THREE schedulers."""

    name = "federation"

    def build(self, ctx: Ctx) -> None:
        from ...apps.gateway import GatewayMiner
        from ...lspnet.detnet import DetServer
        from ...utils.config import GatewayParams
        rng = ctx.rng
        lease = LeaseParams(grace_s=4.0, factor=4.0, floor_s=1.5,
                            tick_s=0.1, queue_alarm_s=30.0)
        qos = QosParams(enabled=True, chunk_s=0.3, max_chunks=8,
                        depth=2, wholesale_s=0.5)

        def mk(server) -> Scheduler:
            s = Scheduler(server, lease=lease, cache=CacheParams(),
                          stripe=StripeParams(enabled=False), qos=qos,
                          coalesce=CoalesceParams(enabled=False),
                          clock=ctx.loop.time)
            ctx.spawn(s.run())
            return s

        parent = mk(ctx.server)
        inner_servers = [DetServer(), DetServer()]
        inners = [mk(srv) for srv in inner_servers]
        ctx.sched = _ProcView([parent] + inners)
        self.inners = inners

        # ---- the federation tier: one gateway per child cluster ----
        async def _conn(server):
            return server.connect()

        gw_params = GatewayParams(
            enabled=True, hint_s=rng.uniform(0.3, 0.8), min_pool=1,
            orphan_s=rng.uniform(0.4, 0.9))
        self.gateways = []
        for i in range(2):
            gw = GatewayMiner(
                parent_connect=lambda: _conn(ctx.server),
                bridge_connect=lambda srv=inner_servers[i]: _conn(srv),
                inner_scheds=[inners[i]], params=gw_params,
                poll_s=0.1, backoff_s=0.2, name=f"gw{i}")
            self.gateways.append(gw)
            ctx.spawn(gw.run_forever())

        # ---- child miners: oracle-exact, until-honoring, hinted ----
        pools = [rng.choice((1, 2)), rng.choice((1, 2))]

        async def child(i: int, mrng: random.Random) -> None:
            hint = mrng.uniform(400.0, 4000.0)
            chan = inner_servers[i].connect()
            try:
                chan.write(new_join(rate=int(hint)).to_json())
                while True:
                    payload = await chan.read()
                    msg = Message.from_json(payload)
                    if msg.type != MsgType.REQUEST:
                        continue
                    await asyncio.sleep(
                        (msg.upper - msg.lower + 1) / 1000.0
                        * mrng.uniform(0.8, 1.2))
                    from .scenario import oracle_min, oracle_until
                    if msg.target:
                        h, n, _found = oracle_until(
                            msg.data, msg.lower, msg.upper, msg.target)
                        echo = msg.target
                    else:
                        h, n = oracle_min(msg.data, msg.lower, msg.upper)
                        echo = 0
                    chan.write(new_result(h, n, echo).to_json())
            except LspError:
                return      # child cluster failed under us

        for i in range(2):
            for _j in range(pools[i]):
                ctx.spawn(child(i, _fork(rng)))

        # ---- mid-schedule child-cluster failure (cluster 0) ----
        self.fail_at = rng.uniform(0.6, 2.2)
        self.failed = False

        async def failover() -> None:
            await asyncio.sleep(self.fail_at)
            self.failed = True
            # Whole-cluster death: every conn of the inner server dies
            # (child miners AND the gateway's bridge), and the inner
            # scheduler observes the drops — the simulate_exit shape.
            for conn_id in list(inner_servers[0]._chans):
                inner_servers[0].close_conn(conn_id)
                inners[0]._on_drop(conn_id)
        ctx.spawn(failover())

        # ---- tenants at the parent (oracle-checked) ----
        ctx.add_client("elephant", [Req(rng.choice(_DATA), 0,
                                        rng.choice((1499, 1999)),
                                        pre_delay=0.3)])
        for j in range(rng.choice((2, 3))):
            data = f"{rng.choice(_DATA)}#{j}"
            upper = rng.choice((99, 199))
            target = 0
            if rng.random() < 0.5:
                if rng.random() < 0.25:
                    target = 1      # unreachable: no-hit arg-min path
                else:
                    q = rng.randrange(0, upper + 2)
                    target = hash_op(data, q) + 1
            ctx.add_client(f"mouse{j}",
                           [Req(data, 0, upper, target=target,
                                pre_delay=0.2 + rng.uniform(0.0, 1.5))])

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        if sum(g.results_forwarded for g in self.gateways) < 1:
            out.append("no Result ever crossed the federation tier "
                       "(gateways never carried the schedule)")
        return out


# ------------------------------------------------------- known-bad fixtures

# --------------------------------------------------------- byzantine_miner

class _ByzantineBase(Scenario):
    """Base of the byzantine_miner family (ISSUE 16): FakeMiners that
    LIE — fabricated pairs, sentinel-without-scan claims, alternating
    honesty, colluding duplicates — against a REAL scheduler running
    the verification tier (claim checks always; full-window audits with
    a seeded stream where the subclass says so). The generic pack is
    the point: exactly-once ORACLE-EXACT replies prove no lie ever
    reached a client, however the explorer interleaves the liars'
    instant answers against honest scans, claim-retry re-issues, audit
    grants, and trust decay — the acceptance bar is 0 violations while
    any honest miner remains, and every population here keeps at least
    one honest miner.

    Subclasses set ``LIAR_MODES`` (one FakeMiner ``byzantine`` mode per
    liar; the seed draws their positions in the 3-miner pool) and
    ``AUDIT_P`` (1.0 + a full-range ``audit_max_nonces`` where claim
    checks alone cannot see the lie: a sentinel claim is a real pair,
    only re-execution exposes it, and the reply hold + audit repair is
    what keeps the final answer exact)."""

    LIAR_MODES: tuple = ()
    AUDIT_P = 0.0
    #: One optional drop-after-send client (wrong-hash only): a lie
    #: about a cancelled request's chunk pops STALE — never
    #: claim-checked — which the caught-liar soft check must tolerate.
    DROPPER = False

    def build(self, ctx: Ctx) -> None:
        from ...utils.config import VerifyParams
        rng = ctx.rng
        _make_sched(
            ctx,
            lease=LeaseParams(grace_s=2.0, factor=4.0, floor_s=0.5,
                              tick_s=0.05, quarantine_after=2,
                              ewma_alpha=0.3, queue_alarm_s=30.0),
            qos=QosParams(enabled=False),
            verify=VerifyParams(enabled=True, audit_p=self.AUDIT_P,
                                audit_max_nonces=1 << 20),
            audit_rng=_fork(rng))
        liar_at = dict(zip(rng.sample(range(3), len(self.LIAR_MODES)),
                           self.LIAR_MODES))
        self.liars = []
        for i in range(3):
            mrng = _fork(rng)
            kw = {"delay_fn": lambda size, r=mrng: r.uniform(0.02, 0.25),
                  "byzantine": liar_at.get(i, "")}
            m = ctx.add_miner(f"m{i}", **kw)
            if kw["byzantine"]:
                self.liars.append(m)
        reqs = []
        for j in range(rng.choice((2, 3))):
            # Unique cache keys (the "#j" suffix): no ResultCache
            # replay, so every reply is a fresh merge the liars raced.
            reqs.append(Req(f"{rng.choice(_DATA)}#{j}", 0,
                            rng.choice((59, 119, 199)),
                            pre_delay=rng.uniform(0.0, 0.3)))
        ctx.add_client("c0", reqs)
        if self.DROPPER and rng.random() < 0.5:
            ctx.add_client("c1", [Req(f"{rng.choice(_DATA)}#x", 0, 99,
                                      pre_delay=rng.uniform(0.0, 0.4),
                                      close_after=True)])

    def check(self, ctx: Ctx):
        out = self.check_replies(ctx)
        out += self.check_accounting(ctx)
        stats = ctx.sched.stats
        lied = sum(m.lies for m in self.liars)
        dropped = any(c.dropped or c.shed for c in ctx.clients)
        if lied and not dropped and not stats["claims_failed"] \
                and not stats["audits_failed"] \
                and not stats["audits_passed"]:
            # Every lie raced a LIVE request (no cancel made it stale),
            # so the tier must have examined at least one: a rejected
            # claim, a failed audit, or a coincidentally-correct
            # sentinel surviving its re-execution. Zero of each means
            # the lies were believed unexamined.
            out.append(
                f"{lied} lie(s) answered live requests but the "
                f"verification tier recorded nothing (claims_failed=0, "
                f"no audit outcomes)")
        if self.AUDIT_P >= 1.0 and not dropped \
                and not stats["audits_issued"]:
            out.append("audit_p=1.0 yet no audit was ever issued")
        return out


class ByzantineWrongHash(_ByzantineBase):
    """One or two miners fabricate an unbeatable fake pair (wrong-hash
    class): the claim check's SHA-256 recompute must reject every one
    BEFORE merge and re-issue the range until an honest scan answers.
    No audits — this class dies at the claim layer."""

    name = "byzantine_wrong_hash"
    DROPPER = True

    def build(self, ctx: Ctx) -> None:
        self.LIAR_MODES = ("wrong_hash",) * ctx.rng.choice((1, 2))
        super().build(ctx)


class ByzantineCollude(_ByzantineBase):
    """Colluding duplicates: TWO miners submit the IDENTICAL fabricated
    pair (FakeMiner wrong-hash fabrication is deterministic), the class
    that defeats any vote-counting verifier. Recomputation does not
    count votes: both copies must fail the claim check independently,
    and the surviving honest miner's scans answer everything."""

    name = "byzantine_collude"
    LIAR_MODES = ("wrong_hash", "wrong_hash")


class ByzantineSentinel(_ByzantineBase):
    """Sentinel-without-scan: the liar hashes ONE nonce and claims it
    as its chunk's argmin — a REAL in-range pair the claim check
    cannot fault. Full-window audits (p=1.0) re-execute every merged
    chunk on a disjoint miner while the reply HOLDS; a failed audit
    merges the auditor's verified sub-argmin (the repair) before the
    release, so the client still sees the oracle-exact answer."""

    name = "byzantine_sentinel"
    LIAR_MODES = ("sentinel",)
    AUDIT_P = 1.0


class ByzantineSelective(_ByzantineBase):
    """Selectively-correct: the liar alternates honest scans with
    sentinel claims — building trust and spending it, the adversary
    reputation decay alone cannot keep out. Full-window audits catch
    each lying call regardless of the honest calls around it."""

    name = "byzantine_selective"
    LIAR_MODES = ("selective",)
    AUDIT_P = 1.0


class FixtureLostUpdate(Scenario):
    """KNOWN-BAD: classic read-yield-write lost update. Two tasks
    increment a counter with an await between load and store; any
    schedule that interleaves the loads loses one increment. dbmcheck
    MUST find a failing schedule here (tests pin that it does)."""

    name = "fixture_lost_update"

    def build(self, ctx: Ctx) -> None:
        self.box = {"counter": 0}

        async def bump():
            v = self.box["counter"]
            await asyncio.sleep(0)       # the racy yield point
            self.box["counter"] = v + 1

        ctx.spawn(bump(), client=True)
        ctx.spawn(bump(), client=True)

    def check(self, ctx: Ctx):
        if self.box["counter"] != 2:
            return [f"lost update: counter is {self.box['counter']}, "
                    f"expected 2"]
        return []


class FixtureDoubleReply(Scenario):
    """KNOWN-BAD: a mini-scheduler that replies on a merged chunk
    WITHOUT the answered[] guard the real scheduler carries — two
    racing Results (a speculative re-issue and its original) can both
    pass the not-yet-answered check and double-reply."""

    name = "fixture_double_reply"

    def build(self, ctx: Ctx) -> None:
        self.replies: list = []
        self.answered = False

        async def on_result(tag):
            if not self.answered:
                await asyncio.sleep(0)   # check-then-act without a latch
                self.replies.append(tag)
                self.answered = True

        ctx.spawn(on_result("original"), client=True)
        ctx.spawn(on_result("reissue"), client=True)

    def check(self, ctx: Ctx):
        if len(self.replies) != 1:
            return [f"exactly-once broken: {len(self.replies)} replies "
                    f"({self.replies})"]
        return []


SCENARIOS = {
    "lease_reissue": LeaseReissue,
    "qos_shed": QosShed,
    "pipelined_dispatch": PipelinedDispatch,
    "batched_dispatch": BatchedDispatch,
    "difficulty_prefix": DifficultyPrefix,
    "plane_split": PlaneSplit,
    "wide_miner": WideMiner,
    "replayed_storm": ReplayedStorm,
    "replayed_transport_storm": ReplayedTransportStorm,
    "replica_takeover": ReplicaTakeover,
    "adaptive_control": AdaptiveControl,
    "health_takeover": HealthTakeover,
    "federation": Federation,
    "byzantine_wrong_hash": ByzantineWrongHash,
    "byzantine_collude": ByzantineCollude,
    "byzantine_sentinel": ByzantineSentinel,
    "byzantine_selective": ByzantineSelective,
}

FIXTURES = {
    "fixture_lost_update": FixtureLostUpdate,
    "fixture_double_reply": FixtureDoubleReply,
}

ALL = {**SCENARIOS, **FIXTURES}
