"""dbmcheck scenario harness: actors, oracle, invariants (ISSUE 8).

A SCENARIO is a scripted control-plane population — a real
:class:`~...apps.scheduler.Scheduler` (and, in the pipelined scenario, a
real :class:`~...apps.miner.MinerWorker`) wired over the deterministic
transport shim (:mod:`...lspnet.detnet`) to fake miners and scripted
clients, all running on one :class:`.detloop.DetLoop`. The explorer
re-executes a scenario under different pickers; after every explored
schedule the INVARIANT PACK runs:

- **exactly-once, oracle-exact replies**: every non-shed request gets
  exactly ONE Result, bit-equal to the host oracle (arg-min, or the
  difficulty first-hit/weak contract), in per-tenant submission order —
  the client-visible face of "exactly-once chunk merge under re-issue"
  and of the strict arg-min / first-hit merge rules;
- **FIFO dispatch order** (stock scenarios): Results leave the
  scheduler in global request-arrival order — the reference's
  one-in-flight contract;
- **accounting balance**: after quiescence no request is in flight,
  the queue is empty, and every QoS tenant's granted-but-unanswered
  in-flight count is back to zero (lease/QoS in-flight balance);
- **liveness**: the scenario completes within its virtual-time budget
  (a schedule that wedges the scheduler IS the bug class this harness
  exists to find) and drains to quiescence afterwards;
- **sanitizer silence**: the ``utils.sanitize`` ownership / off-loop
  violation counters must not grow during the schedule (PR 6's
  THREAD_SHARED ownership tables, re-checked as happens-before facts
  under the virtual scheduler — the executor hops are real threads);
- **span closure** (ISSUE 10): every request trace the scheduler
  registered during the schedule is CLOSED (terminal reply/cancel) at
  quiescence — an open span is a forgotten request or a trace-plane
  path that lost its terminal event;
- **no unhandled exceptions** anywhere in the population.

Scenario randomness is layered for shrinkability: BUILD-time constants
(ranges, which miner wedges) come from ``Random(seed)``; RUN-time draws
(per-chunk delays, fake compute costs) come from per-actor child
streams forked at build (see scenarios.py ``_fork``); and the PICKER's
randomness is independent of both. An explicit choice-trace replay
(shrinking, DFS) therefore keeps the population constants fixed and
each actor's k-th timing draw a function of its own k — perturbing one
scheduling choice does not re-roll unrelated actors' timing.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional

from ...bitcoin.hash import scan_min, scan_until
from ...bitcoin.message import (Message, MsgType, new_join, new_request,
                                new_result)
from ...lsp.errors import LspError
from ...lspnet.detnet import DetServer
from ...utils.metrics import registry as _registry
from .detloop import DetLoop, Picker, RandomPicker, TracePicker, virtual_time

__all__ = ["Ctx", "Scenario", "FakeMiner", "ClientScript", "Req",
           "execute", "oracle_min", "oracle_until", "SANITIZE_COUNTERS"]

#: Per-schedule budgets. Virtual seconds, not wall seconds: a scenario
#: that cannot finish inside these is reported as a liveness violation.
#: The drain phase gets its OWN step/vtime allowances on top of
#: whatever the main phase consumed — a long-but-legal schedule must
#: not be starved into a spurious "no quiescence" report.
MAX_STEPS = 20_000
MAX_VTIME = 600.0
DRAIN_STEPS = 5_000
DRAIN_VTIME = 120.0

SANITIZE_COUNTERS = ("sanitize.ownership_violations",
                     "sanitize.loop_blocking")

# ------------------------------------------------------------------ oracle

_MIN_CACHE: Dict[tuple, tuple] = {}
_UNTIL_CACHE: Dict[tuple, tuple] = {}


def oracle_min(data: str, lower: int, upper: int) -> tuple:
    """Host-oracle arg-min over the INCLUSIVE range (memoized across
    schedules — the explorer re-runs the same ranges hundreds of
    times)."""
    key = (data, lower, upper)
    hit = _MIN_CACHE.get(key)
    if hit is None:
        hit = _MIN_CACHE[key] = scan_min(data, lower, upper)
    return hit


def oracle_until(data: str, lower: int, upper: int, target: int) -> tuple:
    key = (data, lower, upper, target)
    hit = _UNTIL_CACHE.get(key)
    if hit is None:
        hit = _UNTIL_CACHE[key] = scan_until(data, lower, upper, target)
    return hit


# ------------------------------------------------------------------ actors

class Req:
    """One scripted client request. ``upper`` is the wire-inclusive
    bound; the whole system scans ``[lower, upper+1]`` (the reference
    bound quirk), which is what the oracle checks against."""

    __slots__ = ("data", "lower", "upper", "target", "pre_delay",
                 "close_after")

    def __init__(self, data: str, lower: int, upper: int, target: int = 0,
                 pre_delay: float = 0.0, close_after: bool = False):
        self.data = data
        self.lower = lower
        self.upper = upper
        self.target = target
        self.pre_delay = pre_delay
        self.close_after = close_after   # client drops right after sending


class ClientScript:
    """A scripted tenant: sends its requests in order, then reads
    replies until it has one per request or its conn dies (shed)."""

    def __init__(self, ctx: "Ctx", name: str, requests: List[Req]):
        self.ctx = ctx
        self.name = name
        self.requests = requests
        self.chan = ctx.server.connect()
        self.replies: List[Message] = []
        self.shed = False
        self.dropped = False   # the script itself closed the conn

    async def run(self) -> None:
        import asyncio
        sent = 0
        for req in self.requests:
            if req.pre_delay > 0:
                await asyncio.sleep(req.pre_delay)
            try:
                self.chan.write(new_request(
                    req.data, req.lower, req.upper, req.target).to_json())
            except LspError:
                self.shed = True
                return
            sent += 1
            if req.close_after:
                self.dropped = True
                await self.chan.close()
                return
        while len(self.replies) < sent:
            try:
                payload = await self.chan.read()
            except LspError:
                self.shed = True
                return
            msg = Message.from_json(payload)
            if msg.type == MsgType.RESULT:
                self.replies.append(msg)


class FakeMiner:
    """A well-behaved (or deliberately misbehaving) miner endpoint.

    - ``delay_fn(size) -> float`` virtual seconds of 'compute' for a
      ``size``-nonce chunk;
    - ``wedge_after=N``: answers N chunks then reads forever without
      answering (transport alive, compute wedged — the lease-blow
      shape);
    - ``stock=True``: drops the difficulty target like a reference Go
      miner (answers the chunk arg-min, echoes no target) — the WEAK
      merge shape;
    - ``rate_hint``: nonces/s sent on the Join's Rate extension (the
      ISSUE 14 rate-hint path — the scheduler seeds this miner's EWMA
      from it instead of warming through traffic);
    - ``byzantine`` (ISSUE 16): the miner LIES instead of computing.
      ``"wrong_hash"`` fabricates an unbeatable fake pair (hash 1 at
      the range's first nonce — wins every merge unless claim-checked;
      identical across miners, so two such miners are the colluding-
      duplicates class that defeats vote-counting but not
      recomputation); ``"sentinel"`` hashes ONE nonce (the range's
      first) and claims it as the argmin — a real in-range pair only
      re-execution audits can expose; ``"selective"`` alternates
      honest and sentinel answers (builds trust, spends it).
    """

    def __init__(self, ctx: "Ctx", name: str,
                 delay_fn: Optional[Callable[[int], float]] = None,
                 wedge_after: Optional[int] = None, stock: bool = False,
                 rate_hint: float = 0.0, byzantine: str = ""):
        assert byzantine in ("", "wrong_hash", "sentinel", "selective"), \
            byzantine
        self.ctx = ctx
        self.name = name
        self.delay_fn = delay_fn or (lambda size: 0.0)
        self.wedge_after = wedge_after
        self.stock = stock
        self.rate_hint = rate_hint
        self.byzantine = byzantine
        self.chan = ctx.server.connect()
        self.answered = 0
        self.lies = 0

    def _fabricate(self, msg: Message):
        """The byzantine answer for this REQUEST, or None to answer
        honestly (mirrors lspnet.chaos.ByzantineSearcher)."""
        if not self.byzantine:
            return None
        if self.byzantine == "selective" and self.answered % 2 == 0:
            return None          # even calls honest: trust-building
        self.lies += 1
        if self.byzantine == "wrong_hash":
            return (1, msg.lower)
        from ...bitcoin.hash import hash_op
        return (hash_op(msg.data, msg.lower), msg.lower)

    async def run(self) -> None:
        import asyncio
        self.chan.write(new_join(rate=int(self.rate_hint)).to_json())
        while True:
            try:
                payload = await self.chan.read()
            except LspError:
                return
            msg = Message.from_json(payload)
            if msg.type != MsgType.REQUEST:
                continue
            if self.wedge_after is not None \
                    and self.answered >= self.wedge_after:
                continue   # wedged: keep reading, never answer
            lie = self._fabricate(msg)
            if lie is not None:
                # A liar pays NO compute delay — skipping the scan is
                # the whole point of lying, and the instant answer wins
                # more merge races, which is the adversarial pressure
                # the verification tier must hold against.
                self.answered += 1
                try:
                    self.chan.write(new_result(*lie, 0).to_json())
                except LspError:
                    return
                continue
            d = self.delay_fn(msg.upper - msg.lower + 1)
            if d > 0:
                await asyncio.sleep(d)
            # Upper arrives as an exclusive bound but is scanned
            # INCLUSIVE (the reference miner quirk, miner.go:51-52).
            if msg.target and not self.stock:
                h, n, _found = oracle_until(msg.data, msg.lower,
                                            msg.upper, msg.target)
                echo = msg.target
            else:
                h, n = oracle_min(msg.data, msg.lower, msg.upper)
                echo = 0
            self.answered += 1
            try:
                self.chan.write(new_result(h, n, echo).to_json())
            except LspError:
                return


# ----------------------------------------------------------------- context

class Ctx:
    """Everything one schedule execution owns."""

    def __init__(self, loop: DetLoop, rng: random.Random):
        self.loop = loop
        self.rng = rng
        self.server = DetServer()
        self.sched = None                   # set by scenario.build
        self.clients: List[ClientScript] = []
        self.miners: List[FakeMiner] = []
        self._actor_tasks: list = []
        self._client_tasks: list = []

    def spawn(self, coro, client: bool = False):
        task = self.loop.create_task(coro)
        (self._client_tasks if client else self._actor_tasks).append(task)
        return task

    def add_client(self, name: str, requests: List[Req]) -> ClientScript:
        c = ClientScript(self, name, requests)
        self.clients.append(c)
        self.spawn(c.run(), client=True)
        return c

    def add_miner(self, name: str, **kw) -> FakeMiner:
        m = FakeMiner(self, name, **kw)
        self.miners.append(m)
        self.spawn(m.run())
        return m

    def clients_done(self) -> bool:
        return all(t.done() for t in self._client_tasks)

    def quiescent(self) -> bool:
        if self.sched is None:
            return True
        return not self.sched._inflight and not self.sched.queue


# ---------------------------------------------------------------- scenario

class Scenario:
    """One named scripted population + its invariant pack."""

    name = "base"

    def build(self, ctx: Ctx) -> None:
        raise NotImplementedError

    def check(self, ctx: Ctx) -> List[str]:
        """Scenario-specific invariants; the harness adds the generic
        pack (replies/accounting/liveness/sanitizer/exceptions)."""
        return []

    # ------------------------------------------------- reusable checks

    @staticmethod
    def check_replies(ctx: Ctx, weak_ok: bool = False) -> List[str]:
        """Exactly-once, oracle-exact, per-tenant-ordered replies.

        When any two requests in the schedule share a cache key
        ``(data, lower, upper, target)``, a later duplicate may
        legitimately replay from the ResultCache at arrival —
        overtaking queued work by design (PR 2) — so ordering is then
        checked as a MULTISET (each reply oracle-exact for some
        outstanding request) instead of positionally."""
        out = []
        keys = [(r.data, r.lower, r.upper, r.target)
                for c in ctx.clients for r in c.requests]
        has_dups = len(set(keys)) < len(keys)
        for c in ctx.clients:
            expect = list(c.requests)
            if c.shed or c.dropped:
                # A shed/dropped tenant's replies must still be a
                # correct SUBSET (each oracle-exact), at most one each.
                expect = expect if has_dups else expect[:len(c.replies)]
                if len(c.replies) > len(c.requests):
                    out.append(f"{c.name}: {len(c.replies)} replies for "
                               f"{len(c.requests)} requests")
            elif len(c.replies) != len(c.requests):
                out.append(
                    f"{c.name}: {len(c.replies)} replies for "
                    f"{len(c.requests)} requests (exactly-once broken)")
                if not has_dups:
                    expect = expect[:len(c.replies)]
            if not has_dups:
                for i, (req, rep) in enumerate(zip(expect, c.replies)):
                    out.extend(Scenario._check_one(
                        c.name, i, req, rep, weak_ok))
                continue
            # Multiset matching: consume one outstanding request per
            # reply; a reply matching nothing is a violation.
            pending = list(expect)
            for i, rep in enumerate(c.replies):
                matched = None
                for req in pending:
                    if not Scenario._check_one(c.name, i, req, rep,
                                               weak_ok):
                        matched = req
                        break
                if matched is None:
                    out.append(f"{c.name}[{i}]: reply ({rep.hash}, "
                               f"{rep.nonce}) matches no outstanding "
                               f"request")
                else:
                    pending.remove(matched)
        return out

    @staticmethod
    def _check_one(who: str, i: int, req: Req, rep: Message,
                   weak_ok: bool) -> List[str]:
        # The merged scan covers [lower, upper+1] (bound quirk).
        lo, hi = req.lower, req.upper + 1
        if req.target:
            h, n, found = oracle_until(req.data, lo, hi, req.target)
            if found:
                if rep.hash >= req.target:
                    return [f"{who}[{i}]: difficulty answer hash "
                            f"{rep.hash} does not qualify (target "
                            f"{req.target})"]
                from ...bitcoin.hash import hash_op
                if hash_op(req.data, rep.nonce) != rep.hash:
                    return [f"{who}[{i}]: difficulty answer "
                            f"(h={rep.hash}, n={rep.nonce}) is not a "
                            f"real (hash, nonce) pair"]
                if not weak_ok and (rep.hash, rep.nonce) != (h, n):
                    return [f"{who}[{i}]: difficulty answer "
                            f"(h={rep.hash}, n={rep.nonce}) is not the "
                            f"globally first hit ({h}, {n})"]
                return []
            # No hit in range: exact arg-min, like stock.
            if (rep.hash, rep.nonce) != (h, n):
                return [f"{who}[{i}]: no-hit difficulty answer "
                        f"({rep.hash}, {rep.nonce}) != arg-min "
                        f"({h}, {n})"]
            return []
        h, n = oracle_min(req.data, lo, hi)
        if (rep.hash, rep.nonce) != (h, n):
            return [f"{who}[{i}]: answer ({rep.hash}, {rep.nonce}) != "
                    f"oracle arg-min ({h}, {n}) over [{lo}, {hi}]"]
        return []

    @staticmethod
    def check_global_fifo(ctx: Ctx) -> List[str]:
        """Stock path only: Results leave in request-arrival order.

        Arrival order is the DetServer read-queue delivery order of
        REQUEST payloads; reply order is the write order of RESULTs to
        client conns. Under the reference one-in-flight FIFO contract
        the two sequences' conn ids must match position-wise.

        Results may legitimately be MISSING for a conn whose client
        dropped or was shed (its request cancels); what may never happen
        is a reply overtaking an earlier-arrived live request — so the
        reply sequence must be an order-preserving subsequence of the
        arrival sequence whose skipped entries all belong to
        dropped/shed conns."""
        client_ids = {c.chan.conn_id for c in ctx.clients}
        gone = {c.chan.conn_id for c in ctx.clients
                if c.dropped or c.shed}
        arrivals = []
        for c, payload in ctx.server._read_log:
            if c not in client_ids:
                continue
            msg = Message.from_json(payload)
            if msg.type == MsgType.REQUEST:
                arrivals.append(c)
        replies = [c for c, payload in ctx.server.writes
                   if c in client_ids
                   and Message.from_json(payload).type == MsgType.RESULT]
        i = 0
        for conn in arrivals:
            if i < len(replies) and replies[i] == conn:
                i += 1
            elif conn not in gone:
                return [f"FIFO order broken: request arrivals (conns) "
                        f"{arrivals}, replies {replies} — conn {conn} "
                        f"skipped or overtaken"]
        if i < len(replies):
            return [f"FIFO: more replies than arrivals "
                    f"({replies} vs {arrivals})"]
        return []

    @staticmethod
    def check_accounting(ctx: Ctx) -> List[str]:
        """Post-quiescence lease/QoS in-flight balance."""
        out = []
        sched = ctx.sched
        if sched is None:
            return out
        if sched._inflight:
            out.append(f"requests still in flight after drain: "
                       f"{sorted(sched._inflight)}")
        if sched.queue:
            out.append(f"{len(sched.queue)} request(s) still queued "
                       f"after drain")
        for tenant, st in sched.qos_plane.tenants.items():
            if st.inflight != 0:
                out.append(
                    f"tenant {tenant}: {st.inflight} granted chunks "
                    f"still accounted in flight after quiescence "
                    f"(accounting imbalance)")
        return out

    @staticmethod
    def check_spans_closed(ctx: Ctx) -> List[str]:
        """Trace-span completeness at quiescence (ISSUE 10): every
        request trace the scheduler REGISTERED (dispatched, shed, or
        cache-replayed — queued-then-purged requests never register)
        must be CLOSED (terminal ``reply``/``cancel`` event) once
        nothing is in flight and nothing is queued. An open trace at
        quiescence is a request the scheduler forgot to answer OR a
        trace-plane path that dropped its terminal event — both real
        bugs the per-schedule exploration should surface, not just the
        e2e suites."""
        out = []
        sched = ctx.sched
        if sched is None or sched._inflight or sched.queue:
            return out     # not quiescent: accounting checks report that
        for key, trace in sched.traces.items():
            if not trace.closed:
                events = [e["event"] for e in trace.to_dict()["events"]]
                out.append(
                    f"trace {key!r} open at quiescence (span leak): "
                    f"events={events}")
        return out


# ---------------------------------------------------------------- executor

class ScheduleResult:
    __slots__ = ("scenario", "seed", "status", "steps", "violations",
                 "trace", "choices", "explicit")

    def __init__(self, scenario, seed, status, steps, violations, trace,
                 explicit=False):
        self.scenario = scenario
        self.seed = seed
        self.status = status
        self.steps = steps
        self.violations = violations
        self.trace = trace                   # [(n_alternatives, chosen)]
        self.choices = [c for _n, c in trace]
        self.explicit = explicit             # ran from an explicit trace

    @property
    def failed(self) -> bool:
        return bool(self.violations)

    def schedule_key(self) -> int:
        return hash(tuple(self.steps))


def execute(scenario: Scenario, seed: int,
            choices: Optional[List[int]] = None,
            quiet: bool = True) -> ScheduleResult:
    """Run one schedule of ``scenario``: random walk from ``seed``, or
    an explicit choice-trace replay (``choices``) with the same
    scenario-level randomness. ``quiet`` mutes the ``dbm.*`` loggers
    for the run — scenarios deliberately blow leases and shed tenants,
    and a thousand-schedule exploration must not pay (or emit) a
    warning line per event; pass False when debugging one schedule."""
    import logging
    dbm_logger = logging.getLogger("dbm")
    prev_level = dbm_logger.level
    if quiet:
        dbm_logger.setLevel(logging.CRITICAL)
    try:
        return _execute(scenario, seed, choices)
    finally:
        dbm_logger.setLevel(prev_level)


def _execute(scenario: Scenario, seed: int,
             choices: Optional[List[int]]) -> ScheduleResult:
    if choices is not None:
        picker: Picker = TracePicker(choices)
    else:
        picker = RandomPicker(random.Random((seed << 1) ^ 0x9E3779B9))
    loop = DetLoop(picker)
    rng = random.Random(seed)
    ctx = Ctx(loop, rng)
    before = {name: _registry().counter(name).value
              for name in SANITIZE_COUNTERS}
    violations: List[str] = []
    with loop.running(), virtual_time(loop):
        scenario.build(ctx)
        status = loop.run_until(ctx.clients_done, MAX_STEPS, MAX_VTIME)
        if status == "done":
            drain = loop.run_until(ctx.quiescent,
                                   len(loop.steps) + DRAIN_STEPS,
                                   loop.time() + DRAIN_VTIME)
            if drain != "done":
                violations.append(
                    f"no quiescence after completion ({drain}): "
                    f"inflight={sorted(ctx.sched._inflight) if ctx.sched else []} "
                    f"queued={len(ctx.sched.queue) if ctx.sched else 0}")
        else:
            violations.append(
                f"scenario did not complete ({status}) at vtime "
                f"{loop.time():.2f}s after {len(loop.steps)} steps — "
                f"liveness violation")
        loop.drain()
    loop.close()
    violations.extend(scenario.check(ctx))
    # Generic pack addition (ISSUE 10): every span opened in the
    # explored schedule must be closed at quiescence, whatever the
    # scenario — scenario.check() need not opt in.
    violations.extend(Scenario.check_spans_closed(ctx))
    for name in SANITIZE_COUNTERS:
        delta = _registry().counter(name).value - before[name]
        if delta:
            violations.append(f"{name} grew by {delta} during the "
                              f"schedule (ownership/loop-block)")
    for exc in loop.exceptions:
        violations.append(
            "unhandled exception: "
            f"{exc.get('message')} {exc.get('exception')!r}")
    return ScheduleResult(scenario.name, seed, status, loop.steps,
                          violations, picker.trace,
                          explicit=choices is not None)
