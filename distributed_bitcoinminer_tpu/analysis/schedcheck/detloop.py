"""Deterministic asyncio event loop + virtual clock (dbmcheck, ISSUE 8).

The control plane — scheduler, QoS plane, miner pipeline — is one
asyncio process whose correctness depends on the ORDER its task steps,
timer firings, and ``to_thread`` hops land in. Normal asyncio picks that
order by wall-clock accident, so a chaos test samples a handful of
interleavings out of millions and calls it a day. :class:`DetLoop`
removes the accident: every runnable callback goes through ONE hook —
a :class:`Picker` — that decides which step executes next, and the clock
is virtual (``loop.time()`` and a patched ``time.monotonic`` advance
only when every runnable step has been consumed and the next timer is
due). An explored schedule is therefore a pure function of (scenario,
picker decisions): record the decisions and you can replay the schedule
bit-for-bit; enumerate them and you have loom/Shuttle-style bounded
model checking for the asyncio actor (PAPERS.md: the PNPCoin
coordinator's "millions of clients" plane needs its coordination side
provably right, not sampled right).

Design notes:

- ``DetLoop`` is a from-scratch ``AbstractEventLoop`` — not a patched
  ``BaseEventLoop`` — because the stock ``_run_once`` owns exactly the
  two decisions we need to own (which ready handle runs; when time
  advances). Real ``asyncio.Task`` / ``Future`` / ``Queue`` / ``sleep``
  machinery runs unmodified on top: they only need ``call_soon`` /
  ``call_at`` / ``create_future`` / ``time`` and the running-loop slot,
  all of which this class provides.
- ``run_in_executor`` (the ``asyncio.to_thread`` underbelly — the
  miner's searcher resolution/dispatch/finalize hops) executes the
  function on ONE dedicated worker thread while the loop thread blocks:
  the hop in and the hop back are schedulable steps the picker orders,
  the function body itself is atomic. Running it on a real non-loop
  thread (instead of inline) keeps ``utils.sanitize`` honest —
  ``assert_off_loop`` still distinguishes loop from worker, and a
  ``ThreadOwner`` violation is still a real cross-thread touch.
- The virtual clock must also serve ``time.monotonic`` because the
  control plane stamps leases/deadlines through it directly:
  :func:`virtual_time` patches it for the duration of a run. Code that
  captured ``time.monotonic`` at import (default args — e.g.
  ``QosPlane(clock=...)``) keeps wall time; scenarios inject
  ``loop.time`` there explicitly.
"""

from __future__ import annotations

import asyncio
import functools
import heapq
import queue
import threading
import time as _time_mod
from asyncio import events as _events
from typing import Callable, List, Optional

__all__ = ["DetLoop", "Picker", "RandomPicker", "TracePicker",
           "virtual_time", "step_label"]


class Picker:
    """The scheduler hook: ``choose(labels)`` returns the index of the
    ready step to run next. Called ONLY when there are >= 2 runnable
    steps (a forced step is not a choice point); implementations record
    their decisions so a failing schedule can be replayed and shrunk."""

    #: (n_alternatives, chosen_index) per choice point, in order.
    def __init__(self) -> None:
        self.trace: List[tuple] = []

    def choose(self, labels: List[str]) -> int:
        raise NotImplementedError


class RandomPicker(Picker):
    """Seed-driven random walk over the schedule space."""

    def __init__(self, rng) -> None:
        super().__init__()
        self.rng = rng

    def choose(self, labels: List[str]) -> int:
        idx = self.rng.randrange(len(labels))
        self.trace.append((len(labels), idx))
        return idx


class TracePicker(Picker):
    """Replay a recorded choice trace; beyond its end (or on an
    alternative-count mismatch after shrinking) falls back to index 0 —
    the deterministic FIFO default, which is exactly what makes
    truncation a valid shrinking move."""

    def __init__(self, choices) -> None:
        super().__init__()
        self._choices = list(choices)
        self._pos = 0

    def choose(self, labels: List[str]) -> int:
        idx = 0
        if self._pos < len(self._choices):
            idx = self._choices[self._pos]
            if idx >= len(labels):
                idx = 0
        self._pos += 1
        self.trace.append((len(labels), idx))
        return idx


def step_label(handle) -> str:
    """Stable human-readable label of one ready handle.

    Task steps name their coroutine (``task:Scheduler.run``); timers and
    plain callbacks name the function. Labels are what the golden-replay
    test compares bit-for-bit, so they must be a pure function of the
    callback — no ids, no addresses."""
    cb = getattr(handle, "_callback", None)
    while isinstance(cb, functools.partial):
        cb = cb.func
    owner = getattr(cb, "__self__", None)
    if isinstance(owner, asyncio.Task):
        coro = owner.get_coro()
        name = getattr(coro, "__qualname__", None)
        if name:
            return f"task:{name}"
    for attr in ("__qualname__", "__name__"):
        name = getattr(cb, attr, None)
        if name:
            return f"cb:{name}"
    return "cb:?"


class _Patch:
    """Context manager: ``time.monotonic`` -> the loop's virtual clock."""

    def __init__(self, loop: "DetLoop"):
        self._loop = loop
        self._orig = None

    def __enter__(self):
        self._orig = _time_mod.monotonic
        _time_mod.monotonic = self._loop.time
        return self

    def __exit__(self, *exc):
        _time_mod.monotonic = self._orig
        return False


def virtual_time(loop: "DetLoop") -> _Patch:
    """Patch ``time.monotonic`` to ``loop.time`` for a ``with`` scope."""
    return _Patch(loop)


class _LabeledHandle(asyncio.Handle):
    """A Handle carrying an explicit step label (Handle is __slots__)."""

    __slots__ = ("_det_label",)


class DetLoop(asyncio.AbstractEventLoop):
    """Deterministic, picker-driven, virtual-clock event loop."""

    def __init__(self, picker: Optional[Picker] = None):
        self._picker = picker if picker is not None else TracePicker([])
        self._now = 0.0
        self._ready: List[asyncio.Handle] = []
        self._timers: list = []          # heap of (when, seq, TimerHandle)
        self._seq = 0
        self._closed = False
        self._debug = False
        self.steps: List[str] = []       # executed step labels, in order
        self.tasks: List[asyncio.Task] = []
        self.exceptions: List[dict] = []  # unhandled callback/task errors
        self._worker: Optional[threading.Thread] = None
        self._jobs: "queue.Queue" = queue.Queue()

    # ------------------------------------------------------------ clock

    def time(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        """Jump the virtual clock mid-step (fake compute cost: a searcher
        that 'takes' 50ms advances here instead of sleeping)."""
        if dt > 0:
            self._now += dt

    # -------------------------------------------------------- scheduling

    def call_soon(self, callback, *args, context=None):
        handle = asyncio.Handle(callback, args, self, context)
        self._ready.append(handle)
        return handle

    # The worker thread never races the loop thread (it only runs while
    # the loop thread blocks in _run_job), so threadsafe == soon.
    call_soon_threadsafe = call_soon

    def call_later(self, delay, callback, *args, context=None):
        return self.call_at(self._now + max(0.0, delay), callback, *args,
                            context=context)

    def call_at(self, when, callback, *args, context=None):
        timer = asyncio.TimerHandle(when, callback, args, self, context)
        self._seq += 1
        heapq.heappush(self._timers, (when, self._seq, timer))
        return timer

    def _timer_handle_cancelled(self, handle) -> None:
        pass   # cancelled timers are skipped at pop time

    # ------------------------------------------------- futures and tasks

    def create_future(self) -> asyncio.Future:
        return asyncio.Future(loop=self)

    def create_task(self, coro, *, name=None, context=None):
        task = asyncio.Task(coro, loop=self, name=name)
        self.tasks.append(task)
        return task

    def run_in_executor(self, executor, func, *args):
        """One serialized worker thread; the job runs as ONE schedulable
        step (the loop thread blocks while the worker executes), so
        thread hops are explored but job bodies stay atomic."""
        fut = self.create_future()
        handle = _LabeledHandle(self._run_job, (func, args, fut), self)
        # Label the step after the innermost function so schedules read
        # "executor:MinerWorker._resolve_and_dispatch" (asyncio.to_thread
        # wraps the target as partial(ctx.run, func, *args)).
        inner = func
        while isinstance(inner, functools.partial):
            if inner.args and callable(inner.args[0]):
                inner = inner.args[0]
            else:
                inner = inner.func
        handle._det_label = "executor:" + (
            getattr(inner, "__qualname__", None)
            or getattr(inner, "__name__", None) or "?")
        self._ready.append(handle)
        return fut

    def _run_job(self, func, args, fut: asyncio.Future) -> None:
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._worker_main, name="detloop-exec", daemon=True)
            self._worker.start()
        box: dict = {}
        done = threading.Event()
        self._jobs.put((func, args, box, done))
        done.wait()
        if fut.cancelled():
            return
        if "error" in box:
            fut.set_exception(box["error"])
        else:
            fut.set_result(box.get("result"))

    def _worker_main(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            func, args, box, done = job
            try:
                box["result"] = func(*args)
            except BaseException as exc:  # noqa: BLE001 — relayed to fut
                box["error"] = exc
            finally:
                done.set()

    # -------------------------------------------------------- exceptions

    def default_exception_handler(self, context) -> None:
        self.exceptions.append(dict(context))

    def call_exception_handler(self, context) -> None:
        # CancelledError fallout from teardown is routine, not a finding.
        exc = context.get("exception")
        if isinstance(exc, asyncio.CancelledError):
            return
        self.exceptions.append(dict(context))

    # ---------------------------------------------------------- stepping

    def _due_timers(self) -> None:
        while self._timers and self._timers[0][0] <= self._now:
            _, _, timer = heapq.heappop(self._timers)
            if not timer.cancelled():
                self._ready.append(timer)

    def _prune(self) -> None:
        self._ready = [h for h in self._ready if not h.cancelled()]

    def step(self) -> bool:
        """Run ONE step (advancing virtual time if needed); False when
        nothing is runnable now or ever (quiescence/deadlock)."""
        self._due_timers()
        self._prune()
        while not self._ready:
            if not self._timers:
                return False
            # Advance to the next timer deadline; several timers sharing
            # it become simultaneous alternatives for the picker.
            self._now = max(self._now, self._timers[0][0])
            self._due_timers()
            self._prune()
        if len(self._ready) == 1:
            handle = self._ready.pop(0)
        else:
            labels = [self._label(h) for h in self._ready]
            idx = self._picker.choose(labels)
            handle = self._ready.pop(idx)
        self.steps.append(self._label(handle))
        handle._run()
        return True

    @staticmethod
    def _label(handle) -> str:
        return getattr(handle, "_det_label", None) or step_label(handle)

    def run_until(self, done: Callable[[], bool], max_steps: int,
                  max_vtime: float) -> str:
        """Drive steps until ``done()``; returns "done", "deadlock"
        (nothing runnable), "steps" or "vtime" on budget exhaustion.
        Must be called inside :meth:`running` / :func:`virtual_time`."""
        while not done():
            if len(self.steps) >= max_steps:
                return "steps"
            if self._now > max_vtime:
                return "vtime"
            if not self.step():
                return "deadlock"
        return "done"

    def drain(self, max_steps: int = 2000) -> None:
        """Teardown: cancel every known task and step (deterministically
        — cancellations leave at most bookkeeping steps) until all are
        finished, retrieving exceptions so no __del__ fires later."""
        for task in self.tasks:
            if not task.done():
                task.cancel()
        budget = max_steps
        while any(not t.done() for t in self.tasks) and budget > 0:
            if not self.step():
                break
            budget -= 1
        for task in self.tasks:
            if task.done() and not task.cancelled():
                exc = task.exception()
                if exc is not None:
                    self.exceptions.append(
                        {"message": "task raised", "exception": exc,
                         "task": repr(task)})

    class _Running:
        def __init__(self, loop): self._loop = loop

        def __enter__(self):
            _events._set_running_loop(self._loop)
            return self._loop

        def __exit__(self, *exc):
            _events._set_running_loop(None)
            return False

    def running(self) -> "_Running":
        """Context manager installing this loop as the running loop (so
        ``get_running_loop`` / ``Queue`` / ``sleep`` bind to it)."""
        return DetLoop._Running(self)

    # ------------------------------------------------------ housekeeping

    def get_debug(self) -> bool:
        return self._debug

    def set_debug(self, enabled: bool) -> None:
        self._debug = enabled

    def is_running(self) -> bool:
        return _events._get_running_loop() is self

    def is_closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        if self._worker is not None:
            self._jobs.put(None)
            self._worker.join(timeout=5.0)
            self._worker = None
        self._closed = True
