"""schedcheck — deterministic interleaving exploration for the control
plane (dbmcheck, ISSUE 8).

The dbmlint pack (the sibling ``analysis`` modules) proves STATIC facts
— a knob is documented, a blocking call stays off the loop. This
package proves SCHEDULING facts: it runs the real scheduler / QoS /
miner-pipeline state machines on a controlled event loop
(:mod:`.detloop`) where a picker — not wall-clock accident — chooses
every next step and a virtual clock drives every timer, then checks the
control plane's invariants after each explored schedule
(:mod:`.scenario`), over seed-driven random walks, bounded exhaustive
DFS, and replay/shrink of failing schedules (:mod:`.explore`).

Entry point: ``python scripts/dbmcheck.py`` (the tier-1 gate runs it
with a fixed seed budget; any printed seed spec replays its schedule
bit-for-bit).

Unlike the rest of ``analysis/`` this package IMPORTS the control plane
(scheduler, qos, miner — still no JAX); it is therefore not imported by
``analysis/__init__`` or the dbmlint CLI, keeping the lint leg's
import graph unchanged.
"""

from .detloop import DetLoop, Picker, RandomPicker, TracePicker
from .explore import (ExploreStats, explore_scenarios, format_spec,
                      parse_spec, replay, run_dfs, run_walks, shrink)
from .scenario import Ctx, Req, Scenario, ScheduleResult, execute
from .scenarios import ALL, FIXTURES, SCENARIOS

__all__ = [
    "DetLoop", "Picker", "RandomPicker", "TracePicker",
    "ExploreStats", "explore_scenarios", "format_spec", "parse_spec",
    "replay", "run_dfs", "run_walks", "shrink",
    "Ctx", "Req", "Scenario", "ScheduleResult", "execute",
    "ALL", "FIXTURES", "SCENARIOS",
]
