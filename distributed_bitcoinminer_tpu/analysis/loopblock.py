"""Analyzer: blocking calls inside ``async def`` bodies (loop-block).

The bug class (PR 4 review, round-5 live incident): synchronous JAX or
subprocess work executed directly on the asyncio event loop starves the
LSP engine's heartbeat/ack timers — a miner wedged in backend init or a
long ``subprocess.run`` passes its transport's epoch check late or never
and gets declared dead while healthy. Every compute call must hop to a
worker thread (``asyncio.to_thread`` / ``run_in_executor``).

Scope: ``apps/`` and ``lsp/`` (the asyncio actors). The walk covers the
DIRECT body of each ``async def`` — nested ``def``/``lambda`` bodies run
wherever they are later called (usually a thread pool), so only the
statements the coroutine itself executes are charged to the loop.

What counts as blocking (curated for this repo, not a general list):

- ``time.sleep`` (the asyncio one is fine);
- subprocess execution (``subprocess.run/check_*/call``, ``os.system``);
- JAX result forcing and transfer: ``.block_until_ready()``,
  ``jax.device_get``, ``.item()``, ``np/jnp.asarray``;
- backend/searcher construction and resolution: ``probe_backend``,
  ``jax_devices_robust``, ``_pin_platform_if_backend_wedged``,
  ``make_searcher``, ``default_searcher_factory``, ``NonceSearcher``,
  ``ShardedNonceSearcher``, ``_get_searcher`` (first touch runs backend
  init — minutes on a wedged tunnel);
- the searcher compute surface: ``.search()``, ``.search_until()``,
  ``.finalize()``, ``.dispatch()`` (dispatch can hide a full jit
  trace+compile), and the native scans ``scan_min_native`` /
  ``scan_until_native``.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, SourceFile, dotted

NAME = "loop-block"

SCOPE_PREFIXES = (
    "distributed_bitcoinminer_tpu/apps/",
    "distributed_bitcoinminer_tpu/lsp/",
)

#: Exact dotted-name suffixes that block (matched against the call's
#: dotted form, so ``time.sleep`` hits both ``time.sleep(...)`` and an
#: aliased ``t.sleep`` only when spelled with the module name).
BLOCKING_DOTTED = {
    "time.sleep", "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "subprocess.call", "os.system",
    "jax.device_get", "np.asarray", "numpy.asarray", "jnp.asarray",
}

#: Bare function / constructor names that block regardless of receiver.
BLOCKING_NAMES = {
    "probe_backend", "jax_devices_robust",
    "_pin_platform_if_backend_wedged", "default_searcher_factory",
    "NonceSearcher", "ShardedNonceSearcher", "PodSearcher",
    "scan_min_native", "scan_until_native", "run_follower",
}

#: Method names that block on ANY receiver (the compute surface).
BLOCKING_ATTRS = {
    "block_until_ready", "item", "search", "search_until", "finalize",
    "dispatch", "make_searcher", "_get_searcher", "_search",
    "_resolve_and_dispatch",
}


def _direct_body(fn: ast.AsyncFunctionDef):
    """Nodes the coroutine itself executes: walk, but do not descend into
    nested function/lambda definitions (their bodies run elsewhere)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def _blocking_reason(call: ast.Call):
    func = call.func
    name = dotted(func)
    if name in BLOCKING_DOTTED:
        return f"call to {name}"
    if isinstance(func, ast.Name) and func.id in BLOCKING_NAMES:
        return f"call to {func.id} (backend/searcher construction)"
    if isinstance(func, ast.Attribute) and func.attr in BLOCKING_ATTRS:
        # `self.foo.item` etc. — attribute on any receiver.
        if isinstance(func.value, ast.Name) and \
                func.value.id == "asyncio":
            return None   # asyncio.sleep etc.
        return f"call to .{func.attr}() (blocking compute surface)"
    if isinstance(func, ast.Name) and func.id in BLOCKING_ATTRS and \
            func.id not in ("search", "dispatch", "item", "finalize"):
        # Bare-name forms of the repo helpers (imported unqualified); the
        # generic method names stay attribute-only to avoid false hits.
        return f"call to {func.id} (blocking compute surface)"
    return None


def analyze(files: List[SourceFile], repo: str) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if f.tree is None or not f.rel.startswith(SCOPE_PREFIXES):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in _direct_body(node):
                if not isinstance(sub, ast.Call):
                    continue
                reason = _blocking_reason(sub)
                if reason is None:
                    continue
                callee = dotted(sub.func)
                out.append(Finding(
                    NAME, f.rel, sub.lineno,
                    f"{NAME}:{f.rel}:{node.name}:{callee}",
                    f"async def {node.name} runs blocking {reason} on "
                    f"the event loop; hop to a worker thread "
                    f"(asyncio.to_thread) so LSP heartbeats keep "
                    f"flowing"))
    return out
