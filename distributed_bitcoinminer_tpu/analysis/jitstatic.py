"""Analyzer: runtime-derived scalars at jit static boundaries (jit-static).

The bug class (PR 5 bench hardening): a value passed to a
``static_argnames`` parameter — or any shape-determining position — is
baked into the jit signature, so every DISTINCT value is a fresh XLA
trace+compile. A static argument derived from runtime state with an
unbounded value set (EWMA-drifted stripe sizes was the live incident:
``nbatches`` followed the scheduler's per-chunk nonce counts and
recompiled mid-leg, blowing 120s leases) turns the compile cache into a
recompile storm. Static arguments must come from QUANTIZED value sets —
pow2 sub-dispatch sizes, decimal block widths, fixed bench geometry.

Scope: ``ops/``, ``models/``, ``parallel/``. Two passes:

1. collect functions decorated ``functools.partial(jax.jit,
   static_argnames=(...))`` (or ``jax.jit(... static_argnames=...)``) —
   name -> static parameter names;
2. at every call site of a collected function, classify each static
   keyword's value expression:

   - **stable**: literals; attribute chains (precomputed state such as
     ``plan.rem`` — quantization happened where the plan was built);
     names that don't resolve to a local assignment (parameters, loop
     targets — the value was quantized upstream and the site is
     auditable); names whose single local assignment is itself stable;
     tuples/unary ops/boolean comparisons of stable parts; constant
     arithmetic.
   - **unstable** (finding): arithmetic on runtime values, function-call
     results, subscripts — computed AT the boundary, where nothing
     enforces a bounded value set. Sites that ARE bounded by
     construction document it with
     ``# dbmlint: ok[jit-static] <why bounded>``.

   Calls to a registered QUANTIZER (``BOUNDED_CALLS``) are stable by
   definition: the function's whole contract is to collapse a runtime
   value onto a bounded set — ``pow2_bucket`` (ops/search.py, ISSUE 9)
   maps coalesced-batch row counts onto powers of two, bounding the
   padded batch-geometry signature set at log2(max rows). Teaching the
   analyzer the quantizer (instead of suppressing per site) keeps every
   future batched call site machine-checked: an unquantized row count
   at a static boundary still fails.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, SourceFile, dotted

NAME = "jit-static"

#: Registered quantizers: calls whose RESULT is bounded by the callee's
#: contract (see module docstring). Matched on the dotted name's last
#: segment so both ``pow2_bucket(n)`` and ``search.pow2_bucket(n)``
#: resolve. ``devloop_cap`` (ISSUE 19) is the devloop span drivers'
#: static iteration backstop — pow2-quantized by delegation to
#: pow2_bucket, so the in-kernel loop bound's signature set stays at
#: log2(max subs) while the LIVE count rides a traced operand.
BOUNDED_CALLS = {"pow2_bucket", "devloop_cap"}

SCOPE_PREFIXES = (
    "distributed_bitcoinminer_tpu/ops/",
    "distributed_bitcoinminer_tpu/models/",
    "distributed_bitcoinminer_tpu/parallel/",
)


def _static_names_from_decorator(dec: ast.expr) -> Optional[Set[str]]:
    """static_argnames set when ``dec`` is a jit-with-statics decorator."""
    if not isinstance(dec, ast.Call):
        return None
    target = dotted(dec.func)
    args = list(dec.keywords)
    if target.endswith("partial"):
        if not dec.args or not dotted(dec.args[0]).endswith("jit"):
            return None
    elif not target.endswith("jit"):
        return None
    for kw in args:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            names = set()
            for el in v.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    names.add(el.value)
            return names
    return None


def _collect_jitted(files: List[SourceFile]) -> Dict[str, Set[str]]:
    jitted: Dict[str, Set[str]] = {}
    for f in files:
        if f.tree is None or not f.rel.startswith(SCOPE_PREFIXES):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                statics = _static_names_from_decorator(dec)
                if statics:
                    jitted[node.name] = statics
    return jitted


def _local_assignments(fn: ast.AST) -> Dict[str, List[ast.expr]]:
    """name -> assigned value exprs in ``fn``'s own body (nested defs
    excluded). Tuple-unpack targets map to a sentinel None (a slice of a
    call result — unresolvable, treated unstable)."""
    out: Dict[str, List] = {}
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.setdefault(tgt.id, []).append(node.value)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    for el in tgt.elts:
                        if isinstance(el, ast.Name):
                            out.setdefault(el.id, []).append(None)
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name):
            out.setdefault(node.target.id, []).append(None)
        for child in ast.iter_child_nodes(node):
            stack.append(child)
    return out


def _stable(expr: Optional[ast.expr], assigns: Dict[str, List],
            depth: int = 0) -> bool:
    if expr is None or depth > 4:
        return False
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Attribute):
        return True          # precomputed state; quantized at the source
    if isinstance(expr, ast.Name):
        values = assigns.get(expr.id)
        if values is None:
            return True      # parameter / loop target: quantized upstream
        if len(values) != 1:
            return False     # multi-assigned: value set untracked
        return _stable(values[0], assigns, depth + 1)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return all(_stable(el, assigns, depth + 1) for el in expr.elts)
    if isinstance(expr, ast.UnaryOp):
        return _stable(expr.operand, assigns, depth + 1)
    if isinstance(expr, ast.Compare):
        return True          # bool result: two-valued signature set
    if isinstance(expr, ast.IfExp):
        # Branch on anything; the VALUE set is the two branches' union.
        return _stable(expr.body, assigns, depth + 1) and \
            _stable(expr.orelse, assigns, depth + 1)
    if isinstance(expr, ast.BinOp):
        # Constant folding only: arithmetic on runtime values is exactly
        # the hazard.
        return isinstance(expr.left, ast.Constant) and \
            isinstance(expr.right, ast.Constant)
    if isinstance(expr, ast.Call):
        fname = dotted(expr.func)
        if fname in ("bool", "str"):   # bounded / non-shape coercions
            return all(_stable(a, assigns, depth + 1) for a in expr.args)
        if fname.rsplit(".", 1)[-1] in BOUNDED_CALLS:
            return True   # registered quantizer: bounded by contract
        return False
    return False


def analyze(files: List[SourceFile], repo: str) -> List[Finding]:
    jitted = _collect_jitted(files)
    out: List[Finding] = []
    if not jitted:
        return out
    for f in files:
        if f.tree is None or not f.rel.startswith(SCOPE_PREFIXES):
            continue
        # Walk function-by-function so call sites resolve local names.
        funcs = [n for n in ast.walk(f.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            assigns = _local_assignments(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = node.func
                cname = callee.attr if isinstance(callee, ast.Attribute) \
                    else (callee.id if isinstance(callee, ast.Name)
                          else "")
                statics = jitted.get(cname)
                if not statics:
                    continue
                for kw in node.keywords:
                    if kw.arg not in statics:
                        continue
                    if _stable(kw.value, assigns):
                        continue
                    out.append(Finding(
                        NAME, f.rel, kw.value.lineno,
                        f"{NAME}:{f.rel}:{fn.name}:{cname}:{kw.arg}",
                        f"static argument {kw.arg!r} of jitted "
                        f"{cname}() is computed at the call boundary "
                        f"in {fn.name}(); every distinct value is a "
                        f"fresh trace+compile — quantize the value set "
                        f"(pow2 / fixed geometry) where it is computed, "
                        f"or document the bound with a suppression"))
    return out
