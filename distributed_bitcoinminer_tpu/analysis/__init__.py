"""dbmlint — the repo's own AST-based invariant checker (ISSUE 7).

Five PRs of review rounds kept re-finding the same bug classes by hand;
this package machine-checks them. Pure AST + text over the working tree:
importing it (and running every analyzer) must never import JAX, so the
tier-1 lint leg costs seconds, not a backend init.

Analyzers (each a module exporting ``analyze(files) -> [Finding]``):

- ``loopblock`` — blocking calls (JAX forcing, subprocess, sleeps,
  searcher construction/scans) reachable from ``async def`` bodies in
  ``apps/`` and ``lsp/`` without a thread-pool hop.
- ``cardinality`` — dynamic metric label values must have a retirement
  path (a matching ``.remove(...)`` in the same module) or a justified
  suppression, so conn/job/tenant churn can't grow series without bound.
- ``knobs`` — every ``DBM_*`` read routes through ``utils/_env.py`` /
  ``utils/config.py``; the read knob set, the ``utils/config.py``
  docstring, and the README knob tables must all agree (no undocumented
  knobs, no orphaned doc entries).
- ``jitstatic`` — expressions computed inline at a jit boundary's static
  parameter (the stripe-size recompile-storm hazard) in ``ops/``,
  ``models/``, ``parallel/``.
- ``threadstate`` — attributes of ``Scheduler`` / ``QosPlane`` /
  ``MinerWorker`` touched from both coroutines and worker threads must
  appear in the class's ``THREAD_SHARED`` ownership table or be mutated
  under a lock.

Workflow: ``python scripts/dbmlint.py`` checks the tree against the
checked-in baseline (``analysis/baseline.json``). NEW findings fail the
run; fixed findings leave stale baseline entries, flushed with
``--update-baseline`` — which refuses to GROW the baseline unless
``--force`` is given, so the baseline shrinks monotonically.
Line-targeted suppressions use ``# dbmlint: ok[<analyzer>] <why>``.
"""

from .core import (ANALYZERS, Finding, baseline_path, compare, load_baseline,
                   load_files, run_repo, run_source, save_baseline)

__all__ = ["ANALYZERS", "Finding", "baseline_path", "compare",
           "load_baseline", "load_files", "run_repo", "run_source",
           "save_baseline"]
