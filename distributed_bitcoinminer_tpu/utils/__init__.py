"""Cross-cutting utilities: config, structured logging, profiling, metrics.

The reference's auxiliary subsystems (SURVEY §5) map here: its opt-in debug
logs (ref: lspnet/conn.go:32-42, srunner.go:33-37) become ``configure_logging``
plus the lspnet per-packet trace switch; its file logger
(ref: bitcoin/server/server.go:428-445) becomes the standard ``logging``
setup; profiling adds JAX profiler hooks the reference never had; and
``metrics`` is the unified in-process registry + request-trace plane
(counters/gauges/histograms/EWMAs + the periodic JSON-line emitter) that
every layer — LSP engine, lspnet transport, scheduler, miner, model —
reports into (ISSUE 3).
"""

from .config import FrameworkConfig, from_env
from .logging import configure_logging
from .metrics import Registry, ensure_emitter, registry
from .profiling import Timer, device_trace

__all__ = ["FrameworkConfig", "from_env", "configure_logging",
           "Registry", "ensure_emitter", "registry",
           "Timer", "device_trace"]
