"""Cross-process request tracing + device-timing flight recorder (ISSUE 10).

PR 3's request traces stop at the scheduler's LSP boundary: once a chunk
is granted, the miner's pipeline wait, coalesced-batch membership, device
dispatch/force latency, and jit recompiles are invisible per request.
This module is the shared substrate of the end-to-end plane; the apps
wire it up:

- **Chunk spans** (apps/miner.py): the miner records one span per served
  chunk — reader-queue wait, dispatch enqueue, pipeline wait, force/
  finalize, inter-chunk bubble gap, and (for coalesced batches) the
  shared-launch id + lane count — and ships it back PIGGYBACKED on the
  Result as a ``Span`` wire extension (bitcoin/message.py; appended only
  when tracing is on, so ``DBM_TRACE=0`` keeps stock bytes bit-for-bit;
  a stock Go endpoint drops the unknown key). Span context needs no new
  identifiers: LSP is in-order exactly-once, so the k-th Result from a
  miner answers the k-th pending chunk — the scheduler's existing
  ``(job_id, chunk idx)`` FIFO pop machinery IS the stitch key.
- **Stitching** (apps/scheduler.py): ``_on_result`` folds the span into
  the request's existing :class:`~.metrics.RequestTrace` as a
  ``miner_span`` event (same TraceBuffer/cardinality discipline as
  PR 3), naming the DOMINANT phase so a stalled request's dump reads
  "the force stalled on miner 7", not a pile of floats.
- **Jit-compile observer** (:class:`CompileObserver`, hooked at the
  model layer's launch sites): every device launch carries a static
  SIGNATURE (entry, rem, k, batch, nbatches, ...) — the same tuple the
  ``jit-static`` dbmlint analyzer guards statically. The first launch of
  a fresh signature is (trace +) compile; its elapsed is recorded
  per-signature, and a burst of NEW signatures inside a short window —
  the recompile storm an unquantized runtime scalar causes — fires a
  structured alarm (``trace.recompile_storms``) plus a flight-recorder
  dump. The dynamic complement to the static lint.
- **Flight recorder** (:class:`FlightRecorder`): a bounded ring of
  control-plane events in BOTH processes (scheduler grant/assign/alarm
  edges, miner chunk lifecycle), dumped as one JSON line on queue-age /
  in-flight alarms, sanitizer warnings, and unhandled-exception exit —
  post-mortem for the chaos failures dbmcheck's deterministic scenarios
  cannot reach in real nondeterministic runs.
- **Perfetto export** (:func:`to_chrome_trace`, ``Scheduler.
  export_trace``, ``scripts/dbmtrace.py``): stitched traces render as
  Chrome trace-event JSON — one track per process/miner/tenant, spans as
  complete (``X``) slices and lease blows/sheds/re-issues as instant
  events — loadable in ui.perfetto.dev / chrome://tracing.

Knobs (all routed through utils/_env; catalog in utils/config.py):
``DBM_TRACE`` (default 1; 0 restores stock behavior bit-for-bit),
``DBM_TRACE_FLIGHT`` (ring capacity; 0 disables the recorder),
``DBM_TRACE_STORM_N`` / ``DBM_TRACE_STORM_S`` (storm alarm: N fresh
compile signatures within S seconds). ``DBM_TRACE_XPROF`` (the XProf
logdir, utils/profiling.py) selects the ORTHOGONAL JAX device profiler;
this plane is request-scoped, that one is kernel-scoped.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from ._env import float_env as _float_env, int_env as _int_env
from .metrics import (capture_info as _capture_info,
                      proc_identity as _proc_identity,
                      registry as _registry)

_log = logging.getLogger("dbm.trace")

#: Span phase keys a miner-side chunk span may carry, in pipeline order.
#: Everything is seconds; ``launch``/``lanes`` (shared coalesced launch),
#: ``compiles`` (fresh jit signatures compiled during this chunk's
#: dispatch), ``serial`` (blocking-path chunk) and ``subs`` (in-kernel
#: sub-window count of a device-resident devloop span, ISSUE 19 — a
#: devloop chunk reports ONE dispatch phase plus this count instead of
#: zero-width per-sub dispatch/force pairs) are the non-phase extras.
#: The wire dict draws from exactly these keys — a fixed vocabulary so
#: the exporter and the golden-format test can pin keys.
SPAN_PHASES = ("queue_s", "dispatch_s", "wait_s", "force_s", "gap_s")
SPAN_EXTRAS = ("launch", "lanes", "compiles", "serial", "subs")


def enabled() -> bool:
    """True when the tracing plane is on (``DBM_TRACE``, default 1).

    Read per call (not cached at import) so tests and embedded drivers
    can toggle the knob around individual constructions — the same
    contract as ``sanitize.enabled``. With it off, every hook in the
    apps reduces to this one boolean check: no span dicts, no wire
    extension, no flight events, no observer bookkeeping.
    """
    return _int_env("DBM_TRACE", 1) != 0


def sample_rate() -> float:
    """``DBM_TRACE_SAMPLE`` (default 1.0): fraction of requests that
    allocate a real :class:`~.metrics.RequestTrace` (ISSUE 11).

    At 10k tenants the per-request trace object is itself a melt point;
    the load harness runs at e.g. 0.01 so tracing stays ON (a sampled
    request's record is complete end-to-end) without being the
    bottleneck. 1.0 is bit-for-bit today's behavior — the parity pin the
    knob-off matrix leg holds. Clamped to [0, 1]; read per call so
    embedded drivers can vary it per construction (the scheduler reads
    it once at init).
    """
    return min(1.0, max(0.0, _float_env("DBM_TRACE_SAMPLE", 1.0)))


def sample_hit(seq: int, rate: float) -> bool:
    """Deterministic sampling decision for the ``seq``-th request id.

    A Knuth multiplicative hash of the request's arrival/job sequence
    number against the rate: deterministic (the same storm samples the
    same requests on every run — load-harness comparisons stay
    apples-to-apples), uniform (no phase-locking with wave patterns the
    way a bare modulo would), and allocation-free.
    """
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return ((seq * 0x9E3779B1) & 0xFFFFFFFF) < rate * 4294967296.0


def slow_phase(span: dict) -> Optional[str]:
    """The dominant PHASE of a span dict (None when empty/malformed) —
    what a stalled chunk was actually doing, named without the ``_s``
    suffix (``force``, ``queue``, ...) to match the exported slice
    names. The stitched ``miner_span`` event carries it so a wedged
    request's trace dump names the phase, not just the miner."""
    best, best_v = None, 0.0
    for key in SPAN_PHASES:
        v = span.get(key)
        if isinstance(v, (int, float)) and v > best_v:
            best, best_v = key[:-2], float(v)
    return best


# ------------------------------------------------------------ flight recorder


class FlightRecorder:
    """Bounded ring of control-plane events, dumped on demand.

    ``record()`` is one deque append under a lock — cheap enough to ride
    every grant/assign/result edge. ``dump(why)`` logs the whole ring as
    ONE structured JSON line through ``dbm.trace`` (the same sink the
    metrics emitter uses) and counts in ``trace.flight_dumps``; the ring
    keeps accumulating afterwards (a second alarm dumps the newer
    window). ``cap=0`` disables: record() is a no-op returning
    immediately.
    """

    def __init__(self, cap: Optional[int] = None):
        self.cap = cap if cap is not None else _int_env(
            "DBM_TRACE_FLIGHT", 512)
        self._d: deque = deque(maxlen=max(1, self.cap))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._dumps = _registry().counter("trace.flight_dumps")

    def record(self, event: str, **detail) -> None:
        if self.cap <= 0:
            return
        ev = {"t": round(time.monotonic() - self._t0, 6), "event": event}
        if detail:
            ev.update(detail)
        with self._lock:
            self._d.append(ev)

    def events(self) -> list:
        with self._lock:
            return list(self._d)

    def __len__(self) -> int:
        return len(self._d)

    def dump(self, why: str) -> None:
        """One JSON line with the whole ring (oldest first). When a
        workload capture is active (ISSUE 15) the dump names it (path +
        line count) — a crash artifact points at the trace of the
        traffic that produced it."""
        if self.cap <= 0:
            return
        self._dumps.inc()
        doc = {"why": why, "events": self.events()}
        info = _capture_info()
        if info is not None:
            doc["capture"] = info
        # Same contract as the metrics emitter (ISSUE 18): a --procs
        # cluster interleaves N recorders into one stream, so the dump
        # names the role/rid/incarnation it came from.
        ident = _proc_identity()
        if ident is not None:
            doc["identity"] = ident
        _log.warning("flight recorder dump (%s): %s", why,
                     json.dumps(doc, sort_keys=True, default=str))


_flight: Optional[FlightRecorder] = None
_flight_lock = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process flight recorder (constructed on first use)."""
    global _flight
    with _flight_lock:
        if _flight is None:
            _flight = FlightRecorder()
        return _flight


def flight(event: str, **detail) -> None:
    """Record one control-plane event into the process ring (no-op when
    the plane or the ring is off — one boolean check)."""
    if not enabled():
        return
    flight_recorder().record(event, **detail)


def flight_dump(why: str) -> None:
    """Dump the process ring (no-op when the plane or ring is off)."""
    if not enabled():
        return
    flight_recorder().dump(why)


_excepthook_installed = False


def _install_excepthook() -> None:
    """Chain-wrap ``sys.excepthook`` so an unhandled-exception exit dumps
    the flight recorder first — the post-mortem window for the crash
    shapes chaos testing cannot reproduce deterministically. Idempotent;
    never installed when the plane is off at ensure time."""
    global _excepthook_installed
    if _excepthook_installed:
        return
    _excepthook_installed = True
    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            flight_recorder().record("unhandled_exception",
                                     exception=repr(exc)[:200])
            flight_recorder().dump("unhandled-exception exit")
        except Exception:   # noqa: BLE001 — never mask the real crash
            pass
        prev(exc_type, exc, tb)

    sys.excepthook = _hook


def ensure_tracer() -> bool:
    """Arm the process-level pieces iff ``DBM_TRACE=1``; returns enabled().

    Scheduler and miner call this at construction (the ensure_emitter /
    ensure_sanitizer shape): one knob arms the flight recorder's
    crash-exit dump and the compile observer in every endpoint with no
    call-site changes.
    """
    if not enabled():
        return False
    flight_recorder()
    _install_excepthook()
    return True


# ---------------------------------------------------------- compile observer


class CompileObserver:
    """Per-signature device-launch/compile bookkeeping + storm alarm.

    ``launch(sig)`` is called (via :func:`observe_launch`) around every
    jitted device dispatch at the model layer with the launch's STATIC
    signature tuple. A signature's first launch pays jit trace+compile
    on the calling thread, so its elapsed is the compile estimate; later
    launches only count. ``storm_n`` fresh signatures within
    ``storm_s`` seconds is a RECOMPILE STORM — the dynamic symptom of a
    runtime-derived scalar reaching a static boundary (the bug class the
    ``jit-static`` dbmlint analyzer catches in source) — and fires a
    structured warning + ``trace.recompile_storms`` + a flight dump,
    once per storm episode (re-armed once the window drains).
    """

    def __init__(self, storm_n: Optional[int] = None,
                 storm_s: Optional[float] = None):
        # Default 12: a COLD process legitimately warms ~8 fresh
        # signatures (digit classes x pow2 subs + the batch-width
        # buckets) in its first seconds — the bound must clear that
        # startup burst, while a true unquantized churn mints a fresh
        # signature per REQUEST and blows past any constant.
        self.storm_n = storm_n if storm_n is not None else _int_env(
            "DBM_TRACE_STORM_N", 12)
        self.storm_s = storm_s if storm_s is not None else _float_env(
            "DBM_TRACE_STORM_S", 30.0)
        self._lock = threading.Lock()
        self.sigs: Dict[tuple, dict] = {}      # sig -> {n, compile_s}
        self._fresh: deque = deque()           # monotonic stamps of new sigs
        self._storming = False
        self._compiles = _registry().counter("trace.jit_compiles")
        self._launches = _registry().counter("trace.observed_launches")
        self._storms = _registry().counter("trace.recompile_storms")
        self._worst = _registry().gauge("trace.jit_compile_worst_s")

    def launch(self, sig: tuple, seconds: float) -> Optional[float]:
        """Record one launch of ``sig`` that took ``seconds`` on the
        dispatching thread. Returns the compile estimate when this was
        the signature's FIRST launch (the span records it), else None."""
        now = time.monotonic()
        storm = None
        with self._lock:
            self._launches.inc()
            rec = self.sigs.get(sig)
            if rec is not None:
                rec["n"] += 1
                return None
            self.sigs[sig] = {"n": 1, "compile_s": seconds}
            self._compiles.inc()
            if seconds > self._worst.value:
                self._worst.set(seconds)
            self._fresh.append(now)
            while self._fresh and now - self._fresh[0] > self.storm_s:
                self._fresh.popleft()
            if len(self._fresh) >= self.storm_n:
                if not self._storming:
                    self._storming = True
                    self._storms.inc()
                    storm = len(self._fresh)
            else:
                self._storming = False
        if storm is not None:
            _log.warning(
                "recompile storm: %d fresh jit signatures within %.0fs "
                "(bound %d) — a runtime-derived value is reaching a "
                "static jit boundary (latest: %r); expect multi-second "
                "stalls per launch until the signature set stabilizes",
                storm, self.storm_s, self.storm_n, sig)
            flight("recompile_storm", fresh=storm, sig=repr(sig)[:120])
            flight_dump("recompile storm")
        return seconds

    def snapshot(self) -> dict:
        """JSON-native per-signature view (ordered by compile cost)."""
        with self._lock:
            items = [(repr(sig), dict(rec))
                     for sig, rec in self.sigs.items()]
        items.sort(key=lambda kv: -kv[1].get("compile_s", 0.0))
        return {sig: {"n": rec["n"],
                      "compile_s": round(rec["compile_s"], 6)}
                for sig, rec in items}


_observer: Optional[CompileObserver] = None
_observer_lock = threading.Lock()


def compile_observer() -> CompileObserver:
    """The process compile observer (constructed on first use)."""
    global _observer
    with _observer_lock:
        if _observer is None:
            _observer = CompileObserver()
        return _observer


class observe_launch:
    """Context manager the model layer wraps each jitted dispatch in:

        with observe_launch(("search_span", rem, k, batch, nbatches)) as ob:
            triple = search_span(...)
        # ob.compile_s is set when this launch compiled a fresh signature

    With the plane off this is one boolean check and no bookkeeping.
    """

    __slots__ = ("sig", "compile_s", "_t0", "_on")

    def __init__(self, sig: tuple):
        self.sig = sig
        self.compile_s: Optional[float] = None
        self._on = enabled()
        self._t0 = 0.0

    def __enter__(self) -> "observe_launch":
        if self._on:
            self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if self._on and exc_type is None:
            self.compile_s = compile_observer().launch(
                self.sig, time.monotonic() - self._t0)


# ----------------------------------------------------------------- trackset


class TrackSet:
    """Export-track registry under the metrics cardinality discipline.

    The Perfetto export draws one track per miner and per tenant; track
    identity is a labeled name exactly like a metric series, and the
    same failure mode applies — conn churn minting a track per dead conn
    id grows the export without bound. Tracks therefore live behind the
    ``DBM_METRICS_MAX_SERIES`` bound (overflow collapses into one
    ``{overflow=true}`` track) and MUST be retired where the entity dies
    (miner drop, tenant GC) — the ``cardinality`` dbmlint analyzer
    checks ``.track()`` sites for a same-module ``.retire()`` path, the
    same rule it applies to labeled metric series.
    """

    _OVERFLOW: Tuple[Tuple[str, str], ...] = (("overflow", "true"),)

    def __init__(self, max_tracks: Optional[int] = None):
        self.max_tracks = (max_tracks if max_tracks is not None
                           else _int_env("DBM_METRICS_MAX_SERIES", 64))
        self._lock = threading.Lock()
        self._d: Dict[str, Dict[tuple, int]] = {}
        self._next_tid = 0
        self._overflows = 0

    @staticmethod
    def _key(labels: dict) -> tuple:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def track(self, name: str, **labels) -> int:
        """Stable integer track id for one labeled entity (registers on
        first sight; collapses to the overflow track past the bound)."""
        key = self._key(labels)
        with self._lock:
            family = self._d.setdefault(name, {})
            tid = family.get(key)
            if tid is None:
                if key and len(family) >= self.max_tracks \
                        and key != self._OVERFLOW:
                    self._overflows += 1
                    key = self._OVERFLOW
                    tid = family.get(key)
                if tid is None:
                    self._next_tid += 1
                    tid = family[key] = self._next_tid
            return tid

    def retire(self, name: str, **labels) -> None:
        """Free one entity's track slot (no-op when absent) — the
        miner-drop / tenant-GC path, mirroring ``Registry.remove``."""
        with self._lock:
            family = self._d.get(name)
            if family is not None:
                family.pop(self._key(labels), None)

    def items(self, name: str) -> list:
        """``[(labels_tuple, tid), ...]`` of one family's live tracks."""
        with self._lock:
            return list(self._d.get(name, {}).items())

    def __len__(self) -> int:
        with self._lock:
            return sum(len(f) for f in self._d.values())


# ------------------------------------------------------------- chrome export

#: Scheduler-side request events drawn as INSTANT markers on the owning
#: tenant's track (everything else is a slice or span detail).
_INSTANT_EVENTS = ("lease_blown", "reissue", "quarantine", "park",
                   "queue_alarm", "inflight_alarm", "miner_drop",
                   "stale_result", "cache_hit")

#: Fixed synthetic pids: one "process" per role. Miners get
#: ``_PID_MINERS`` with one thread per miner conn; tenants ride the
#: scheduler process with one thread per tenant.
_PID_SCHED = 1
_PID_MINERS = 2


def _span_events(trace_dict: dict, base_us: int, t0_us: int,
                 tenant_tid: int, miner_tids: dict) -> list:
    """Chrome events for ONE stitched RequestTrace dict.

    The scheduler timeline anchors everything: request-level slices
    (queued, in-flight) land on the tenant's track; each ``miner_span``
    is laid out BACKWARDS from its fold stamp on the owning miner's
    track (miner clocks are a different process's monotonic — the span
    ships durations, the scheduler stamp places them)."""
    events = trace_dict.get("events", [])
    meta = trace_dict.get("meta", {})
    key = trace_dict.get("key")
    out = []

    def at(ev) -> int:
        return t0_us + int(ev["t"] * 1e6) - base_us

    by_name: dict = {}
    for ev in events:
        by_name.setdefault(ev["event"], []).append(ev)
    enq = by_name.get("enqueue", [None])[0]
    disp = by_name.get("dispatch", [None])[0]
    done = (by_name.get("reply", []) or by_name.get("cancel", [None]))[0]
    args = {"key": str(key), "range": [meta.get("lower"),
                                       meta.get("upper")]}
    if meta.get("target"):
        args["target"] = meta["target"]
    if enq is not None and disp is not None:
        out.append({"name": "queued", "ph": "X", "pid": _PID_SCHED,
                    "tid": tenant_tid, "ts": at(enq),
                    "dur": max(0, at(disp) - at(enq)), "args": args})
    start = disp if disp is not None else enq
    if start is not None and done is not None:
        out.append({"name": f"request {key}", "ph": "X",
                    "pid": _PID_SCHED, "tid": tenant_tid, "ts": at(start),
                    "dur": max(0, at(done) - at(start)), "args": args})
    for name in _INSTANT_EVENTS:
        for ev in by_name.get(name, []):
            out.append({"name": name, "ph": "i", "s": "t",
                        "pid": _PID_SCHED, "tid": tenant_tid,
                        "ts": at(ev),
                        "args": {k: v for k, v in ev.items()
                                 if k not in ("t", "event")}})
    for ev in by_name.get("miner_span", []):
        miner = str(ev.get("miner"))
        tid = miner_tids.get(miner)
        if tid is None:
            tid = miner_tids[miner] = \
                max(miner_tids.values(), default=0) + 1
        total_us = sum(int(float(ev.get(k, 0.0) or 0.0) * 1e6)
                       for k in SPAN_PHASES)
        ts = at(ev) - total_us
        sargs = {"job": str(key), "idx": ev.get("idx")}
        if ev.get("launch") is not None:
            sargs["launch"] = ev["launch"]
            sargs["lanes"] = ev.get("lanes")
        if ev.get("subs") is not None:
            sargs["subs"] = ev["subs"]
        if ev.get("slow"):
            sargs["slow"] = ev["slow"]
        # Layout order differs from the vocabulary order: gap_s is the
        # executor's idle time BEFORE this chunk, so it renders FIRST —
        # ending the chain at force so the force slice abuts the fold
        # stamp (rendering gap last would displace force earlier and
        # draw a phantom post-force stall — code review).
        for phase in ("gap_s",) + tuple(k for k in SPAN_PHASES
                                        if k != "gap_s"):
            dur = int(float(ev.get(phase, 0.0) or 0.0) * 1e6)
            if dur <= 0:
                continue
            out.append({"name": phase[:-2], "ph": "X", "pid": _PID_MINERS,
                        "tid": tid, "ts": ts, "dur": dur, "args": sargs})
            ts += dur
    return out


def to_chrome_trace(trace_dicts: list, tenant_tracks: Optional[dict] = None,
                    miner_tracks: Optional[dict] = None) -> dict:
    """Chrome trace-event JSON (Perfetto-loadable) from stitched trace
    dicts (``RequestTrace.to_dict()`` shape, plus an optional ``t0``
    monotonic stamp — absent t0s are laid out in list order).

    ``tenant_tracks`` / ``miner_tracks`` map entity id strings to track
    ids (the scheduler passes its :class:`TrackSet` view); unknown
    entities get tracks appended after the known ones. Events are sorted
    by (pid, tid, ts) so every track's timeline is monotonic — the
    golden-format contract tests/test_trace.py pins.
    """
    tenant_tids = dict(tenant_tracks or {})
    miner_tids = dict(miner_tracks or {})
    t0s = [d.get("t0") for d in trace_dicts]
    known = [t for t in t0s if isinstance(t, (int, float))]
    base = min(known) if known else 0.0
    base_us = int(base * 1e6)
    events: list = []
    for i, d in enumerate(trace_dicts):
        t0 = d.get("t0")
        t0_us = int(t0 * 1e6) if isinstance(t0, (int, float)) \
            else base_us + i
        tenant = str(d.get("meta", {}).get("client"))
        tid = tenant_tids.get(tenant)
        if tid is None:
            tid = tenant_tids[tenant] = \
                max(tenant_tids.values(), default=0) + 1
        events.extend(_span_events(d, base_us, t0_us, tid, miner_tids))
    meta = [
        {"name": "process_name", "ph": "M", "pid": _PID_SCHED, "tid": 0,
         "args": {"name": "scheduler"}},
        {"name": "process_name", "ph": "M", "pid": _PID_MINERS, "tid": 0,
         "args": {"name": "miners"}},
    ]
    for tenant, tid in sorted(tenant_tids.items(), key=lambda kv: kv[1]):
        meta.append({"name": "thread_name", "ph": "M", "pid": _PID_SCHED,
                     "tid": tid, "args": {"name": f"tenant {tenant}"}})
    for miner, tid in sorted(miner_tids.items(), key=lambda kv: kv[1]):
        meta.append({"name": "thread_name", "ph": "M", "pid": _PID_MINERS,
                     "tid": tid, "args": {"name": f"miner {miner}"}})
    events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"],
                               -e.get("dur", 0)))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
