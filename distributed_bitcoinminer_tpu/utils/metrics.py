"""In-process metrics registry + request traces for every framework layer.

The reference system's only visibility is per-packet stderr debug lines and
a microsecond file logger (SURVEY §5); this build's robustness plane (leases,
quarantine, speculative re-issue, result cache) and compute plane (hoisted
kernels, tier degradation) need first-class numbers. This module is that
plane: a lightweight, thread-safe registry of

- **counters** — monotonic event counts;
- **gauges** — last-write-wins scalars;
- **histograms** — fixed-bucket latency/occupancy distributions
  (cumulative-``le`` buckets, Prometheus-style, plus count and sum);
- **EWMAs** — irregular-series exponentially-weighted moving averages
  (``alpha = 1 - exp(-dt/tau)``), for rates like nonces/s;

with named-label support (``registry.counter("lsp.retransmits",
backoff="2")``). Label cardinality is bounded per metric family: past
``max_series`` distinct label sets, further sets collapse into one
``{overflow="true"}`` series, so a conn-id label can never grow memory
without bound. ``series_overflow`` counts LOOKUPS routed to an overflow
series (not distinct collapsed sets — tracking those would itself need
unbounded memory): zero means the bound never bit; a growing value means
real traffic is being aggregated away and ``max_series`` is too small.

Design constraints, in order:

1. **Near-zero overhead when idle.** No background work exists unless an
   emitter is started; an update is one short critical section on the
   registry lock (plain attribute arithmetic — no allocation on the hot
   path); fetching a labeled child is a dict lookup callers can (and the
   per-packet LSP call sites do) hoist out of their loops.
2. **Thread-safe.** The miner computes in worker threads while the asyncio
   loop serves LSP; a shared ``RLock`` per registry makes every update and
   ``snapshot()`` atomic. Cross-registry lock order is strictly
   parent->mounted (only ``snapshot`` crosses), so no cycles.
3. **JSON-stable snapshots.** ``snapshot()`` returns only JSON-native
   types with deterministically ordered keys (sections sorted, series keys
   sorted, buckets fixed at construction) so two snapshots of the same
   process diff cleanly — the property ``BENCH_*.json`` comparisons rely
   on (guarded by tests/test_metrics.py).

Process wiring: :func:`registry` returns the process-default registry that
the LSP engine, lspnet transport, miner worker, and model layer all write
to. Subsystems with per-instance stats (the scheduler) keep their own
:class:`Registry` and ``mount()`` it into the default one under a prefix,
so one ``snapshot()`` still covers the whole process. :func:`ensure_emitter`
starts (once per process) a daemon thread that logs one JSON line per
``DBM_METRICS_INTERVAL_S`` seconds through the existing ``dbm`` logger tree
(``dbm.metrics``), plus an atexit final dump — 0 disables the emitter.

Request traces (:class:`RequestTrace` / :class:`TraceBuffer`) are the
per-request complement of the aggregate registry: an ordered, timestamped
span record (enqueue -> dispatch -> result -> merge -> reply) keyed by the
scheduler's existing ``job_id`` — no wire-format change — retrievable via
``Scheduler.trace(request_id)`` and dumped wholesale on a queue-age alarm
so a stalled request explains itself.
"""

from __future__ import annotations

import atexit
import json
import logging
import math
import threading
import time
from bisect import bisect_left
from typing import Callable, Dict, Optional, Tuple

from ._env import float_env as _float_env, int_env as _int_env

_log = logging.getLogger("dbm.metrics")

#: Default histogram buckets (seconds): spans sub-ms LSP RTTs through
#: multi-minute wedged-chunk latencies. Cumulative ``le`` semantics; an
#: implicit +Inf bucket is the final count.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

#: Occupancy buckets (counts): sliding windows, FIFO depths, queue lengths.
OCCUPANCY_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                     256.0, 512.0)

_LabelKey = Tuple[Tuple[str, str], ...]
_OVERFLOW_KEY: _LabelKey = (("overflow", "true"),)


class Counter:
    """Monotonic event counter."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _snap(self):
        return self._value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def _snap(self):
        return round(self._value, 6)


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` buckets + count + sum).

    Buckets are frozen at construction so every snapshot of a series has
    the identical shape — the stable-key property BENCH diffs rely on.
    """

    __slots__ = ("_lock", "buckets", "_counts", "_count", "_sum")

    def __init__(self, lock: threading.RLock,
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS_S):
        self._lock = lock
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        i = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _snap(self):
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        # Cumulative counts, one per finite bound; `count` is the +Inf one.
        cum, acc = [], 0
        for c in counts[:-1]:
            acc += c
            cum.append(acc)
        return {"le": list(self.buckets), "counts": cum,
                "count": total, "sum": round(s, 6)}


class Ewma:
    """Irregular-series EWMA: ``alpha = 1 - exp(-dt / tau)`` per sample.

    ``observe(x)`` folds a new sample in, weighted by the wall-clock gap
    since the previous one — the standard way to EWMA rate samples that
    arrive at uneven intervals (a per-chunk nonces/s sample every few
    hundred ms under load, minutes apart when idle).
    """

    __slots__ = ("_lock", "tau_s", "_value", "_t", "_clock", "_n")

    def __init__(self, lock: threading.RLock, tau_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = lock
        self.tau_s = tau_s
        self._value: Optional[float] = None
        self._t = 0.0
        self._n = 0
        self._clock = clock

    def observe(self, x: float) -> None:
        now = self._clock()
        with self._lock:
            if self._value is None:
                self._value = float(x)
            else:
                dt = max(now - self._t, 1e-9)
                alpha = 1.0 - math.exp(-dt / self.tau_s)
                self._value += alpha * (x - self._value)
            self._t = now
            self._n += 1

    @property
    def value(self) -> Optional[float]:
        return self._value

    def _snap(self):
        with self._lock:
            v = self._value
            n = self._n
        return {"value": round(v, 6) if v is not None else None,
                "samples": n}


_KINDS = ("counters", "gauges", "histograms", "ewmas")


class Registry:
    """One metric namespace: families of labeled series, snapshot-able.

    ``max_series`` bounds distinct label sets per family (overflow
    collapses into one ``{overflow="true"}`` series). Registries can be
    ``mount()``-ed into each other so a per-instance registry (the
    scheduler's) shows up, prefixed, in the process snapshot.
    """

    def __init__(self, max_series: Optional[int] = None):
        self._lock = threading.RLock()
        self.max_series = (max_series if max_series is not None
                           else _int_env("DBM_METRICS_MAX_SERIES", 64))
        # kind -> name -> labelkey -> metric
        self._families: Dict[str, Dict[str, Dict[_LabelKey, object]]] = {
            k: {} for k in _KINDS}
        self._mounts: Dict[str, "Registry"] = {}
        self._overflows = 0

    # ------------------------------------------------------------- factories

    def _series(self, kind: str, name: str, labels: dict, factory):
        key: _LabelKey = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            family = self._families[kind].setdefault(name, {})
            metric = family.get(key)
            if metric is None:
                if key and len(family) >= self.max_series \
                        and key != _OVERFLOW_KEY:
                    # Cardinality bound: collapse, never grow unbounded.
                    # Counted per LOOKUP routed here (module docstring) —
                    # the original key is deliberately not remembered.
                    self._overflows += 1
                    key = _OVERFLOW_KEY
                    metric = family.get(key)
                if metric is None:
                    metric = factory(self._lock)
                    family[key] = metric
            return metric

    def counter(self, name: str, **labels) -> Counter:
        return self._series("counters", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._series("gauges", name, labels, Gauge)

    def histogram(self, name: str,
                  buckets: Tuple[float, ...] = LATENCY_BUCKETS_S,
                  **labels) -> Histogram:
        return self._series("histograms", name, labels,
                            lambda lock: Histogram(lock, buckets))

    def ewma(self, name: str, tau_s: float = 30.0, **labels) -> Ewma:
        return self._series("ewmas", name, labels,
                            lambda lock: Ewma(lock, tau_s))

    def remove(self, name: str, **labels) -> None:
        """Delete one labeled series (every kind; no-op when absent).

        Frees the family's cardinality slot. Call when the labeled entity
        is gone for good — e.g. the scheduler drops a miner's rate/lease
        gauges on disconnect, so miner churn neither leaves dead conn-ids
        in snapshots nor exhausts ``max_series`` over a long process life.
        """
        key: _LabelKey = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            for kind in _KINDS:
                family = self._families[kind].get(name)
                if family is not None:
                    family.pop(key, None)

    # --------------------------------------------------------------- mounts

    def mount(self, prefix: str, other: "Registry") -> None:
        """Include ``other``'s snapshot under ``prefix.`` in this one.

        Re-mounting the same prefix replaces the previous registry (a new
        scheduler instance supersedes the old one's series).
        """
        if other is self:
            raise ValueError("a registry cannot mount itself")
        with self._lock:
            self._mounts[prefix] = other

    # ------------------------------------------------------------- snapshot

    @staticmethod
    def _series_key(name: str, key: _LabelKey) -> str:
        if not key:
            return name
        return name + "{" + ",".join(f"{k}={v}" for k, v in key) + "}"

    def snapshot(self) -> dict:
        """JSON-native, stable-keyed view of every series (incl. mounts).

        Shape: ``{"counters": {...}, "gauges": {...}, "histograms": {...},
        "ewmas": {...}, "series_overflow": N}`` with all series keys
        sorted. Safe to ``json.dumps`` as-is.
        """
        with self._lock:
            out: dict = {}
            overflow = self._overflows
            for kind in _KINDS:
                section: Dict[str, object] = {}
                for name, family in self._families[kind].items():
                    for key, metric in family.items():
                        section[self._series_key(name, key)] = metric._snap()
                out[kind] = dict(sorted(section.items()))
            mounts = dict(self._mounts)
        for prefix, other in sorted(mounts.items()):
            sub = other.snapshot()
            overflow += sub.get("series_overflow", 0)
            for kind in _KINDS:
                merged = out[kind]
                for k, v in sub[kind].items():
                    merged[f"{prefix}.{k}"] = v
                out[kind] = dict(sorted(merged.items()))
        out["series_overflow"] = overflow
        return out


# ------------------------------------------------------------------ emitter


class Emitter(threading.Thread):
    """Daemon thread logging one JSON snapshot line per interval.

    Rides the existing ``dbm`` logger tree (``dbm.metrics``) so the line
    lands wherever ``configure_logging`` pointed the process — the same
    sink as every other structured log. ``stop()`` emits one final line.
    """

    def __init__(self, reg: Registry, interval_s: float,
                 logger: Optional[logging.Logger] = None):
        super().__init__(name="dbm-metrics-emitter", daemon=True)
        self.registry = reg
        self.interval_s = interval_s
        self.logger = logger if logger is not None else _log
        self._stop = threading.Event()
        self._t0 = time.monotonic()

    def emit(self, final: bool = False) -> None:
        doc = {"event": "metrics", "final": final,
               "interval_s": self.interval_s,
               "uptime_s": round(time.monotonic() - self._t0, 3),
               "snapshot": self.registry.snapshot()}
        # Crash/exit artifacts name the active workload capture
        # (ISSUE 15): the final atexit dump is often the only line an
        # operator has after an incident, and "which traffic produced
        # this" should be on it.
        info = capture_info()
        if info is not None:
            doc["capture"] = info
        # Interleaved --procs logs attribute without pid cross-referencing
        # (ISSUE 18): a multi-process run funnels N emitters into one
        # stream, and "whose snapshot is this" must be on the line itself.
        ident = proc_identity()
        if ident is not None:
            doc["identity"] = ident
        self.logger.info("%s", json.dumps(doc, sort_keys=True))

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.emit()
            except Exception:  # noqa: BLE001 — the emitter must never die
                self.logger.exception("metrics emit failed; continuing")

    def stop(self, final_dump: bool = True) -> None:
        if not self._stop.is_set():
            self._stop.set()
            if final_dump:
                self.emit(final=True)


_default_registry = Registry()
_emitter: Optional[Emitter] = None
_emitter_lock = threading.Lock()

# Active workload capture (ISSUE 15): the capture plane registers a
# zero-argument info callable here so CRASH ARTIFACTS name the workload
# that produced them — the flight-recorder dump (utils/trace.py) and
# the emitter's snapshot lines (incl. the atexit final dump) both embed
# it. Lives in this module because it is the bottom layer both sides
# already import (trace.py cannot be imported from here, and the apps
# layer cannot be imported from either).
_capture_info = None
_capture_info_lock = threading.Lock()


def set_capture_info(fn) -> None:
    """Register the active capture's info callable (or None to clear)."""
    global _capture_info
    with _capture_info_lock:
        _capture_info = fn


def clear_capture_info(fn) -> None:
    """Clear the slot iff ``fn`` still owns it (a test's short-lived
    capture must not clobber the process capture's registration)."""
    global _capture_info
    with _capture_info_lock:
        if _capture_info is fn:
            _capture_info = None


def capture_info() -> Optional[dict]:
    """The active capture's ``{"path", "lines", ...}``, or None.

    Never raises: a capture mid-close returning garbage must not take
    down the alarm path embedding this."""
    with _capture_info_lock:
        fn = _capture_info
    if fn is None:
        return None
    try:
        info = fn()
    except Exception:   # noqa: BLE001 — crash-artifact path, best effort
        return None
    return info if isinstance(info, dict) else None


# Process identity (ISSUE 18): an env-armed process (router / replica /
# miner agent) registers its role/rid/incarnation here so every emitter
# snapshot line and flight-recorder dump self-attributes — the same
# triple the rollup plane stamps onto published metric blobs. Same slot
# discipline as the capture info above, and in this module for the same
# layering reason.
_proc_identity: Optional[dict] = None
_proc_identity_lock = threading.Lock()


def set_proc_identity(role: Optional[str], rid=None,
                      incarnation: Optional[str] = None) -> None:
    """Register this process's identity triple (``role=None`` clears)."""
    global _proc_identity
    with _proc_identity_lock:
        if role is None:
            _proc_identity = None
        else:
            _proc_identity = {"role": str(role), "rid": rid,
                              "inc": incarnation}


def proc_identity() -> Optional[dict]:
    """A copy of the registered identity dict, or None (never raises)."""
    with _proc_identity_lock:
        ident = _proc_identity
    return dict(ident) if ident is not None else None


def registry() -> Registry:
    """The process-default registry every built-in layer writes to."""
    return _default_registry


def ensure_emitter(interval_s: Optional[float] = None) -> Optional[Emitter]:
    """Start the process emitter once; later calls return the running one.

    ``interval_s=None`` reads ``DBM_METRICS_INTERVAL_S`` (default 30.0);
    ``<= 0`` disables (returns None without starting anything — the
    "near-zero overhead when idle" contract). The final atexit dump is
    registered with the first started emitter.
    """
    if interval_s is None:
        interval_s = _float_env("DBM_METRICS_INTERVAL_S", 30.0)
    if interval_s <= 0:
        return None
    global _emitter
    with _emitter_lock:
        if _emitter is None or not _emitter.is_alive():
            _emitter = Emitter(_default_registry, interval_s)
            _emitter.start()
            atexit.register(_final_dump)
        return _emitter


def _final_dump() -> None:
    with _emitter_lock:
        em = _emitter
    if em is not None:
        em.stop(final_dump=True)


# ------------------------------------------------------------------- traces


class RequestTrace:
    """Ordered, timestamped span record for one request.

    Events are ``{"t": seconds-since-trace-start, "event": name, ...}``
    dicts; the record is *closed* once a terminal event (``reply`` or
    ``cancel``) lands. Event count is capped so a pathological request
    (thousands of sweeps) cannot grow one trace without bound — overflow
    is counted, not silently dropped.
    """

    MAX_EVENTS = 512

    #: Real traces are sampled-in; :class:`NullRequestTrace` overrides.
    null = False

    __slots__ = ("key", "meta", "events", "dropped", "_t0", "_lock")

    def __init__(self, **meta):
        self.key = None            # set by TraceBuffer.register
        self.meta = meta
        self.events: list = []
        self.dropped = 0
        self._t0 = time.monotonic()
        self._lock = threading.Lock()

    def event(self, name: str, **detail) -> None:
        ev = {"t": round(time.monotonic() - self._t0, 6), "event": name}
        ev.update(detail)
        with self._lock:
            if len(self.events) >= self.MAX_EVENTS \
                    and name not in ("reply", "cancel"):
                # Terminal events bypass the cap: a trace that filled up
                # with sweep noise must still CLOSE when the request
                # finally replies — the operator contract reads "last
                # event is reply" as completed, and the buffer's eviction
                # preference keys on closure.
                self.dropped += 1
                return
            self.events.append(ev)

    @property
    def closed(self) -> bool:
        with self._lock:
            return any(e["event"] in ("reply", "cancel")
                       for e in reversed(self.events))

    @property
    def t0(self) -> float:
        """Monotonic birth stamp (event ``t`` values are relative to it;
        the Perfetto exporter uses it to place traces on one timeline)."""
        return self._t0

    def to_dict(self) -> dict:
        """JSON-native dump (the queue-age alarm logs this wholesale)."""
        with self._lock:
            events = [dict(e) for e in self.events]
            dropped = self.dropped
        out = {"key": self.key, "meta": dict(self.meta), "events": events}
        if dropped:
            out["events_dropped"] = dropped
        return out


class NullRequestTrace:
    """Shared no-op stand-in for an UNSAMPLED request's trace
    (``DBM_TRACE_SAMPLE``, ISSUE 11).

    At 10k tenants the per-request :class:`RequestTrace` allocation —
    object + lock + an event dict per lifecycle edge — is itself a
    control-plane melt point. An unsampled request carries this
    singleton instead: every ``event()`` is one no-op method call, it
    never registers in a :class:`TraceBuffer` (``register`` drops it),
    and it reports ``closed`` so span-completeness checks skip it.
    ``DBM_TRACE_SAMPLE=1.0`` (the default) never constructs it — today's
    behavior bit-for-bit.
    """

    __slots__ = ()

    null = True
    key = None
    meta: dict = {}
    events: tuple = ()
    dropped = 0
    closed = True
    t0 = 0.0

    def event(self, name: str, **detail) -> None:
        pass

    def to_dict(self) -> dict:
        return {"key": None, "meta": {}, "events": [], "sampled": False}


#: The one shared unsampled-trace instance (it is stateless).
NULL_TRACE = NullRequestTrace()


class TraceBuffer:
    """Bounded LRU store of traces, keyed by request id.

    Eviction prefers CLOSED traces: a burst of short-lived entries (e.g.
    cache-replay traces during a retry storm) must not evict the live
    in-flight request's still-open trace — the one record the alarm dump
    exists to preserve. Reads refresh recency, so an actively-updated
    trace stays resident.
    """

    def __init__(self, cap: Optional[int] = None):
        self.cap = cap if cap is not None else _int_env(
            "DBM_METRICS_TRACE_CAP", 256)
        self._d: Dict[object, RequestTrace] = {}
        self._lock = threading.Lock()

    def new(self, **meta) -> RequestTrace:
        """A fresh, not-yet-registered trace (queued requests have no
        job_id yet; they register at dispatch)."""
        return RequestTrace(**meta)

    def register(self, key, trace: RequestTrace) -> None:
        if trace.null:
            return     # unsampled (DBM_TRACE_SAMPLE): nothing to retain
        trace.key = key
        with self._lock:
            self._d.pop(key, None)
            self._d[key] = trace
            while len(self._d) > self.cap:
                victim = next((k for k, t in self._d.items() if t.closed),
                              None)
                if victim is None:      # everything open: oldest goes
                    victim = next(iter(self._d))
                self._d.pop(victim)

    def get(self, key) -> Optional[RequestTrace]:
        with self._lock:
            trace = self._d.pop(key, None)
            if trace is not None:
                self._d[key] = trace    # LRU refresh
            return trace

    def items(self):
        with self._lock:
            return list(self._d.items())

    def __len__(self) -> int:
        return len(self._d)
