"""One config object for a whole endpoint (transport + compute plane).

The reference threads ``lsp.Params`` plus ad-hoc CLI flags through every
binary (ref: lsp/params.go:8-42, srunner.go:15-24, server/server.go:447-457);
here those knobs live in a single dataclass with environment overrides so
every process — scheduler, miner, runner — is configured the same way.

Environment variables:

- ``DBM_COMPUTE``: ``auto`` (default; widest JAX plane), ``host`` (native
  C++/SHA-NI scan, no JAX), ``jax`` (force single-device JAX).
- ``DBM_BATCH``: per-device lane count per device step.
- ``DBM_EPOCH_LIMIT`` / ``DBM_EPOCH_MILLIS`` / ``DBM_WINDOW`` /
  ``DBM_MAX_BACKOFF``: transport parameters (defaults 5/2000/1/0, matching
  lsp/params.go:29-36).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..lsp.params import Params


@dataclass
class FrameworkConfig:
    params: Params = field(default_factory=Params)
    compute: str = "auto"          # auto | host | jax
    batch: int | None = None       # None -> platform default

    def make_searcher(self, data: str):
        """Build the configured searcher for one message string."""
        if self.compute == "host":
            from ..apps.miner import HostSearcher
            return HostSearcher(data)
        if self.compute == "jax":
            from ..models import NonceSearcher
            return NonceSearcher(data, batch=self.batch or (1 << 20))
        from ..apps.miner import default_searcher_factory
        return default_searcher_factory(data, self.batch)


def _int_env(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def from_env() -> FrameworkConfig:
    params = Params(
        epoch_limit=_int_env("DBM_EPOCH_LIMIT", Params().epoch_limit),
        epoch_millis=_int_env("DBM_EPOCH_MILLIS", Params().epoch_millis),
        window_size=_int_env("DBM_WINDOW", Params().window_size),
        max_backoff_interval=_int_env("DBM_MAX_BACKOFF",
                                      Params().max_backoff_interval),
    )
    batch = os.environ.get("DBM_BATCH")
    return FrameworkConfig(
        params=params,
        compute=os.environ.get("DBM_COMPUTE", "auto"),
        batch=int(batch) if batch else None,
    )
