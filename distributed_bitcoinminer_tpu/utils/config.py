"""One config object for a whole endpoint (transport + compute plane).

The reference threads ``lsp.Params`` plus ad-hoc CLI flags through every
binary (ref: lsp/params.go:8-42, srunner.go:15-24, server/server.go:447-457);
here those knobs live in a single dataclass with environment overrides so
every process — scheduler, miner, runner — is configured the same way.

Environment variables:

- ``DBM_COMPUTE``: ``auto`` (default; widest JAX plane), ``host`` (native
  C++/SHA-NI scan, no JAX), ``jax`` (force single-device JAX), or a
  device-kernel tier — ``jnp`` / ``pallas`` — which keeps auto searcher
  selection but pins the kernel (models.miner_model.default_tier).
- ``DBM_BATCH``: per-device lane count per device step.
- ``DBM_EPOCH_LIMIT`` / ``DBM_EPOCH_MILLIS`` / ``DBM_WINDOW`` /
  ``DBM_MAX_BACKOFF``: transport parameters (defaults 5/2000/1/0, matching
  lsp/params.go:29-36).
- ``DBM_LEASE`` (0 disables) / ``DBM_LEASE_GRACE_S`` / ``DBM_LEASE_FACTOR``
  / ``DBM_LEASE_FLOOR_S`` / ``DBM_LEASE_TICK_S`` / ``DBM_LEASE_QUARANTINE``:
  scheduler chunk-lease plane (apps/scheduler.py): a chunk whose lease
  expires is speculatively re-issued, and a miner that blows
  ``DBM_LEASE_QUARANTINE`` consecutive leases is quarantined from new
  assignments until it answers again.
- ``DBM_RETRY_ATTEMPTS`` / ``DBM_RETRY_TIMEOUT_S`` / ``DBM_RETRY_BACKOFF_S``
  / ``DBM_RETRY_BACKOFF_CAP_S``: client submit-with-retry plane
  (apps/client.py submit_with_retry).
- ``DBM_CACHE`` (0 disables) / ``DBM_CACHE_SIZE``: scheduler-side
  ``(data, lower, upper, target)`` -> Result memoization (bounded LRU):
  a retried/resubmitted request after a lost Result replays in O(1)
  instead of re-running the whole search (apps/scheduler.ResultCache).
- ``DBM_QUEUE_ALARM_S``: age bound after which a still-queued (or
  still-in-flight) request emits a structured warning PLUS a full
  request-trace dump (rides the scheduler's sweep timer), so a stalled
  queue — empty or fully-quarantined pool, or a wedged in-flight request
  — explains itself to an operator instead of staying silent.
- ``DBM_LEASE_FIFO`` (0 disables): position-aware lease clocks — a chunk
  queued behind other entries in a miner's pending FIFO starts its lease
  when the miner actually reaches it, so deep FIFOs stop blowing leases
  spuriously (``leases_blown_spurious`` counts the old failure mode when
  this is off).
- ``DBM_DESPERATION`` (0 disables): when the ENTIRE pool is quarantined,
  dispatch a queued request to the least-bad available quarantined miner
  as a last resort (``desperation_dispatch`` metric + structured warning)
  instead of only alarming.
- ``DBM_METRICS_INTERVAL_S``: period of the in-process metrics emitter
  (utils/metrics.py) — one JSON snapshot line through the ``dbm.metrics``
  logger per interval, plus a final atexit dump. Default 30; 0 disables
  the emitter entirely (the registry still accumulates; ``bench.py``
  embeds a snapshot either way).
- ``DBM_METRICS_MAX_SERIES``: per-family label-cardinality bound of the
  metrics registry (default 64; overflowing label sets collapse into one
  ``{overflow="true"}`` series).
- ``DBM_METRICS_TRACE_CAP``: how many request traces the scheduler
  retains for ``Scheduler.trace(request_id)`` (default 256, LRU).
- ``DBM_HOIST`` (0 disables): lane-invariant SHA-256 hoist (deep
  midstate + precombined schedule terms, ops/sha256_jnp.build_hoist).
- ``DBM_HOIST_DEEP`` (0/1 overrides): extend the hoist's static schedule
  window from rounds 16..31 to 16..47 in the jnp tier
  (ops/sha256_jnp.build_hoist). Unset = platform default: ON for CPU
  backends — the widened window leaves one rolled iteration, which XLA
  inlines into a straight-line chain measuring ~5x the rolled carry
  (ROADMAP "hoist rounds 32+" verdict) — and OFF on chip, where the
  same unroll is the known live-chain HBM spill.
- ``DBM_UNTIL_PIPELINE`` (0 disables): difficulty-mode sub-dispatch
  pipelining (models.miner_model._until_block).
- ``DBM_DEVLOOP`` (default 1; 0 restores the stock pow2 sub-dispatch
  chain bit-for-bit — the knob-off matrix leg pins it): device-resident
  span loop (ISSUE 19). Argmin dispatch iterates a block's sub-windows
  INSIDE one jitted launch (ops/search.devloop_span; whole-mesh twin
  parallel/mesh_search.mesh_devloop_span), threading a 5-word searchop
  carry across blocks so a span costs one launch per 10^k block and ONE
  <=20-byte host fetch at finalize. Chunks whose estimated scan time is
  under the amortization floor (models/miner_model._DEVLOOP_MIN_EST_S)
  keep the stock batched path, so the coalescer population is unchanged.
- ``DBM_DEVLOOP_UNTIL`` (default 0): difficulty mode ALSO rides the
  device-resident loop — on-device first-hit predicate in the while
  condition (early exit without a host round-trip; an already-found
  carry short-circuits later block launches device-side), one 32-byte
  fetch per span, exact first-*qualifying*-nonce semantics. Staged
  behind the argmin rollout because the early-exit/prefix-release
  contract is the subtler one.
- ``DBM_DEVLOOP_PALLAS`` (default 0): serve the devloop on the pallas
  tier via the persistent grid (ops/sha256_pallas.pallas_devloop_span)
  — running min held in VMEM accumulators across grid steps, live step
  count as a scalar-prefetch operand. Off, a pallas searcher keeps the
  stock per-sub path (never a silent tier switch). Interpret-validated
  in tier-1; default off until the chip smoke
  (scripts/chip_chain.py devloop-smoke), the ``DBM_PEEL`` /
  ``DBM_COALESCE_PALLAS`` rollout discipline.
- ``DBM_PIPELINE`` (0 disables) / ``DBM_PIPELINE_DEPTH``: miner-side
  dispatch pipeline (apps/miner.MinerWorker): incoming Requests land in
  a bounded local queue (depth = ``DBM_PIPELINE_DEPTH``, default 8) and
  a compute executor dispatches chunk k+1's device work while chunk k's
  results force and serialize; Results are written strictly in request
  order. 0 restores the stock read -> blocking search -> write loop.
- ``DBM_STRIPE`` (0 disables) / ``DBM_STRIPE_CHUNK_S`` /
  ``DBM_STRIPE_DEPTH``: scheduler-side request striping
  (apps/scheduler._load_balance): each miner's even-split share is cut
  into up to ``DBM_STRIPE_DEPTH`` contiguous chunks sized at
  ``DBM_STRIPE_CHUNK_S`` seconds of work from the miner's throughput
  EWMA, so its pending FIFO is deep enough for the dispatch pipeline to
  overlap. A cold pool (no EWMA yet) always falls back to the stock
  one-chunk-per-miner split; ``DBM_STRIPE=0`` — or a non-positive
  ``DBM_STRIPE_CHUNK_S`` — pins that split unconditionally.
- ``DBM_QOS`` (0 disables): the fair-share QoS dispatch plane
  (apps/qos.py + apps/scheduler.py). With it on, the scheduler keys every
  request to a TENANT (its client conn id — no wire change), admits
  requests through a per-tenant token bucket, bounds total intake, and
  drains tenants by deficit-round-robin at CHUNK granularity: a large
  request whose estimated scan exceeds ``DBM_QOS_WHOLESALE_S`` is split
  into EWMA-sized chunks held in the scheduler and granted to miners
  incrementally (per-miner live FIFO capped at ``DBM_QOS_DEPTH``), so
  concurrent tenants' chunks interleave across the pool instead of a
  2^40 elephant parking every mouse behind its last chunk. Small or
  cold-pool requests dispatch through the stock wholesale path, so
  single-tenant traffic — and every request with ``DBM_QOS=0`` — keeps
  today's FIFO dispatch order bit-for-bit.
- ``DBM_QOS_CHUNK_S``: target seconds of work per QoS grant chunk, from
  the pool throughput EWMA (default 1.0; <=0 disables chunking, pinning
  the wholesale path like ``DBM_QOS=0`` but keeping admission/shedding).
- ``DBM_QOS_MAX_CHUNKS``: upper bound on chunks planned per request
  (default 4096); a request too large for ``chunk_s``-sized chunks under
  the cap gets proportionally larger chunks.
- ``DBM_QOS_DEPTH``: per-miner live-chunk cap for incremental grants
  (default 2 — one computing, one prefetched so the miner dispatch
  pipeline still overlaps).
- ``DBM_QOS_WHOLESALE_S``: estimated-duration threshold below which a
  request dispatches wholesale exactly like the stock scheduler (default
  5.0 seconds; a cold pool — no throughput observed — always dispatches
  wholesale, preserving reference parity for first requests).
- ``DBM_QOS_MAX_QUEUED``: total queued-request bound (default 1024;
  0 = unbounded). Above it the OLDEST queued request is shed: cancelled
  through the trace/cancel path and its conn closed, so a
  ``submit_with_retry`` client backs off and resubmits instead of
  hanging into its wire deadline.
- ``DBM_QOS_RATE`` / ``DBM_QOS_BURST``: per-tenant token-bucket
  admission — ``rate`` requests/second refill (default 0 = admission
  off) with ``burst`` capacity (default 8). A request arriving on an
  empty bucket is shed at admission. ResultCache replays bypass the
  bucket entirely: an already-answered retry never burns quota.
- ``DBM_QOS_MAX_INFLIGHT``: per-tenant cap on granted-but-unanswered
  chunks (default 256; 0 = unlimited).
- ``DBM_QOS_WEIGHT_DEFAULT`` / ``DBM_QOS_WEIGHTS``: deficit-round-robin
  weights. ``DBM_QOS_WEIGHTS`` is ``tenant:weight`` pairs separated by
  commas (tenant = conn id as decimal string); everything else gets the
  default (1.0). Programmatic drivers use
  ``Scheduler.set_tenant_weight`` instead.
- ``DBM_COALESCE`` (default 1; 0 disables): cross-request batched
  dispatch (apps/miner.MinerWorker + apps/scheduler). The pipelined
  miner drains compatible small argmin chunks — possibly from different
  requests/tenants — from its local queue into ONE batched device
  launch with a per-request segment-min
  (models.NonceSearcher.dispatch_batch / ops.search.search_span_segmin)
  and scatters the per-request Results out of a single force, still in
  strict request order; the scheduler's QoS pump emits the matching
  grant hint (multiple DRR picks may target one miner's coalescing
  window, the windowed chunks counting as ONE live-FIFO slot).
  ``DBM_COALESCE=0`` reproduces the stock one-chunk-one-dispatch path
  bit-for-bit (tier-1 matrix leg).
- ``DBM_COALESCE_LANES``: max chunks per coalesced launch / per
  scheduler grant window (default 8).
- ``DBM_COALESCE_MAX``: largest chunk (in nonces) eligible for
  coalescing (default 2^20; <=0 disables like ``DBM_COALESCE=0``) —
  batching an elephant-sized chunk would delay its own result more
  than a dispatch round-trip costs.
- ``DBM_COALESCE_SMALL_S``: scheduler-side smallness bound in ESTIMATED
  seconds at the pool throughput EWMA (default 0.25; <=0 disables the
  plane): only a chunk whose scan is launch-overhead-scale may join a
  coalescing window — an absolute nonce bound alone would misclassify a
  slow pool's rate-scaled elephant chunks as mice.
- ``DBM_COALESCE_PALLAS`` (default 0): serve coalesced batches on the
  pallas tier (ops/sha256_pallas.pallas_segmin — one jitted program of
  per-row Mosaic kernels + the segment combine). Interpret-validated;
  default off until an on-chip smoke, the ``DBM_PEEL`` rollout
  discipline — with it off, pallas-tier miners fall back to per-chunk
  dispatch and only the jnp tier batches.
- ``DBM_BENCH_BATCH`` (0 disables) / ``DBM_BENCH_BATCH_ROUNDS``: the
  bench's continuous-batching probe (``bench.py detail.batch``;
  CPU-only): mice requests/s and device dispatches-per-mouse at fixed
  elephant goodput, coalescing off vs on, legs interleaved order-swapped
  per round and median-aggregated like ``detail.qos``.
- ``DBM_BENCH_QOS`` (0 disables) / ``DBM_BENCH_QOS_ROUNDS``: the bench's
  mixed-load QoS probe (``bench.py detail.qos``; CPU-only): one elephant
  plus a train of mice through a real localhost LSP stack, QoS off vs
  on, legs interleaved per round and median-aggregated like
  ``detail.pipeline``, recording mice p50/p99 reply latency and the
  elephant's completion time.
- ``DBM_BENCH_PROBE`` (0 disables): the bench's deadlined accelerator
  probe subprocess; 0 skips it entirely (trust ``JAX_PLATFORMS``) so
  chip-less boxes stop paying the init deadline every run.
- ``DBM_BENCH_PIPELINE`` (0 disables) / ``DBM_BENCH_PIPELINE_ROUNDS``:
  the bench's end-to-end dispatch-pipeline before/after probe
  (bench.py ``_pipeline_probe``; CPU-only) and its interleaved
  round count (default 6; the on/off legs alternate order per round
  and report medians, the noise discipline the probe docstring
  explains).
- ``DBM_TIER1_MATRIX`` (0 disables): scripts/tier1.sh's knob-off
  matrix leg, which re-runs the recovery/chaos/parity modules with
  ``DBM_PIPELINE=0 DBM_STRIPE=0`` after a green main leg.
- ``DBM_TIER1_LINT`` (0 disables): scripts/tier1.sh's dbmlint leg — the
  pure-AST static-analysis gate (``scripts/dbmlint.py``) that runs
  before the pytest leg (analysis/ package; no JAX import, seconds).
- ``DBM_SANITIZE`` (default 0) / ``DBM_SANITIZE_SLOW_S``: the runtime
  sanitizer plane (utils/sanitize.py). With ``DBM_SANITIZE=1`` every
  scheduler/miner construction installs an asyncio slow-callback
  watchdog — any callback holding the event loop longer than
  ``DBM_SANITIZE_SLOW_S`` seconds (default 0.1) is named in a
  ``dbm.sanitize`` warning and counted in ``sanitize.slow_callbacks``
  — plus thread-ownership assertions on the scheduler's hot
  structures and on the miner's compute entry points (compute on the
  event loop is the bug class the dbmlint loop-block analyzer catches
  statically; this catches what slips through at runtime).
  Observability only: violations log and count, never raise.
- ``DBM_PEEL`` (default 0): pallas-tier peeled-compression kernel
  variant (ops/sha256_pallas.peel_enabled; chip-gated rollout — see
  scripts/chip_chain.py).
- ``DBM_TRACE`` (default 1; 0 disables): the cross-process tracing
  plane (utils/trace.py, ISSUE 10). With it on, the miner records one
  span per served chunk (reader-queue wait, dispatch, pipeline wait,
  force, bubble gap, shared coalesced-launch id) and ships it back on
  the Result's ``Span`` wire extension; the scheduler stitches spans
  into the request's trace (``miner_span`` events naming the dominant
  phase), keeps per-miner/per-tenant export tracks, and
  ``Scheduler.export_trace()`` / ``scripts/dbmtrace.py`` emit
  Perfetto-loadable Chrome trace JSON. The model layer's compile
  observer and both processes' flight recorders ride the same knob.
  ``DBM_TRACE=0`` reproduces stock behavior bit-for-bit: no Span
  bytes on the wire, no span events, every hook one boolean check.
- ``DBM_TRACE_FLIGHT``: flight-recorder ring capacity (default 512;
  0 disables) — a bounded ring of control-plane events in scheduler
  AND miner processes, dumped as one JSON line on queue-age /
  in-flight alarms, sanitizer warnings, recompile storms, and
  unhandled-exception exit (utils/trace.FlightRecorder).
- ``DBM_TRACE_STORM_N`` / ``DBM_TRACE_STORM_S``: recompile-storm alarm
  bound of the jit-compile observer (default 12 fresh signatures within
  30 seconds — above a cold process's legitimate warmup burst, far
  below a per-request churn): the dynamic complement of the ``jit-static`` dbmlint
  analyzer — a runtime-derived value reaching a static jit boundary
  shows up as a burst of fresh compile signatures, warned once per
  episode with a flight-recorder dump (utils/trace.CompileObserver).
- ``DBM_TRACE_XPROF``: directory for a JAX device-profiler (XProf)
  trace of one timed search per tier (bench.py via
  utils/profiling.device_trace; unset = no capture). Orthogonal to
  ``DBM_TRACE``: this captures kernels, that captures requests.
- ``DBM_BENCH_INIT_TIMEOUT``: deadline in seconds for the bench /
  chip-script backend probe subprocess (default 300).
- ``DBM_BENCH_REM_SWEEP`` (default 0): bench.py's opt-in rem-sweep
  micro-bench (hoisted vs plain jnp rates across message lengths).
- ``DBM_MINER_PROBE_TIMEOUT_S``: the miner's pre-join deadlined
  accelerator probe (default 120; 0 skips — apps/miner
  _pin_platform_if_backend_wedged). On probe failure the miner pins
  itself to CPU instead of hanging in backend init.
- ``DBM_MESH`` (default 1): the ISSUE 14 mesh plane. Multi-device
  boxes serve through ``models.MeshNonceSearcher`` — per-core stripe
  windows cut by the partition-rule table
  (``parallel/partition.py``), carry-chained whole-mesh launches with
  the on-device lexicographic min-hash all-reduce, and exactly ONE
  (hash, nonce) pair crossing the host per span. ``DBM_MESH=0``
  restores the round-3 ``ShardedNonceSearcher`` (per-sub partials,
  stock local-device sharding) byte-for-byte — the tier-1 matrix leg
  pins it. The pod path (``parallel/multihost.PodSearcher`` and its
  followers) reads the same knob, which must agree across hosts.
- ``DBM_RATE_HINT`` (default 0 = no hint): the miner's JOIN rate hint
  in nonces/s. A number is sent as the Join's ``Rate`` extension so
  the scheduler seeds that miner's throughput EWMA warm (bounded at
  1e12, decayed ~2%/sweep until real Results confirm it — a cold
  1B-nps mesh must not warm up through mouse-sized chunks);
  ``probe`` measures it at startup with two timed spans
  (apps/miner.measure_rate_hint). Hint-less Joins keep
  reference-identical bytes and stock scheduling.
- ``DBM_COORDINATOR`` / ``DBM_NUM_PROCS`` / ``DBM_PROC_ID``: multi-host
  pod mode (parallel/multihost.initialize_multihost): the
  jax.distributed coordinator address and process geometry; unset =
  single-host.
- ``DBM_POD_TIMEOUT_S`` (default 600) / ``DBM_POD_IDLE_TIMEOUT_S``
  (default 0 = unbounded): pod failure-domain bounds — one pod job's
  collective deadline, and the follower's optional between-jobs
  broadcast wait bound (parallel/multihost.bounded_pod_call).
- ``DBM_CHECK`` (0 disables): scripts/tier1.sh's dbmcheck leg — the
  deterministic interleaving explorer (``scripts/dbmcheck.py``,
  ``analysis/schedcheck``): the control plane's scenario catalog run
  over seed-driven random walks plus a bounded DFS on a controlled
  event loop + virtual clock, with the merge/FIFO/accounting/liveness
  invariants checked after every explored schedule and every failure
  printed as a replayable (shrunk) seed spec.
- ``DBM_CHECK_SEEDS``: random-walk seeds per scenario (default 200).
- ``DBM_CHECK_BUDGET_S``: wall budget in seconds for the whole
  exploration (default 75; scenarios share it).
- ``DBM_CHECK_DFS``: bounded-exhaustive-DFS schedules per scenario
  (default 64; 0 disables the DFS pass).
- ``DBM_CHECK_SCENARIOS``: comma-separated scenario subset (default:
  the full real-scenario catalog; ``scripts/dbmcheck.py --list``).
- ``DBM_CHECK_MIN_DISTINCT``: tier1.sh-side floor on the leg's
  DBMCHECK_DISTINCT total (default 500; 0 disables) — a starved box
  whose budget expired after a handful of schedules must fail the
  gate, not pass green having checked nothing.
- ``DBM_REPLICAS`` (default 1): scheduler replica count
  (apps/replicas.ReplicaSet). With N>1 the server runs N in-process
  scheduler replicas behind one LSP socket: tenants consistent-hashed
  across replicas, miners sliced to the thinnest replica at join, one
  SHARED ResultCache replay tier, and lease takeover on replica death
  (a dead replica's miners are adopted — pending chunks popping in
  order as stale — and its unanswered requests re-served exactly-once
  through the new ring owners). 1 = the plain single scheduler,
  today's topology bit-for-bit.
- ``DBM_RECV_BATCH`` (default 64): scheduler/replica-router recv batch
  — after each awaited transport read, up to this many
  already-delivered messages are handled without an event-loop
  round-trip (at 10k conns the per-await wakeups dominate the recv
  path). Handlers run in identical order either way; 1 restores the
  stock one-message-per-await loop (tier-1 matrix leg).
- ``DBM_TIMER_WHEEL`` (default 1): collapse every LSP conn's epoch
  timer onto ONE shared per-loop timer task (lsp/timerwheel.py) — 10k
  conns become 10k heap entries instead of 10k sleeping tasks. Tick
  schedule and semantics are unchanged (first tick at +epoch, next
  relative to when this one ran); 0 restores the per-conn epoch task
  (tier-1 matrix leg).
- ``DBM_TRACE_SAMPLE`` (default 1.0): fraction of requests that
  allocate a real RequestTrace (utils/trace.sample_hit — a
  deterministic hash of the arrival sequence, so the same storm
  samples the same requests every run). Unsampled requests carry a
  shared no-op trace and never register in the trace buffer or export
  tracks; sampled ones record complete end-to-end. 1.0 is bit-for-bit
  today's allocate-every-trace behavior (tier-1 matrix leg pin); the
  10k-tenant load harness runs at ~0.01 so tracing stays on without
  being the bottleneck.
- ``DBM_QOS_LAZY`` (default 1): lazy ring-ordered DRR candidate walk
  (ISSUE 12; apps/qos.QosPlane.pick_lazy + apps/scheduler.
  _qos_pump_lazy). The stock pump rebuilds an O(backlogged-tenants)
  candidate map and re-syncs the DRR ring before EVERY grant — the
  per-completion scan behind the single-replica superlinear tail at
  10k tenants (BENCH_r06). With the lazy walk, ring membership is
  maintained at the edges (enqueue hook, chunked activation, lazy
  removal during the walk) and each visited tenant's head is priced on
  demand from O(1) per-tenant indexes, with an INCREMENTAL quantum
  bound (max head cost seen) replacing the per-pick max — grants are
  O(1) amortized, DRR fairness/starvation guarantees unchanged (grant
  ORDER may differ from the stock walk; dbmcheck explores the lazy
  path by default). 0 = the stock walk bit-for-bit (tier-1 matrix
  leg). Measured (loadharness, 1 replica): 5k tenants 186 -> 1981
  admitted/s, CPU/request 5.3ms -> 0.5ms.
- ``DBM_ADAPT`` (default 1 since ISSUE 14 — the ISSUE 13 soak PR ran
  clean, so the self-tuning control plane is ON by default;
  ``apps/adapt.py``). With it on, the scheduler mounts small setpoint
  controllers that retune the dispatch knobs from already-collected
  signals: chunk/stripe seconds-of-work driven toward a per-chunk
  force-latency setpoint (AIMD with hysteresis and hard
  floors/ceilings, plus a lease-margin guard), the coalescing-window
  bound widened under mouse floods and collapsed when ``gap_s`` spans
  show pipeline bubbles, and a congestion-style scheduler-wide
  admission bucket whose rate tracks the queue-age slope (additive
  increase on falling age, multiplicative decrease on rising age) so
  shed rate follows actual service capacity. ``DBM_ADAPT=0`` is
  bit-for-bit stock: no controller objects exist and every hook is one
  attribute test (kept pinned in the tier-1 knob-off matrix leg).
- ``DBM_ADAPT_PER_MINER`` (default 0): per-miner chunk setpoints under
  the adapt plane (ISSUE 14 satellite). The chunk-size controller ALSO
  keys force-latency samples by answering miner conn, and once the
  pool's rate EWMAs diverge past 4x (a heterogeneous pool — host tier
  next to a mesh miner) it forks a per-miner AIMD value per sampled
  miner; the per-miner values size that miner's STRIPE chunks
  (``MinerPlane.chunk_s_overrides``, ``adapt_chunk_s_miner`` gauge)
  while the pool-wide value keeps driving the QoS chunk plan.
- ``DBM_ADAPT_TICK_S``: minimum seconds between controller adjustments
  (default 1.0; the controllers ride the scheduler sweep and
  rate-limit themselves to this).
- ``DBM_ADAPT_BAND``: hysteresis dead-band as a fraction of each
  setpoint (default 0.35) — measurements inside the band adjust
  nothing, which is what keeps AIMD's sawtooth from becoming churn.
  The default is wide enough that an honestly-tuned static
  configuration measures INSIDE it (chunk plans ceil-divide, so
  steady-state per-chunk force sits at ~0.7-0.9x the target): an
  adaptive run over healthy traffic changes nothing, and only a real
  divergence (rate drift, mis-tuned deployment) moves a knob.
- ``DBM_ADAPT_FORCE_S``: the per-chunk force-latency setpoint the
  chunk/stripe sizing controller drives toward (default 1.0 second —
  what the static ``DBM_QOS_CHUNK_S`` default already targets when
  the rate EWMA is honest, so the controller is CORRECTIVE: it moves
  only when measurement diverges from the static plan, e.g. after a
  pool-rate drift the EWMA lags).
- ``DBM_ADAPT_RATE0``: starting rate (requests/s) of the adaptive
  admission bucket (default 0 = start OPEN at the controller ceiling —
  nothing is shed until congestion is actually observed).
- ``DBM_ADAPT_CHUNK`` / ``DBM_ADAPT_COALESCE`` / ``DBM_ADAPT_ADMIT``
  (default 1 each): per-controller enables under the master knob, for
  A/B isolation of one controller at a time.
- ``DBM_TIER1_ADAPT`` (0 disables): scripts/tier1.sh's adapt leg — the
  dbmcheck ``adaptive_control`` stability scenario at a >=500 distinct
  schedule floor plus a mini mice-stampede workload with a
  completion/p99 gate.
- ``DBM_BENCH_ADAPT`` (0 disables) / ``DBM_BENCH_ADAPT_ROUNDS``: the
  bench's ``detail.adapt`` A/B — the three adversarial load-harness
  workloads (mice stampede, elephant convoy, tenant churn storm) run
  with the static defaults vs the adaptive controllers, legs
  interleaved order-swapped per round (default 3) and
  median-aggregated.
- ``DBM_HEALTH_BEAT_S`` (default 0.5) / ``DBM_HEALTH_MISS_K``
  (default 3): the multi-process replica tier's health plane
  (apps/health.py + apps/procs.py, ISSUE 12). Every replica process
  heartbeats a Beat blob (seq, serving bit, miner-slice size, queue
  depth, epoch seen) to its state-dir beat file every
  ``DBM_HEALTH_BEAT_S`` seconds; the router declares a replica DEAD —
  and fences its incarnation at a bumped membership epoch — once its
  beat seq has been frozen for ``DBM_HEALTH_MISS_K`` beats. Detection
  is purely seq-based (a SIGSTOPped process's stale file is a death,
  not a heartbeat).
- ``DBM_PROC_CACHE`` (default 1): the multi-process tier's replicated
  result-cache tier (apps/procs.SpoolResultCache): finished results
  write through to an append-only per-incarnation spool file and every
  replica ingests its peers' spools on the beat cadence, so a tenant
  re-hashed after a failover replays answers the dead replica
  produced; lines written by a FENCED incarnation are dropped at
  ingest. 0 = per-replica caches only (failover replays degrade to
  recompute — never to a wrong or duplicate reply either way).
- ``DBM_TIER1_PROCS`` (0 disables): scripts/tier1.sh's multi-process
  smoke leg (scripts/procsmoke.py): router + 2 replica processes + 1
  miner agent on localhost, kill -9 of the replica owning an in-flight
  request, exactly-once oracle-exact reply asserted with failover
  driven solely by missed health beats.
- ``DBM_BENCH_LOAD_PROCS`` (0 disables): ``bench.py detail.load``'s
  in-process-vs-multi-process comparison leg — 2 in-process replicas
  vs the real 2-process topology (loadharness ``--procs``) at equal
  tenant count.
- ``DBM_TIER1_MESH`` (0 disables): scripts/tier1.sh's mesh smoke leg
  (scripts/meshsmoke.py): an 8-virtual-device CPU mesh miner serving
  one elephant through a real localhost LSP stack — reply must be
  oracle-exact with exactly one device launch and one host fetch per
  whole-mesh span.
- ``DBM_BENCH_MESH`` (0 disables): ``bench.py detail.mesh`` — the
  mesh plane's per-device-count scaling sweep (1/2/4/8 virtual
  devices on CPU: nonces/s, device launches per span, host-crossing
  bytes per span) plus a mixed-pool storm (one 100x rate-skewed fake
  miner under the real scheduler) recording per-tier grant share vs
  rate-EWMA ratio; the same dict is the ``MULTICHIP_r06.json``
  artifact schema the chip chain records on real devices.
- ``DBM_TIER1_LOAD`` (0 disables): scripts/tier1.sh's mini-load leg —
  a bounded ~500-tenant storm through the split scheduler on detnet
  (scripts/loadharness.py) gating completion, a generous reply-p99
  ceiling, and bounded metric-series growth.
- ``DBM_BENCH_LOAD`` (0 disables) / ``DBM_BENCH_LOAD_TENANTS`` /
  ``DBM_BENCH_LOAD_ROUNDS``: the bench's control-plane load curve
  (``bench.py detail.load``): tenants vs p50/p99/shed-rate for 1 vs 4
  scheduler replicas on detnet with instant miners, interleaved
  order-swapped rounds (default 2), median-aggregated.
  ``DBM_BENCH_LOAD_TENANTS`` is the comma-separated tenant-count
  sweep (default "500,2000"; the checked-in BENCH_r06 artifact used
  "500,2000,10000").
- ``DBM_CAPTURE`` (default 0): the workload capture plane
  (apps/capture.py, ISSUE 15). 0 = bit-for-bit stock: no capture
  object exists anywhere, every scheduler hook is one attribute test
  (pinned in the knob-off matrix leg). 1 = the scheduler(s) append a
  versioned JSONL workload trace — per-request arrival stamp, salted-
  hash tenant key, geometry (range size, argmin vs difficulty, pow2
  data-size class), shed/cancel/re-issue events, folded span phases,
  periodic pool-composition snapshots — that ``loadharness --replay``
  re-drives and the dbmcheck ``replayed_storm`` scenario explores.
- ``DBM_CAPTURE_PATH`` (default ``dbm_capture.jsonl``): where the
  env-armed capture writes (explicit harness legs pass
  ``capture_path=``/``--capture-to`` instead).
- ``DBM_CAPTURE_LINES`` (default 200000, floor 1024): rotation bound —
  past this many lines the file rotates (current renamed to
  ``<path>.1``, previous ``.1`` unlinked), so a long-lived capture
  holds at most ~two windows on disk and every window is
  independently loadable (each restarts with its own header).
- ``DBM_CAPTURE_SNAP_S`` (default 5.0): pool-composition snapshot
  period (rides the scheduler sweep); doubles as the flush cadence.
- ``DBM_REPLAY_SPEED`` (default 1.0): replay time-warp — captured
  inter-arrival gaps are divided by it and rate-limited replay miners
  are scaled by it (the load factor, i.e. the shape, survives the
  warp); the fidelity p99 bound only gates at 1.0.
- ``DBM_CHECK_CAPTURE`` (default: the checked-in
  ``analysis/schedcheck/replay_fixture.jsonl``): capture file the
  dbmcheck ``replayed_storm`` scenario replays — the tier-1 replay
  leg points it at the storm it just captured, so interleaving
  exploration runs over that session's own measured traffic.
- ``DBM_TIER1_REPLAY`` (0 disables): scripts/tier1.sh's replay leg —
  capture a mini detnet storm (``loadharness --capture-to``), replay
  it under the fidelity gate (``--replay --assert-fidelity``), then
  run the ``replayed_storm`` dbmcheck scenario over the fresh capture
  with a >=500 distinct-schedule floor.
- ``DBM_BENCH_REPLAY`` (0 disables) / ``DBM_BENCH_REPLAY_ROUNDS``
  (default 2): ``bench.py detail.replay`` — capture a synthesized
  storm, replay it, embed the side-by-side fidelity report (capture's
  own admitted/s, shed rate, p50/p99, span medians vs each replay
  round's, plus the ``within`` verdict).
- ``DBM_VERIFY`` (default 1): the verification tier's claim checks
  (ISSUE 16). 1 = every claimed winning ``(hash, nonce)`` is
  recomputed host-side (one SHA-256 via ``bitcoin.hash_op``) BEFORE it
  may merge; a mismatch (or, in difficulty mode, a claimed hit above
  the target) is rejected as a ``claim_failed`` lease event, the
  liar's trust decays, and the chunk is re-granted to another miner.
  0 = bit-for-bit stock: Results are believed verbatim (pinned in the
  knob-off matrix leg). Cost is microseconds per WINNER, not per
  nonce — bench-geometry throughput is unaffected within noise.
- ``DBM_AUDIT_P`` (default 0.02, clamped to [0, 1]): probabilistic
  audit rate. With probability p per completed (merged) chunk, a
  random subwindow of it is re-granted to a DISJOINT miner and the
  sub-argmin cross-checked against the original claim over that
  window — a strictly better hash inside the window proves the
  original never scanned it (the "sentinel-without-scan" lazy-miner
  class that claim checks cannot see) and fires ``audit_failed``.
  0 disables audits entirely (no RNG draw, no bookkeeping).
  ISSUE 16 shipped the knob at 0 pending soak; ISSUE 20 flips the
  ENV default to 0.02 (~1 audit per 50 merged chunks — sub-percent
  grant overhead at the 2^16 subwindow cap) now the byzantine
  dbmcheck family and the tier-1 byzantine leg have soaked clean.
  Only the env path flips: the ``VerifyParams`` dataclass field
  stays 0.0, so programmatic constructions (dbmcheck scenarios,
  bench probes, fake-miner rigs whose fabricated hashes an audit
  would convict) remain audit-free and deterministic unless they
  opt in; the knob-off matrix leg pins 0 explicitly.
- ``DBM_AUDIT_MAX`` (default 65536, floor 1): audit subwindow size
  cap in nonces — audits must stay launch-overhead-scale, never a
  second full scan.
- ``DBM_TRUST_DECAY`` (default 0.25, clamped to (0, 1)): multiplier
  applied to a miner's trust score on each claim/audit failure.
- ``DBM_TRUST_RECOVER`` (default 0.05, clamped to (0, 1)): per
  confirmed-result step of trust recovery toward 1.0 (new miners
  start at full trust; the score only matters once they misbehave).
- ``DBM_TRUST_FLOOR`` (default 0.01): lower clamp on trust, so a
  repeat liar's score can still recover through confirmed work.
- ``DBM_TRUST_BAR`` (default 0.2): grant-eligibility bar — a miner
  whose trust falls below it is excluded from new grants exactly like
  a quarantined miner (desperation dispatch still floors
  availability when the WHOLE pool is below the bar/quarantined).
  Trust also weights striping share (effective rate x trust) and
  clamps the unauthenticated JOIN rate hint (PR 14 bugfix), so a
  byzantine miner cannot inflate its grant share by overclaiming.
- ``DBM_TIER1_BYZ`` (0 disables): scripts/tier1.sh's byzantine leg —
  dbmcheck's ``byzantine_*`` scenario family (wrong-hash fabricators,
  colluding duplicates, sentinel-without-scan and selectively-correct
  liars) under the exactly-once oracle-exact invariant pack, with the
  same >=500 distinct-schedule floor as the other dbmcheck legs.
- ``DBM_WIRE_FAST`` (default 1): the allocation-free wire codec
  (lsp/wire.py, ISSUE 17). 1 = canonical LSP frames are serialized by
  byte-template substitution and parsed by a positional scanner —
  byte-for-byte identical output to ``Message.to_json`` and identical
  accept/reject behavior to ``Message.from_json`` (fuzz-pinned in
  tests/test_transport_fast.py; non-canonical frames fall back to the
  stock parser). 0 = stock json/dataclass codec bit-for-bit (pinned
  in the knob-off matrix leg).
- ``DBM_MMSG`` (default 1): batched datagram syscalls (lsp/_mmsg.py +
  lspnet/net.py ``MmsgEndpoint``, ISSUE 17). 1 = on Linux/IPv4 with
  ``recvmmsg``/``sendmmsg`` present, every readable event drains up
  to a batch of datagrams in ONE syscall and outbound sends queue and
  flush as one ``sendmmsg`` per event-loop turn; wire bytes, fault
  pipeline, and delivery order are unchanged. Falls back to the stock
  one-syscall-per-packet endpoint when unavailable (non-Linux, IPv6,
  missing libc symbols). 0 = stock endpoint bit-for-bit (knob-off
  matrix leg pin).
- ``DBM_MMSG_BATCH`` (default 32): max datagrams per batched syscall
  in each direction — the recv buffer array (64 KiB per slot) is
  preallocated at this size per endpoint.
- ``DBM_BENCH_TRANSPORT`` (0 disables): the bench's
  ``detail.transport`` probe (bench.py via apps/transportbench.py;
  CPU-only): an echo-storm msgs/s A/B of the fast datapath
  (``DBM_MMSG=1 DBM_WIRE_FAST=1``) vs stock (both 0) in subprocess
  legs, interleaved order-swapped per round and median-aggregated
  like ``detail.pipeline``, recording syscalls/msg, bytes/msg, p99
  ack RTT, and per-conn RSS at 10k/50k/100k sans-io cores.
- ``DBM_BENCH_TRANSPORT_CONNS`` (default 32) /
  ``DBM_BENCH_TRANSPORT_INFLIGHT`` (default 8) /
  ``DBM_BENCH_TRANSPORT_PAYLOAD`` (default 128) /
  ``DBM_BENCH_TRANSPORT_SECS`` (default 1.0) /
  ``DBM_BENCH_TRANSPORT_WARMUP_S`` (default 0.3) /
  ``DBM_BENCH_TRANSPORT_ROUNDS`` (default 3): echo-storm geometry —
  client count, per-client closed-loop inflight, payload bytes,
  measured window and warmup seconds per leg, and interleaved round
  count.
- ``DBM_TIER1_TRANSPORT`` (0 disables): scripts/tier1.sh's
  transport-regression leg — ``bench.py --transport-only`` diffed
  against ``scripts/transport_floor.json`` by scripts/benchdiff.py at
  ``--threshold 0.3``: echo-storm msgs/s may not fall below the floor
  (set ~30-50% under measured medians, outside box noise) and the
  fast-vs-stock speedup may not collapse toward 1.0.
- ``DBM_ROLLUP`` (default 1): the cluster observability plane
  (apps/rollup.py, ISSUE 18). 1 = every env-armed process (replica,
  router, miner agent under ``--procs``) publishes its metrics
  registry as a versioned snapshot blob (``metrics_<role>_<rid>.json``,
  atomic tmp+rename, stamped role/rid/incarnation + beat cadence)
  into the health-beat state directory at every beat, and the
  aggregator merges the fresh ones into one cluster snapshot
  (``scripts/dbmtop.py``, ``dbmtrace summarize``, the loadharness
  ``--assert-rollup`` gate). 0 = no publisher objects, no blobs, no
  identity stamps — bit-for-bit stock (knob-off matrix leg pin).
- ``DBM_ROLLUP_STALE_K`` (default: ``DBM_HEALTH_MISS_K``, 3): beat
  windows without a FRESH snapshot (wall stamp within
  ``beat_s * K``, seq advancing) before a source's blob is flagged
  ``stale`` and excluded from cluster totals — a frozen publisher is
  flagged, never silently averaged in. Fenced replica incarnations
  are excluded the same way a fenced writer's cache spool lines are.
- ``DBM_SLO_AVAIL`` (default 0.99): reply-availability SLO target
  (apps/slo.py): fraction of decided requests answered rather than
  shed, ``results_sent / (results_sent + qos_shed)``; the error
  budget is ``1 - target``.
- ``DBM_SLO_P99_S`` (default 60): queue-wait p99 SLO threshold in
  seconds (mirrors the tier-1 mini-load leg's ``--assert-p99 60``
  bar), read from the merged cumulative-``le`` ``sched.queue_wait_s``
  buckets; budget 1% by the definition of a p99 objective.
- ``DBM_SLO_SHED`` (default 0.25): shed-rate SLO budget — fraction of
  admission decisions shed, ``qos_shed / (qos_grants + qos_shed)``
  (the loadharness storm gates treat <=25% shed under deliberate
  overload as healthy back-pressure).
- ``DBM_SLO_WINDOW_S`` (default 300): the LONG burn-rate window in
  seconds; the short window is long/12 (the classic fast-burn pair
  ratio). An alert fires only on the transition into "both windows
  burning" — the short window gates on sustained current pain, the
  long one keeps a transient blip from paging.
- ``DBM_SLO_BURN`` (default 4.0): burn-rate alert threshold — windowed
  error fraction over budget; 4.0 = the error budget is being spent
  4x faster than the SLO allows. Firing alerts are flight-recorder
  events naming the burning objective and the worst-offending
  process.
- ``DBM_TIER1_BUDGET_S`` (default: nproc-derived — 870 on >=2 cores,
  1740 on 1 core): scripts/tier1.sh's main pytest wall budget in
  seconds; the knob-off matrix leg scales to ~55% of it (the
  historical 480/870 ratio). The original 870 was calibrated on a
  2-core runner — a 1-core box needs roughly double the wall for the
  same suite.
- ``DBM_BENCH_ROLLUP`` (0 disables) / ``DBM_BENCH_ROLLUP_ROUNDS``
  (default 2): the bench's ``detail.rollup`` overhead probe — an
  interleaved order-swapped A/B of the multi-process loadharness with
  the rollup plane on vs off (makespan/admitted-per-s/cpu-per-request
  medians + the makespan ratio; publish must be within noise), plus a
  microbench of one publish and one aggregate over synthetic
  4-process registries (``publish_ms`` / ``aggregate_ms``).
- ``DBM_BENCH_DEVLOOP`` (0 disables) / ``DBM_BENCH_DEVLOOP_PAIRS``
  (default 120): the bench's ``detail.devloop`` A/B probe — paired
  alternating devloop-on/off spans at a launch-bound geometry (nps +
  launches/transfers/bytes per span + until time-to-first-hit +
  pallas-interpret counter parity). PAIRS is the number of
  order-swapped on/off span pairs per timing leg; paired timing holds
  the CPU drift envelope to a few percent where blocked legs wander.
- ``DBM_GATEWAY`` (default 1): scheduler federation (apps/gateway.py,
  ISSUE 20). 1 = a repeat JOIN from a conn the scheduler already
  knows as a live miner REFRESHES that miner's rate hint in place
  (the GatewayMiner's pool-sum refresh path over the existing
  ``Rate`` wire extension) and ``ReplicaSet`` routes it to the
  existing owner replica. 0 = bit-for-bit stock flat topology: a
  repeat JOIN registers a fresh miner exactly as before (pinned in
  the knob-off matrix leg) and ``gateway serve`` refuses to start.
- ``DBM_GATEWAY_HINT_S`` (default 2.0, floor 0.05): period of the
  gateway's rate-hint refresher — every tick it sums the rate EWMAs
  of its non-quarantined inner pool and, when the aggregate moved
  >= ~10% (or the pool emptied/filled), re-sends the JOIN with the
  new hint so the parent's stripe planner tracks the pool.
- ``DBM_GATEWAY_MIN_POOL`` (default 1): inner miners that must have
  JOINed the gateway's inner tier before it announces itself to the
  parent — a gateway with nothing downstream must not accept grants
  it can only let expire.
- ``DBM_GATEWAY_ORPHAN_S`` (default 10.0, floor 0.1): orphan
  watchdog — when the inner pool stays EMPTY this long while parent
  work is pending, the gateway closes its parent conn so the stock
  lease/drop/re-issue plane re-grants its chunks to siblings (a
  fenced child cluster = one blown lease at the parent).
- ``DBM_TIER1_FED`` (0 disables): scripts/tier1.sh's federation leg —
  dbmcheck's ``federation`` scenario (two-level topology, gateway
  rate-hint refresh, mid-schedule child-cluster failover) under the
  exactly-once oracle-exact invariant pack with the same >=500
  distinct-schedule floor as the other dbmcheck legs.
- ``DBM_BENCH_FEDERATION`` (0 disables) /
  ``DBM_BENCH_FEDERATION_ROUNDS`` (default 2): the bench's
  ``detail.federation`` probe — federated (gateways re-sharding to
  children) vs flat (same miners JOINed directly) makespan at equal
  pool size (``overhead_ratio``), plus a >=10x child-pool-skew leg
  recording per-gateway grant share against rate share
  (``tracking_error``).
"""

from __future__ import annotations

import hashlib
import os
import platform
from dataclasses import dataclass, field

from ..lsp.params import Params
from ._env import (float_env as _float_env, int_env as _int_env,
                    str_env as _str_env)

#: Platform names that mean "a real chip" — the axon plugin's registered
#: name is cwd-dependent in this image (axon vs tpu), and the miner's tier
#: selection plus every chip gate must agree on the set.
CHIP_PLATFORMS = ("tpu", "axon")


def host_fingerprint() -> str:
    """12-hex CPU-feature fingerprint of this host.

    Used to key every cross-run build artifact that encodes the build
    host's ISA (the JAX persistent compile cache, the ``-march=native``
    C++ library): an artifact written on one machine and loaded on another
    runs misfeatured code — observed in round 3 as ``cpu_aot_loader.cc``
    feature-mismatch errors followed by a compute hang (round 2's
    "test_pallas.py never finishes" root cause: a poisoned ``.jax_cache``
    carried across driver/judge machines in the working tree).
    """
    try:
        with open("/proc/cpuinfo") as f:
            sig = next((ln for ln in f if ln.startswith("flags")), "")
    except OSError:
        sig = ""
    sig = sig or platform.processor() or platform.machine()
    return hashlib.sha256(sig.encode()).hexdigest()[:12]


def apply_jax_platform_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative before any device use.

    This image's sitecustomize registers the axon TPU plugin at interpreter
    start, which overrides the environment variable; only a config-level
    update actually steers backend selection. Apps call this before their
    first ``jax.devices()`` so ``JAX_PLATFORMS=cpu`` reliably keeps a
    process off a (possibly wedged) chip — a bare env var silently did
    nothing (round-3 finding, same mechanism as the round-1 bench hang).
    """
    plats = os.environ.get("JAX_PLATFORMS")
    if plats:
        import jax
        jax.config.update("jax_platforms", plats)


def jax_devices_robust():
    """``jax.devices()`` with a fallback to automatic platform selection.

    A pinned ``jax_platforms`` naming a platform that cannot initialize
    in THIS process — e.g. ``JAX_PLATFORMS=axon`` inherited from the
    image environment by a miner launched from a directory where the
    axon plugin registers its platform under a different name — made the
    round-3 e2e miner crash on first use. Falling back to "" resolves
    whatever the plugin actually registered. Deliberately NOT probed
    inside :func:`apply_jax_platform_env`: an eager ``jax.devices()``
    there initializes backends before ``jax.distributed.initialize`` and
    breaks the multi-host pod path.
    """
    import jax
    try:
        return jax.devices()
    except RuntimeError as exc:
        import logging
        logging.getLogger("dbm.config").warning(
            "pinned jax_platforms=%r failed to initialize (%s); falling "
            "back to automatic platform selection — if the pin existed to "
            "avoid a wedged device, that protection is gone for this "
            "process", jax.config.jax_platforms, exc)
        jax.config.update("jax_platforms", "")
        return jax.devices()


#: Process-wide memo of the first probe outcome (see probe_backend).
_probe_cache: dict | None = None


def probe_backend(timeout_s: float, repo_dir: str | None = None,
                  refresh: bool = False) -> dict:
    """Resolve the JAX backend in a CHILD process with a deadline.

    Uses the SAME resolution order as the apps — ``apply_jax_platform_env``
    then ``jax_devices_robust`` — so the reported platform is the one a
    miner spawned in this environment would actually compute on (a probe
    skipping ``apply_jax_platform_env`` once vouched for a chip while the
    miner honored a ``JAX_PLATFORMS=cpu`` pin, code-review r4). A wedged
    accelerator can never hang the caller: that is the whole point of the
    subprocess (bench round-1 failure mode). Returns ``{"platform", "n"}``
    or ``{"error": ...}``.

    The outcome is memoized for the PROCESS: a wedged tunnel does not heal
    mid-process, and before the memo every probe caller — the bench, then
    each in-process MinerWorker it spawns for the pipeline probe — re-paid
    the full init deadline on chip-less boxes (the recurring ``backend
    init exceeded 300s deadline`` artifact error). ``refresh=True`` forces
    a fresh child probe.
    """
    import json
    import subprocess
    import sys

    global _probe_cache
    if _probe_cache is not None and not refresh:
        return _probe_cache
    repo = repo_dir or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # The child hard-exits after printing: this image's axon/jax stack
    # can hang for minutes in interpreter-shutdown finalizers (bench.py
    # tail, round 3), and subprocess.run waits for process EXIT — a
    # healthy chip would otherwise be reported as a probe timeout
    # (code-review r4).
    code = (
        "import sys, os, json; sys.path.insert(0, %r); "
        "from distributed_bitcoinminer_tpu.utils.config import "
        "apply_jax_platform_env, jax_devices_robust; "
        "apply_jax_platform_env(); d = jax_devices_robust(); "
        "print(json.dumps({'platform': d[0].platform, 'n': len(d)})); "
        "sys.stdout.flush(); os._exit(0)"
        % repo)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=repo)
    except subprocess.TimeoutExpired:
        out = {"error": f"backend init exceeded {timeout_s:.0f}s deadline"}
    else:
        if proc.returncode != 0:
            out = {"error":
                   f"backend init failed: {proc.stderr.strip()[-400:]}"}
        else:
            try:
                out = json.loads(proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                out = {"error":
                       f"unparseable probe output: {proc.stdout[-200:]}"}
    _probe_cache = out
    return out


def host_cache_dir(root: str) -> str:
    """Host-fingerprinted JAX persistent-cache path under ``root`` (see
    :func:`host_fingerprint` for why the key exists)."""
    return os.path.join(root, ".jax_cache", host_fingerprint())


@dataclass(frozen=True)
class LeaseParams:
    """Chunk-lease knobs for the scheduler's robustness plane.

    A chunk's lease is ``max(floor_s, factor * size / rate)`` where ``rate``
    is the assigned miner's observed per-chunk throughput EWMA (falling back
    to the pool-wide EWMA, then to the flat ``grace_s`` when no throughput
    has been observed yet). ``quarantine_after`` consecutive blown leases
    quarantine a miner from new assignments until it answers again.
    """
    enabled: bool = True
    grace_s: float = 30.0          # lease with no throughput history
    factor: float = 4.0            # headroom multiplier over the estimate
    floor_s: float = 2.0           # lower clamp on any computed lease
    tick_s: float = 1.0            # lease-check cadence
    quarantine_after: int = 3      # consecutive blown leases -> quarantine
    ewma_alpha: float = 0.3        # weight of the newest throughput sample
    queue_alarm_s: float = 30.0    # queued/in-flight age alarm bound
    fifo_aware: bool = True        # lease clock starts at FIFO head
    desperation: bool = True       # all-quarantined last-resort dispatch


@dataclass(frozen=True)
class CacheParams:
    """Scheduler result-memoization knobs (apps/scheduler.ResultCache).

    The cache keys on the full request identity ``(data, lower, upper,
    target)`` and replays the recorded Result without touching the pool;
    ``size`` bounds it as an LRU. Weak difficulty merges (a stock miner
    answered a target chunk) are never cached — their answer is only
    guaranteed qualifying, not deterministic.
    """
    enabled: bool = True
    size: int = 256


@dataclass(frozen=True)
class StripeParams:
    """Scheduler request-striping knobs (apps/scheduler._load_balance).

    With striping on, each miner's even-split share of a request is cut
    into up to ``depth`` contiguous chunks, each sized at ``chunk_s``
    seconds of work from that miner's observed throughput EWMA (pool EWMA
    when unobserved), so the miner's pending FIFO is deep enough for its
    dispatch pipeline (``DBM_PIPELINE``) to overlap chunk k+1's device
    work with chunk k's result fetch + serialize — and a blown lease
    forfeits one stripe chunk, not the whole share. A COLD rate (nothing
    observed yet) always falls back to the stock one-chunk-per-miner
    split, which keeps the off-path conformance shape for first requests;
    ``enabled=False`` pins that split unconditionally (Go-parity mode).
    Chunk boundaries stay contiguous and ascending, so the merge rules
    (arg-min, difficulty first-hit prefix release) are untouched.
    """
    enabled: bool = True
    chunk_s: float = 1.0           # target seconds of work per stripe chunk
    depth: int = 8                 # max chunks per miner share

    def __post_init__(self):
        # chunk_s <= 0 disables striping (the repo-wide 0-disables env
        # convention) rather than targeting 0 seconds of work per chunk,
        # which would split every share to the full depth cap.
        if self.chunk_s <= 0:
            object.__setattr__(self, "enabled", False)


@dataclass(frozen=True)
class CoalesceParams:
    """Cross-request batched-dispatch knobs (ISSUE 9; apps/miner.py
    coalescer + apps/scheduler.py grant window).

    Miner side: the pipelined executor drains up to ``lanes`` compatible
    small chunks (argmin mode, <= ``max_nonces`` each) from its local
    queue into one batched device launch. Scheduler side: within one
    QoS pump pass, after a small chunk is granted to a miner, further
    small grants may target the same miner's COALESCING WINDOW (up to
    ``lanes`` chunks) with the windowed chunks counting as ONE live
    chunk against the ``DBM_QOS_DEPTH`` cap — the "these N chunks may
    share a dispatch" hint that actually puts multiple small chunks in
    one miner's queue at once. Per-tenant DRR/admission accounting is
    per chunk, unchanged. ``enabled=False`` (or ``max_nonces <= 0``)
    reproduces stock grant and dispatch behavior bit-for-bit.
    """
    enabled: bool = True
    lanes: int = 8                 # max chunks per shared launch/window
    max_nonces: int = 1 << 20      # largest coalescible chunk (absolute)
    small_s: float = 0.25          # largest coalescible chunk (est. secs)

    def __post_init__(self):
        if self.max_nonces <= 0 or self.small_s <= 0:
            object.__setattr__(self, "enabled", False)


@dataclass(frozen=True)
class AdaptParams:
    """Self-tuning control-plane knobs (ISSUE 13; ``apps/adapt.py``).

    With ``enabled`` the scheduler mounts an
    :class:`~..apps.adapt.AdaptPlane`: an AIMD chunk/stripe-seconds
    controller driving per-chunk force latency toward ``force_s``, a
    coalescing-window controller (mouse-flood widen / pipeline-bubble
    collapse), and a congestion-style scheduler-wide admission bucket
    controlled on the queue-age slope. ``band`` is the hysteresis
    dead-band (fraction of setpoint); ``tick_s`` rate-limits
    adjustments; ``rate0`` seeds the admission rate (0 = start open at
    the controller ceiling). The per-controller flags isolate one
    controller for A/B work. Hard floors/ceilings live on the
    controllers themselves (class constants) — no observation sequence
    can push a knob outside them. ``enabled=False`` constructs
    nothing: bit-for-bit stock scheduling (the default was False for
    the ISSUE 13 soak PR; ON since ISSUE 14 after the soak ran clean).
    ``per_miner`` (default False) forks per-miner chunk setpoints once
    the pool's rate EWMAs diverge >4x (``DBM_ADAPT_PER_MINER``).
    """
    enabled: bool = True
    tick_s: float = 1.0
    band: float = 0.35
    force_s: float = 1.0
    rate0: float = 0.0
    chunk: bool = True
    coalesce: bool = True
    admit: bool = True
    per_miner: bool = False


@dataclass(frozen=True)
class QosParams:
    """Fair-share QoS dispatch knobs (apps/qos.py + apps/scheduler.py).

    Tenancy is the client conn id (no wire change). Three planes:

    - **Fairness**: deficit-round-robin across tenants at chunk
      granularity. A request estimated to scan longer than
      ``wholesale_s`` (pool throughput EWMA) is split into
      ``chunk_s``-seconds chunks (at most ``max_chunks``) held centrally
      and granted to miners incrementally, each miner's live FIFO capped
      at ``depth`` — so chunks of concurrent tenants interleave across
      the pool. Smaller (or cold-pool) requests dispatch wholesale
      through the stock path, which keeps single-tenant traffic — and
      everything with ``enabled=False`` — bit-identical to the stock
      FIFO scheduler.
    - **Admission**: per-tenant token bucket (``rate`` requests/s refill,
      ``burst`` capacity; rate 0 = off) plus a per-tenant cap of
      ``max_inflight`` granted-but-unanswered chunks (0 = off).
      ResultCache replays bypass admission entirely.
    - **Shedding**: when more than ``max_queued`` requests are queued
      (0 = unbounded), the OLDEST queued request is cancelled through
      the trace/cancel path and its conn closed, so a retrying client
      backs off and resubmits instead of hanging into its wire deadline.

    ``weights`` maps tenant id strings to DRR weights (grant share is
    proportional to weight under sustained contention); unlisted tenants
    get ``default_weight``.
    """
    enabled: bool = True
    chunk_s: float = 1.0           # target seconds of work per grant chunk
    max_chunks: int = 4096         # chunk-plan cap per request
    depth: int = 2                 # per-miner live chunks for QoS grants
    wholesale_s: float = 5.0       # below this estimate: stock dispatch
    max_queued: int = 1024         # total queued bound (0 = unbounded)
    max_inflight: int = 256        # per-tenant granted-unanswered cap
    rate: float = 0.0              # admission tokens/s (0 = admission off)
    burst: float = 8.0             # admission bucket capacity
    default_weight: float = 1.0
    weights: tuple = ()            # ((tenant_id_str, weight), ...)
    lazy: bool = True              # lazy ring walk (DBM_QOS_LAZY)

    def __post_init__(self):
        # chunk_s <= 0 pins the wholesale path (the repo-wide 0-disables
        # convention) rather than planning zero-second chunks.
        if self.chunk_s <= 0:
            object.__setattr__(self, "wholesale_s", float("inf"))

    def weight_for(self, tenant) -> float:
        for key, w in self.weights:
            if key == str(tenant):
                return max(w, 1e-3)
        return max(self.default_weight, 1e-3)


@dataclass(frozen=True)
class VerifyParams:
    """Verification-tier knobs (ISSUE 16; apps/scheduler.py claim checks
    + audits, apps/miner_plane.py trust plane).

    Miners so far could crash, wedge, or vanish — never LIE. With
    ``enabled``, every claimed winning ``(hash, nonce)`` is recomputed
    host-side (one ``bitcoin.hash_op`` SHA-256 per winner) before it may
    merge; mismatches are rejected as ``claim_failed`` lease events and
    the chunk re-granted. ``audit_p`` re-grants a random subwindow
    (capped at ``audit_max_nonces``) of a completed chunk to a disjoint
    miner with that probability and cross-checks the sub-argmin — the
    only defense against a lazy miner that returns a VALID but
    non-minimal pair without scanning. Trust starts at 1.0 per miner,
    multiplies by ``trust_decay`` per failure, steps back by
    ``trust_recover`` per confirmed result (clamped to
    ``[trust_floor, 1.0]``); below ``trust_bar`` a miner is ineligible
    for new grants (desperation dispatch still floors availability).
    ``enabled=False`` with ``audit_p=0`` is bit-for-bit stock: no
    recompute, no RNG draw, no trust bookkeeping on any hot path.
    """
    enabled: bool = True
    audit_p: float = 0.0
    audit_max_nonces: int = 1 << 16
    trust_decay: float = 0.25
    trust_recover: float = 0.05
    trust_floor: float = 0.01
    trust_bar: float = 0.2


@dataclass(frozen=True)
class GatewayParams:
    """Scheduler-federation knobs (ISSUE 20; apps/gateway.py GatewayMiner
    + the repeat-JOIN rate-hint refresh in apps/scheduler.py /
    apps/replicas.py).

    A GatewayMiner JOINs a parent scheduler as ONE miner whose rate hint
    is the summed rate EWMAs of its downstream pool and re-shards each
    granted chunk through a stock inner scheduler — zero wire change.
    ``hint_s`` paces the pool-sum refresh (re-sent as a repeat JOIN over
    the existing ``Rate`` extension); ``min_pool`` delays the parent
    JOIN until that many inner miners exist; ``orphan_s`` bounds how
    long an EMPTY inner pool may sit on granted work before the gateway
    drops its parent conn and lets the stock re-issue plane recover.
    ``enabled=False`` (``DBM_GATEWAY=0``) is bit-for-bit stock flat
    topology: repeat JOINs register fresh miners exactly as before.
    """
    enabled: bool = True
    hint_s: float = 2.0
    min_pool: int = 1
    orphan_s: float = 10.0


@dataclass(frozen=True)
class RetryParams:
    """Client submit-with-retry knobs (apps/client.py submit_with_retry).

    ``attempts`` counts total tries (1 = the reference one-shot submit);
    ``timeout_s`` is the per-attempt Result deadline (0 = wait forever —
    transport death still triggers a retry); backoff between attempts is
    exponential from ``backoff_s`` capped at ``backoff_cap_s``. Budget
    ``timeout_s``/``backoff_s`` above the scheduler's epoch death window
    (``epoch_limit * epoch_millis``): LSP close is a local flush with no
    wire handshake, so an abandoned attempt's request is only cancelled
    once the scheduler's epoch timer declares the conn lost, and a faster
    resubmission queues behind it (latency, never a wrong answer).
    """
    attempts: int = 3
    timeout_s: float = 0.0
    backoff_s: float = 0.5
    backoff_cap_s: float = 8.0


@dataclass
class FrameworkConfig:
    params: Params = field(default_factory=Params)
    compute: str = "auto"          # auto | host | jax
    batch: int | None = None       # None -> platform default
    lease: LeaseParams = field(default_factory=LeaseParams)
    retry: RetryParams = field(default_factory=RetryParams)
    cache: CacheParams = field(default_factory=CacheParams)
    stripe: StripeParams = field(default_factory=StripeParams)
    qos: QosParams = field(default_factory=QosParams)

    def make_searcher(self, data: str):
        """Build the configured searcher for one message string.

        Tier-valued settings (``jnp``/``pallas``) are threaded through
        explicitly rather than re-read from the environment downstream
        (review r3: a programmatic ``FrameworkConfig(compute="pallas")``
        silently fell back to jnp unless the env var happened to be set).
        """
        if self.compute == "host":
            from ..apps.miner import HostSearcher
            return HostSearcher(data)
        if self.compute == "jax":
            from ..models import NonceSearcher
            apply_jax_platform_env()
            return NonceSearcher(data, batch=self.batch or (1 << 20))
        from ..apps.miner import default_searcher_factory
        tier = self.compute if self.compute in ("jnp", "pallas") else None
        return default_searcher_factory(data, self.batch, tier=tier)


def lease_from_env() -> LeaseParams:
    d = LeaseParams()
    return LeaseParams(
        enabled=_int_env("DBM_LEASE", 1) != 0,
        grace_s=_float_env("DBM_LEASE_GRACE_S", d.grace_s),
        factor=_float_env("DBM_LEASE_FACTOR", d.factor),
        floor_s=_float_env("DBM_LEASE_FLOOR_S", d.floor_s),
        tick_s=_float_env("DBM_LEASE_TICK_S", d.tick_s),
        quarantine_after=_int_env("DBM_LEASE_QUARANTINE", d.quarantine_after),
        queue_alarm_s=_float_env("DBM_QUEUE_ALARM_S", d.queue_alarm_s),
        fifo_aware=_int_env("DBM_LEASE_FIFO", 1) != 0,
        desperation=_int_env("DBM_DESPERATION", 1) != 0,
    )


def cache_from_env() -> CacheParams:
    d = CacheParams()
    return CacheParams(
        enabled=_int_env("DBM_CACHE", 1) != 0,
        size=max(1, _int_env("DBM_CACHE_SIZE", d.size)),
    )


def stripe_from_env() -> StripeParams:
    d = StripeParams()
    return StripeParams(
        enabled=_int_env("DBM_STRIPE", 1) != 0,
        chunk_s=_float_env("DBM_STRIPE_CHUNK_S", d.chunk_s),
        depth=max(1, _int_env("DBM_STRIPE_DEPTH", d.depth)),
    )


def coalesce_from_env() -> CoalesceParams:
    d = CoalesceParams()
    return CoalesceParams(
        enabled=_int_env("DBM_COALESCE", 1) != 0,
        lanes=max(2, _int_env("DBM_COALESCE_LANES", d.lanes)),
        max_nonces=_int_env("DBM_COALESCE_MAX", d.max_nonces),
        small_s=_float_env("DBM_COALESCE_SMALL_S", d.small_s),
    )


def qos_from_env() -> QosParams:
    d = QosParams()
    weights = []
    for part in _str_env("DBM_QOS_WEIGHTS", "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        tenant, _, raw = part.partition(":")
        try:
            weights.append((tenant.strip(), float(raw)))
        except ValueError:
            continue   # malformed pair: ignored, like every other knob
    return QosParams(
        enabled=_int_env("DBM_QOS", 1) != 0,
        chunk_s=_float_env("DBM_QOS_CHUNK_S", d.chunk_s),
        max_chunks=max(1, _int_env("DBM_QOS_MAX_CHUNKS", d.max_chunks)),
        depth=max(1, _int_env("DBM_QOS_DEPTH", d.depth)),
        wholesale_s=_float_env("DBM_QOS_WHOLESALE_S", d.wholesale_s),
        max_queued=max(0, _int_env("DBM_QOS_MAX_QUEUED", d.max_queued)),
        max_inflight=max(0, _int_env("DBM_QOS_MAX_INFLIGHT",
                                     d.max_inflight)),
        rate=max(0.0, _float_env("DBM_QOS_RATE", d.rate)),
        burst=max(1.0, _float_env("DBM_QOS_BURST", d.burst)),
        default_weight=_float_env("DBM_QOS_WEIGHT_DEFAULT",
                                  d.default_weight),
        weights=tuple(weights),
        lazy=_int_env("DBM_QOS_LAZY", 1) != 0,
    )


def adapt_from_env() -> AdaptParams:
    d = AdaptParams()
    return AdaptParams(
        enabled=_int_env("DBM_ADAPT", 1) != 0,
        tick_s=max(0.01, _float_env("DBM_ADAPT_TICK_S", d.tick_s)),
        band=min(0.9, max(0.0, _float_env("DBM_ADAPT_BAND", d.band))),
        force_s=max(0.01, _float_env("DBM_ADAPT_FORCE_S", d.force_s)),
        rate0=max(0.0, _float_env("DBM_ADAPT_RATE0", d.rate0)),
        chunk=_int_env("DBM_ADAPT_CHUNK", 1) != 0,
        coalesce=_int_env("DBM_ADAPT_COALESCE", 1) != 0,
        admit=_int_env("DBM_ADAPT_ADMIT", 1) != 0,
        per_miner=_int_env("DBM_ADAPT_PER_MINER", 0) != 0,
    )


def verify_from_env() -> VerifyParams:
    d = VerifyParams()
    # The ENV default for audits is 0.02 (ISSUE 20 flip after the ISSUE
    # 16 soak) while the dataclass field stays 0.0: env-configured
    # deployments get the lazy-miner defense by default, but programmatic
    # ``VerifyParams()`` constructions — dbmcheck scenarios, bench
    # probes, fake-miner rigs whose fabricated hashes any audit would
    # convict — stay audit-free and deterministic unless they opt in.
    return VerifyParams(
        enabled=_int_env("DBM_VERIFY", 1) != 0,
        audit_p=min(1.0, max(0.0, _float_env("DBM_AUDIT_P", 0.02))),
        audit_max_nonces=max(1, _int_env("DBM_AUDIT_MAX",
                                         d.audit_max_nonces)),
        trust_decay=min(0.99, max(0.01, _float_env("DBM_TRUST_DECAY",
                                                   d.trust_decay))),
        trust_recover=min(0.99, max(0.001, _float_env("DBM_TRUST_RECOVER",
                                                      d.trust_recover))),
        trust_floor=min(1.0, max(0.0, _float_env("DBM_TRUST_FLOOR",
                                                 d.trust_floor))),
        trust_bar=min(1.0, max(0.0, _float_env("DBM_TRUST_BAR",
                                               d.trust_bar))),
    )


def gateway_from_env() -> GatewayParams:
    d = GatewayParams()
    return GatewayParams(
        enabled=_int_env("DBM_GATEWAY", 1) != 0,
        hint_s=max(0.05, _float_env("DBM_GATEWAY_HINT_S", d.hint_s)),
        min_pool=max(1, _int_env("DBM_GATEWAY_MIN_POOL", d.min_pool)),
        orphan_s=max(0.1, _float_env("DBM_GATEWAY_ORPHAN_S", d.orphan_s)),
    )


def retry_from_env() -> RetryParams:
    d = RetryParams()
    return RetryParams(
        attempts=max(1, _int_env("DBM_RETRY_ATTEMPTS", d.attempts)),
        timeout_s=_float_env("DBM_RETRY_TIMEOUT_S", d.timeout_s),
        backoff_s=_float_env("DBM_RETRY_BACKOFF_S", d.backoff_s),
        backoff_cap_s=_float_env("DBM_RETRY_BACKOFF_CAP_S", d.backoff_cap_s),
    )


def from_env() -> FrameworkConfig:
    params = Params(
        epoch_limit=_int_env("DBM_EPOCH_LIMIT", Params().epoch_limit),
        epoch_millis=_int_env("DBM_EPOCH_MILLIS", Params().epoch_millis),
        window_size=_int_env("DBM_WINDOW", Params().window_size),
        max_backoff_interval=_int_env("DBM_MAX_BACKOFF",
                                      Params().max_backoff_interval),
    )
    # 0/unset/malformed -> platform default (the _env contract: a bad
    # override must never crash an endpoint).
    batch = _int_env("DBM_BATCH", 0)
    return FrameworkConfig(
        params=params,
        # Normalized once here so every downstream comparison (make_searcher,
        # default_searcher_factory, models.default_tier) sees one casing.
        compute=_str_env("DBM_COMPUTE", "auto").lower(),
        batch=batch if batch > 0 else None,
        lease=lease_from_env(),
        retry=retry_from_env(),
        cache=cache_from_env(),
        stripe=stripe_from_env(),
        qos=qos_from_env(),
    )
