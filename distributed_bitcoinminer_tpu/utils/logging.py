"""Structured logging for every framework process.

Replaces the reference's two logging mechanisms — per-packet stderr debug
lines behind ``lspnet.EnableDebugLogs`` (ref: lspnet/conn.go:32-42) and the
scheduler's microsecond file logger (ref: bitcoin/server/server.go:428-445) —
with one ``logging`` configuration under the ``dbm`` namespace, plus the
same per-packet trace switch on the simulated transport.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname).1s %(message)s"
_DATEFMT = "%H:%M:%S"


def configure_logging(level: int = logging.INFO,
                      logfile: Optional[str] = None,
                      packet_trace: bool = False) -> logging.Logger:
    """Set up the ``dbm`` logger tree; returns the root framework logger.

    ``packet_trace`` also flips the lspnet per-packet DROP/DELAY trace (the
    reference's EnableDebugLogs).
    """
    logger = logging.getLogger("dbm")
    logger.setLevel(level)
    logger.handlers.clear()
    handler = (logging.FileHandler(logfile) if logfile
               else logging.StreamHandler(sys.stderr))
    handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
    logger.addHandler(handler)
    if packet_trace:
        from .. import lspnet
        lspnet.enable_debug_logs(True)
    return logger
