"""Structured logging for every framework process.

Replaces the reference's two logging mechanisms — per-packet stderr debug
lines behind ``lspnet.EnableDebugLogs`` (ref: lspnet/conn.go:32-42) and the
scheduler's microsecond file logger (ref: bitcoin/server/server.go:428-445) —
with one ``logging`` configuration under the ``dbm`` namespace, plus the
same per-packet trace switch on the simulated transport.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Optional

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname).1s %(message)s"
_DATEFMT = "%H:%M:%S"

# Re-entrancy state: the handler THIS module installed and the sink it
# points at. configure_logging used to clear the whole handler list and
# re-add — two configuring components in one process (scheduler + miner in
# a test, or a test harness wrapping an app main) raced each other's
# clear/add and duplicated or dropped sinks; and a handler added by someone
# else (pytest caplog, a user's extra sink) was silently destroyed.
_state_lock = threading.Lock()
_installed: dict = {"handler": None, "sink": None}


def configure_logging(level: int = logging.INFO,
                      logfile: Optional[str] = None,
                      packet_trace: bool = False) -> logging.Logger:
    """Set up the ``dbm`` logger tree; returns the root framework logger.

    Idempotent and symmetric: calling it again with the same sink keeps the
    existing handler (no clear/re-add race, no duplicate lines); calling it
    with a different sink replaces only the handler this function
    installed, leaving foreign handlers (test capture, extra user sinks)
    alone. ``packet_trace`` sets the lspnet per-packet DROP/DELAY trace
    (the reference's EnableDebugLogs) to EXACTLY the value given — False
    now disables a previously-enabled trace instead of leaving it on.
    """
    logger = logging.getLogger("dbm")
    sink = ("file", os.path.abspath(logfile)) if logfile else ("stderr",)
    with _state_lock:
        logger.setLevel(level)
        prev = _installed["handler"]
        if prev is None or _installed["sink"] != sink \
                or prev not in logger.handlers:
            if prev is not None and prev in logger.handlers:
                logger.removeHandler(prev)
                prev.close()
            handler = (logging.FileHandler(logfile) if logfile
                       else logging.StreamHandler(sys.stderr))
            handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
            logger.addHandler(handler)
            _installed["handler"] = handler
            _installed["sink"] = sink
    from .. import lspnet
    lspnet.enable_debug_logs(bool(packet_trace))
    return logger
