"""Shared environment-variable parsing (one copy for config and metrics).

Kept dependency-free: ``utils.metrics`` must stay importable mid-way
through the ``utils.config`` -> ``lsp`` -> ``_engine`` import chain, so
neither module can import the other — both pull these helpers from here.
Malformed values fall back to the default silently, matching the knob
philosophy everywhere else (a bad override must never crash an endpoint).
"""

from __future__ import annotations

import os


def int_env(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def float_env(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def str_env(name: str, default=None):
    """Raw string knob (``default`` when unset — callers parse/compare).

    Exists so EVERY ``DBM_*`` read in the tree routes through this module
    (the dbmlint knob-hygiene analyzer enforces it): one grep target for
    the full knob surface, one place where read semantics can change.
    """
    raw = os.environ.get(name)
    return default if raw is None else raw
