"""Profiling hooks: wall-clock meters and the JAX device profiler.

The reference has no profiler (SURVEY §5); the TPU build adds two:
``Timer`` for host-side rate meters (nonces/sec — the BASELINE metric) and
``device_trace`` wrapping ``jax.profiler.trace`` so a search can be captured
for TensorBoard/XProf without touching call sites.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


class Timer:
    """Wall-clock meter: ``with Timer() as t: ...; t.rate(n)``."""

    def __init__(self) -> None:
        self.seconds = 0.0
        self._t0: Optional[float] = None

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0

    def rate(self, items: int) -> float:
        """items/second (0 when nothing was measured)."""
        return items / self.seconds if self.seconds else 0.0


@contextlib.contextmanager
def device_trace(logdir: Optional[str]) -> Iterator[None]:
    """Capture a JAX profiler trace into ``logdir`` (no-op when None)."""
    if not logdir:
        yield
        return
    import jax
    with jax.profiler.trace(logdir):
        yield
