"""Profiling hooks: wall-clock meters and the JAX device profiler.

The reference has no profiler (SURVEY §5); the TPU build adds two:
``Timer`` for host-side rate meters (nonces/sec — the BASELINE metric) and
``device_trace`` wrapping ``jax.profiler.trace`` so a search can be captured
for TensorBoard/XProf without touching call sites. The XProf logdir knob is
``DBM_TRACE_XPROF`` (ISSUE 10 satellite; ``DBM_TRACE`` itself now switches
the request-scoped tracing plane, utils/trace.py — the two are orthogonal:
this one captures kernels, that one captures requests).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional

from ._env import str_env as _str_env


class Timer:
    """Wall-clock meter: ``with Timer() as t: ...; t.rate(n)``.

    Tolerates misuse before ``__enter__`` (ISSUE 10 satellite): an
    un-entered timer reads 0.0 seconds and 0.0 rate instead of raising
    ``TypeError`` from ``None - float`` — a profiling helper must never
    be the thing that kills a measurement path (the bench's exception
    envelope would record the TypeError as the tier's failure).
    """

    def __init__(self) -> None:
        self.seconds = 0.0
        self._t0: Optional[float] = None

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self._t0 is None:
            return          # never entered: stay at 0.0, don't raise
        self.seconds = time.perf_counter() - self._t0

    def rate(self, items: int) -> float:
        """items/second (0 when nothing was measured)."""
        return items / self.seconds if self.seconds else 0.0


def xprof_dir(tier: Optional[str] = None) -> Optional[str]:
    """The configured XProf capture directory (``DBM_TRACE_XPROF``;
    None/empty = capture disabled), with an optional per-tier subdir —
    the one place the knob is read, so the knob-hygiene lint covers it
    and every call site composes paths the same way."""
    base = _str_env("DBM_TRACE_XPROF")
    if not base:
        return None
    return os.path.join(base, tier) if tier else base


@contextlib.contextmanager
def device_trace(logdir: Optional[str] = None,
                 tier: Optional[str] = None) -> Iterator[None]:
    """Capture a JAX profiler trace into ``logdir`` (no-op when None).

    ``logdir=None`` reads ``DBM_TRACE_XPROF`` via :func:`xprof_dir`
    (with the optional ``tier`` subdir), so call sites need no knob
    plumbing of their own; an explicit ``logdir`` wins.
    """
    if logdir is None:
        logdir = xprof_dir(tier)
    if not logdir:
        yield
        return
    import jax
    with jax.profiler.trace(logdir):
        yield
