"""Runtime sanitizer plane: event-loop stall watchdog + thread ownership.

The dbmlint static pack (``distributed_bitcoinminer_tpu/analysis``)
catches the two recurring concurrency bug classes of this codebase at
the AST level — synchronous JAX/subprocess work reachable from ``async
def`` bodies (PR 4 review: a wedged backend init on the event loop
starves LSP heartbeats and gets the miner declared dead), and scheduler
state mutated off its owning thread. This module is the RUNTIME
complement for what an AST cannot see (dynamic dispatch, third-party
callbacks, new code paths): opt-in via ``DBM_SANITIZE=1``, it

- installs an **asyncio slow-callback watchdog**: every loop callback is
  timed (one wrapped ``Handle._run``, two ``monotonic()`` reads — cheap
  enough for the chaos/QoS suites to run sanitized wholesale), and one
  that holds the loop longer than ``DBM_SANITIZE_SLOW_S`` seconds
  (default 0.1) is NAMED in a ``dbm.sanitize`` warning and counted in
  the ``sanitize.slow_callbacks`` metric, with the worst stall kept in
  ``sanitize.slow_callback_worst_s``;
- provides **thread-ownership assertions**: :class:`ThreadOwner` pins a
  set of structures to the first thread that touches them (the
  scheduler's miners/queue/in-flight tables are asyncio-actor state —
  any cross-thread touch is a data race today or a heisenbug tomorrow),
  and :func:`assert_off_loop` asserts a compute entry point is NOT
  running on an event-loop thread (the miner's searcher resolution and
  blocking search must stay on worker threads).

Everything is observability-only: violations warn and count, never
raise — a sanitizer that can kill a healthy-but-slow production process
is worse than the bug it hunts. ``DBM_SANITIZE`` unset (the default)
costs one boolean check per guarded call site and installs nothing.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import threading
import time
from typing import Optional

from ._env import float_env as _float_env, int_env as _int_env
from .metrics import registry as _registry

_log = logging.getLogger("dbm.sanitize")


def _flight_dump(event: str, **detail) -> None:
    """Mirror a sanitizer warning into the flight recorder and dump the
    ring (ISSUE 10): a loop stall or ownership violation is exactly the
    moment the surrounding control-plane event window matters. Imported
    lazily (trace -> metrics -> _env is the import chain; sanitize sits
    beside trace, not under it) and guarded by the trace plane's own
    knob — a sanitized-but-untraced run keeps stock behavior."""
    from . import trace as _trace
    if not _trace.enabled():
        return
    _trace.flight(event, **detail)
    _trace.flight_dump(f"sanitizer: {event}")


def enabled() -> bool:
    """True when the sanitizer plane is switched on (``DBM_SANITIZE=1``).

    Read per call (not cached at import) so tests and embedded drivers
    can toggle the knob around individual constructions.
    """
    return _int_env("DBM_SANITIZE", 0) != 0


def slow_threshold_s() -> float:
    """Watchdog bound: callbacks holding the loop longer than this warn."""
    return _float_env("DBM_SANITIZE_SLOW_S", 0.1)


# --------------------------------------------------------------- watchdog

_install_lock = threading.Lock()
_orig_handle_run = None          # asyncio.events.Handle._run before patch
_threshold_s: float = 0.1


def _describe_callback(handle) -> str:
    """Best-effort name of a Handle's callback for the stall warning.

    Coroutine steps matter most: a Task's step handle is a
    ``TaskStepMethWrapper`` whose repr names nothing — but its
    ``__self__`` is the Task, and the Task's coroutine qualname is
    exactly "which async def held the loop" (the PR-4 wedged-probe
    incident shape this plane exists to attribute)."""
    cb = getattr(handle, "_callback", None)
    while isinstance(cb, functools.partial):
        cb = cb.func
    owner = getattr(cb, "__self__", None)
    if isinstance(owner, asyncio.Task):
        coro = owner.get_coro()
        name = getattr(coro, "__qualname__", None)
        if name:
            return f"coroutine {name}"
    for attr in ("__qualname__", "__name__"):
        name = getattr(cb, attr, None)
        if name:
            mod = getattr(cb, "__module__", None)
            return f"{mod}.{name}" if mod else name
    return repr(cb)


def install_watchdog(threshold_s: Optional[float] = None) -> None:
    """Wrap ``asyncio.events.Handle._run`` with a stall timer (idempotent).

    Covers every loop callback — ``call_soon``/``call_later`` handles AND
    coroutine steps (Task.__step is itself scheduled through a Handle) —
    so a synchronous ``subprocess.run`` inside an ``async def`` shows up
    named, not as mystery heartbeat loss. Installed once per process;
    a later call only tightens/loosens the threshold.
    """
    global _orig_handle_run, _threshold_s
    with _install_lock:
        if threshold_s is not None:
            _threshold_s = threshold_s
        else:
            _threshold_s = slow_threshold_s()
        if _orig_handle_run is not None:
            return
        _orig_handle_run = asyncio.events.Handle._run
        slow = _registry().counter("sanitize.slow_callbacks")
        worst = _registry().gauge("sanitize.slow_callback_worst_s")
        orig = _orig_handle_run

        def _timed_run(self):
            t0 = time.monotonic()
            try:
                return orig(self)
            finally:
                dt = time.monotonic() - t0
                if dt >= _threshold_s:
                    slow.inc()
                    if dt > worst.value:
                        worst.set(dt)
                    who = _describe_callback(self)
                    _log.warning(
                        "event-loop stall: %s held the loop %.3fs "
                        "(bound %.3fs) — move the blocking work to a "
                        "worker thread (asyncio.to_thread)",
                        who, dt, _threshold_s)
                    _flight_dump("slow_callback", callback=who,
                                 held_s=round(dt, 4))

        asyncio.events.Handle._run = _timed_run


def uninstall_watchdog() -> None:
    """Restore the stock ``Handle._run`` (test isolation)."""
    global _orig_handle_run
    with _install_lock:
        if _orig_handle_run is not None:
            asyncio.events.Handle._run = _orig_handle_run
            _orig_handle_run = None


def ensure_sanitizer() -> bool:
    """Install the watchdog iff ``DBM_SANITIZE=1``; returns enabled().

    The scheduler and miner call this at construction (the same shape as
    ``metrics.ensure_emitter``), so exporting one knob sanitizes every
    endpoint in the process with no call-site changes.
    """
    if not enabled():
        return False
    install_watchdog()
    return True


# --------------------------------------------------------- thread ownership

class ThreadOwner:
    """Asserts a structure set is only touched from its owning thread.

    The owner is the FIRST thread that calls :meth:`assert_here` — for
    the scheduler that is the thread running its asyncio loop, without
    needing the loop to exist at construction time. Violations warn with
    both thread names and count in ``sanitize.ownership_violations``;
    they never raise (observability-only, like the whole plane).
    """

    __slots__ = ("what", "_ident", "_name")

    def __init__(self, what: str):
        self.what = what
        self._ident: Optional[int] = None
        self._name = ""

    def assert_here(self) -> bool:
        me = threading.get_ident()
        if self._ident is None:
            self._ident = me
            self._name = threading.current_thread().name
            return True
        if me == self._ident:
            return True
        _registry().counter("sanitize.ownership_violations").inc()
        _log.warning(
            "thread-ownership violation: %s touched from thread %r "
            "(owner: %r)", self.what, threading.current_thread().name,
            self._name)
        _flight_dump("ownership_violation", what=self.what,
                     thread=threading.current_thread().name)
        return False


def assert_off_loop(what: str) -> bool:
    """Assert the caller is NOT on an event-loop thread.

    Guards compute entry points (searcher resolution, blocking search):
    a running loop in the current thread means a blocking call is about
    to starve it. Warns + counts ``sanitize.loop_blocking``; never
    raises.
    """
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return True
    _registry().counter("sanitize.loop_blocking").inc()
    _log.warning(
        "%s ran ON the event loop; expected a worker thread "
        "(asyncio.to_thread)", what)
    _flight_dump("loop_blocking", what=what)
    return False
