"""Seeded chaos harness: scripted faults over the lspnet knobs + app plane.

The ``faults`` knobs mirror the reference staff harness — global packet
drop/delay/corruption percentages. Real outages are rarely that symmetric:
a miner process dies and comes back, a device wedges while its transport
keeps heartbeating, one direction of one flow blackholes. This module adds
those primitives and a deterministic, seeded schedule runner over all of
them, so the property suite in ``tests/test_chaos.py`` can replay the same
storm on every run:

- :class:`WedgeableSearcher` — compute that can be remotely hung and
  released, modeling a stuck device dispatch behind a healthy LSP
  connection (the failure the scheduler's chunk leases exist for);
- :class:`ChaosMiner` — a restartable miner handle with crash-kill,
  wedge/unwedge, and restart;
- one-sided partitions of a single connection
  (:func:`lspnet.partition_conn`), driven here by miner name;
- :func:`generate_schedule` — a seeded list of self-healing fault
  episodes (every kill gets a restart, every wedge an unwedge, every
  partition a heal, every knob flip a clear);
- :func:`run_schedule` — applies a schedule on the event loop clock and
  restores a clean network/pool state in its ``finally``, so an
  interrupted run cannot leak faults into the next test.

Determinism: schedule CONTENT is fully determined by the seed.
Packet-level coin flips (``faults.sometimes``) ride Python's global
``random``; call :func:`seed_packet_faults` to pin those too. Event
TIMING rides the event-loop clock, so cross-run interleavings may differ
— the invariants tested (eventual correct answer, no double delivery,
pool convergence) hold for every interleaving.
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from . import faults

logger = logging.getLogger("lspnet.chaos")


def seed_packet_faults(seed: int) -> None:
    """Pin the global RNG behind ``faults.sometimes`` drop/delay flips."""
    random.seed(seed)


# --------------------------------------------------------------- app plane

class WedgeableSearcher:
    """Wrap a searcher so its compute can be hung and released at will.

    While wedged, ``search``/``search_until`` block in the miner's worker
    thread — the asyncio loop keeps serving LSP heartbeats, so the
    scheduler's epoch-limit drop detection never fires. That is exactly
    the straggler the chunk-lease plane speculates around.
    """

    def __init__(self, inner, gate: Optional[threading.Event] = None):
        self._inner = inner
        if gate is None:
            gate = threading.Event()
            gate.set()
        # A caller-owned gate keeps ITS state: the searcher is built
        # lazily on the first Request, possibly after wedge() was called.
        self.gate = gate
        # Expose search_until ONLY when the inner searcher speaks it: the
        # miner echoes the Request's target iff the attribute exists
        # (apps/miner._search), and the scheduler trusts that echo to
        # claim first-qualifying semantics — a fabricated until wrapper
        # around a plain-argmin searcher would masquerade as
        # extension-speaking and break the weak-merge detection. A None
        # instance attribute shadows the class method, and the miner's
        # `getattr(searcher, "search_until", None) is not None` check
        # then takes the stock path (no echo), exactly like a real
        # Target-dropping miner.
        if not hasattr(inner, "search_until"):
            self.search_until = None

    def search(self, lower: int, upper: int):
        self.gate.wait()
        return self._inner.search(lower, upper)

    def search_until(self, lower: int, upper: int, target: int):
        self.gate.wait()
        return self._inner.search_until(lower, upper, target)


#: Byzantine lying modes (ISSUE 16). ``wrong_hash`` fabricates an
#: impossibly good pair the claim check rejects in microseconds;
#: ``sentinel`` returns a REAL pair (one hash of the range's first
#: nonce, no scan) that only a probabilistic audit can catch;
#: ``selective`` alternates honest and sentinel calls — the miner that
#: builds trust and spends it.
BYZANTINE_MODES = ("wrong_hash", "sentinel", "selective")


class ByzantineSearcher:
    """Wrap a searcher so its ANSWERS (not its liveness) can be turned
    adversarial at will — the failure class the verification tier
    (ISSUE 16) exists for, orthogonal to :class:`WedgeableSearcher`'s
    stuck-compute model. While the shared ``lie_flag`` is set, calls
    fabricate per ``mode`` (see :data:`BYZANTINE_MODES`); clear, they
    pass through to the inner searcher untouched, so one handle models
    a miner that turns coat mid-storm and back.
    """

    def __init__(self, inner, data: str, mode: str,
                 lie_flag: threading.Event):
        assert mode in BYZANTINE_MODES, mode
        self._inner = inner
        self._data = data
        self._mode = mode
        self._lie_flag = lie_flag
        self._calls = 0
        # Same shadow idiom as WedgeableSearcher: only claim the until
        # extension when the inner searcher actually speaks it.
        if not hasattr(inner, "search_until"):
            self.search_until = None

    def _fabricate(self, lower: int):
        """The lie for this call, or None to answer honestly."""
        if not self._lie_flag.is_set():
            return None
        self._calls += 1
        if self._mode == "selective" and self._calls % 2:
            return None
        if self._mode == "wrong_hash":
            # An unbeatable claimed hash for a nonce that almost
            # certainly does not produce it: wins every merge race
            # unless checked, dies instantly under DBM_VERIFY.
            return (0, lower)
        # sentinel (and selective's lying calls): hash ONE nonce and
        # claim it as the scan's answer — a real pair, in range, that
        # passes any recompute; only re-execution can expose it.
        from ..bitcoin.hash import hash_op
        return (hash_op(self._data, lower), lower)

    def search(self, lower: int, upper: int):
        out = self._fabricate(lower)
        return out if out is not None else self._inner.search(lower, upper)

    def search_until(self, lower: int, upper: int, target: int):
        out = self._fabricate(lower)
        if out is not None:
            h, nonce = out
            return (h, nonce, h < target)
        return self._inner.search_until(lower, upper, target)


class ChaosMiner:
    """A restartable miner with crash-kill, compute-wedge, and
    byzantine-answer controls.

    One handle models one miner "process" across restarts: each
    :meth:`start` joins the pool as a fresh LSP connection, and the wedge
    gate — like the byzantine lie flag — is shared across restarts (an
    operator unwedges a host, not a process incarnation; a compromised
    host stays compromised through a respawn).
    """

    def __init__(self, hostport: str, params=None,
                 searcher_factory: Optional[Callable] = None,
                 name: str = "miner", byzantine: str = ""):
        from ..apps.miner import MinerWorker  # lazy: keep lspnet app-free
        self._worker_cls = MinerWorker
        self.hostport = hostport
        self.params = params
        self.name = name
        self.gate = threading.Event()
        self.gate.set()
        #: Set = currently lying (only meaningful with a ``byzantine``
        #: mode; the miner starts honest either way and a schedule's
        #: "byzantine" event flips it).
        self.lie_flag = threading.Event()
        self.byzantine = byzantine
        inner = searcher_factory
        if inner is None:
            from ..apps.miner import HostSearcher
            inner = lambda data, batch: HostSearcher(data)  # noqa: E731
        if byzantine:
            base = inner
            inner = lambda data, batch: ByzantineSearcher(  # noqa: E731
                base(data, batch), data, byzantine, self.lie_flag)
        self._factory = lambda data, batch: WedgeableSearcher(
            inner(data, batch), self.gate)
        self.worker = None
        self.task: Optional[asyncio.Task] = None
        self.restarts = 0

    async def start(self) -> None:
        assert not self.alive, f"{self.name} already running"
        self.worker = self._worker_cls(self.hostport, params=self.params,
                                       searcher_factory=self._factory)
        await self.worker.join()
        self.task = asyncio.get_running_loop().create_task(self.worker.run())

    @property
    def alive(self) -> bool:
        return self.task is not None and not self.task.done()

    @property
    def conn_id(self) -> int:
        """Server-side conn id of the CURRENT incarnation (0 when dead)."""
        if self.worker is None or self.worker.client is None:
            return 0
        return self.worker.client.conn_id()

    def wedge(self) -> None:
        """Hang the next compute dispatch (LSP stays alive)."""
        logger.info("chaos: wedging %s", self.name)
        self.gate.clear()

    def unwedge(self) -> None:
        logger.info("chaos: unwedging %s", self.name)
        self.gate.set()

    @property
    def wedged(self) -> bool:
        return not self.gate.is_set()

    def go_byzantine(self) -> None:
        """Start lying per the ctor's ``byzantine`` mode (no-op without
        one — the flag is set but no ByzantineSearcher reads it)."""
        logger.info("chaos: %s turns byzantine (%s)", self.name,
                    self.byzantine or "no mode: inert")
        self.lie_flag.set()

    def go_honest(self) -> None:
        if self.lie_flag.is_set():
            logger.info("chaos: %s turns honest", self.name)
        self.lie_flag.clear()

    @property
    def lying(self) -> bool:
        return self.lie_flag.is_set()

    async def kill(self) -> None:
        """Crash, not close: abort the conn and drop the socket so the
        scheduler only learns of the death from its epoch timer."""
        if self.worker is None:
            return
        logger.info("chaos: killing %s (conn %d)", self.name, self.conn_id)
        client = self.worker.client
        if client is not None:
            if client._conn is not None:
                client._conn.abort()
            if client._ep is not None:
                client._ep.close()
        if self.task is not None:
            # A wedged compute thread never finishes its read loop; give
            # the task a moment, then cancel — the to_thread compute is
            # released by unwedge (run_schedule and tests do so in their
            # cleanup paths).
            try:
                await asyncio.wait_for(asyncio.shield(self.task), 1.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self.task.cancel()
                try:
                    await self.task
                except asyncio.CancelledError:
                    pass
            self.task = None
        self.worker = None

    async def restart(self) -> None:
        self.restarts += 1
        logger.info("chaos: restarting %s", self.name)
        # Unconditional: a worker whose run() already returned (transport
        # death) still owns an open endpoint + recv task until kill().
        await self.kill()
        await self.start()

    async def close(self) -> None:
        """Teardown for tests: release any wedged thread, then kill."""
        self.unwedge()
        await self.kill()


# ---------------------------------------------------------------- schedule

@dataclass(frozen=True)
class ChaosEvent:
    at: float          # seconds from schedule start
    action: str        # see _apply_event
    subject: str = ""  # miner name for app-plane actions
    value: int = 0     # percentage for knob actions


#: Episode kinds the generator draws from; each expands to a fault event
#: plus its healing event, so every generated schedule is self-healing.
EPISODES = ("drop_read", "drop_write", "delay", "kill", "wedge",
            "partition_in", "partition_out")

#: EPISODES plus the byzantine turn-coat episode (ISSUE 16). Kept out of
#: the default tuple so existing seeded schedules replay byte-identical;
#: storms that wire :class:`ChaosMiner` handles with a ``byzantine``
#: mode pass ``kinds=BYZ_EPISODES`` explicitly.
BYZ_EPISODES = EPISODES + ("byzantine",)


def generate_schedule(seed: int, duration_s: float,
                      miner_names: Sequence[str], *,
                      episodes: int = 6, max_percent: int = 30,
                      kinds: Sequence[str] = EPISODES,
                      ) -> List[ChaosEvent]:
    """Deterministic self-healing fault schedule for one seed.

    Each episode opens a fault at a seeded time and closes it a seeded
    interval later, always inside ``duration_s``; knob episodes draw a
    percentage in ``[5, max_percent]``. The same (seed, duration, names,
    kwargs) always yields the identical event list.
    """
    rng = random.Random(seed)
    events: List[ChaosEvent] = []
    # Each kind heals ITSELF only (its own knob / its own miner's conn):
    # episodes of different kinds routinely overlap, and a global reset
    # here would silently close another episode's still-open fault,
    # making the applied storm weaker than the schedule claims. (Two
    # overlapping episodes of the SAME kind still share one global knob —
    # the first heal closes both; inherent to the reference knob set.)
    heal_of = {"drop_read": "clear_drop_read",
               "drop_write": "clear_drop_write",
               "delay": "clear_delay", "kill": "restart",
               "wedge": "unwedge", "partition_in": "heal_in",
               "partition_out": "heal_out", "byzantine": "honest"}
    for _ in range(episodes):
        kind = rng.choice(list(kinds))
        start = rng.uniform(0.05, duration_s * 0.6)
        span = rng.uniform(duration_s * 0.15, duration_s * 0.35)
        subject = rng.choice(list(miner_names)) if miner_names else ""
        pct = rng.randint(5, max_percent)
        events.append(ChaosEvent(round(start, 3), kind, subject, pct))
        events.append(ChaosEvent(round(min(start + span, duration_s), 3),
                                 heal_of[kind], subject, 0))
    return sorted(events, key=lambda e: (e.at, e.action))


async def _apply_event(ev: ChaosEvent,
                       miners: Dict[str, "ChaosMiner"]) -> None:
    m = miners.get(ev.subject)
    if ev.action == "drop_read":
        faults.set_read_drop_percent(ev.value)
    elif ev.action == "drop_write":
        faults.set_write_drop_percent(ev.value)
    elif ev.action == "delay":
        faults.set_delay_message_percent(ev.value)
    elif ev.action == "clear_drop_read":
        faults.set_read_drop_percent(0)
    elif ev.action == "clear_drop_write":
        faults.set_write_drop_percent(0)
    elif ev.action == "clear_delay":
        faults.set_delay_message_percent(0)
    elif ev.action == "kill":
        if m is not None and m.alive:
            await m.kill()
    elif ev.action == "restart":
        if m is not None and not m.alive:
            await m.restart()
    elif ev.action == "wedge":
        if m is not None:
            m.wedge()
    elif ev.action == "unwedge":
        if m is not None:
            m.unwedge()
    elif ev.action == "byzantine":
        if m is not None:
            m.go_byzantine()
    elif ev.action == "honest":
        if m is not None:
            m.go_honest()
    elif ev.action == "partition_in":
        if m is not None and m.alive:
            faults.partition_conn(m.conn_id, inbound=True, outbound=False)
    elif ev.action == "partition_out":
        if m is not None and m.alive:
            faults.partition_conn(m.conn_id, inbound=False, outbound=True)
    elif ev.action in ("heal", "heal_in", "heal_out"):
        # Heal THIS miner's current conn only, in THIS episode's
        # direction only (see generate_schedule's heal_of note —
        # overlapping in/out episodes must not close each other). A
        # partition of an earlier, now-dead incarnation may linger in
        # the sets; run_schedule's final reset clears it.
        if m is not None:
            faults.heal_conn(m.conn_id,
                             inbound=ev.action != "heal_out",
                             outbound=ev.action != "heal_in")
    else:
        raise ValueError(f"unknown chaos action {ev.action!r}")


# ------------------------------------------------ process-level storms

#: Episode kinds of the PROCESS-level storm generator (ISSUE 12): a raw
#: SIGKILL of the replica owning the in-flight request, a SIGSTOP wedge
#: (partitioned-but-alive: the OS keeps its sockets, its beat seq
#: freezes — the fencing case), and a router kill (control-plane
#: outage: the data path must ride the last advertised membership).
PROC_EPISODES = ("kill_replica", "stop_replica", "kill_router")


@dataclass(frozen=True)
class ProcEpisode:
    """One process-storm episode: submit a request sized to outlive
    failure detection, inject the fault ``fault_at`` seconds later,
    assert the oracle-exact exactly-once reply, heal."""

    kind: str           # see PROC_EPISODES
    fault_at: float     # seconds after the episode's submit
    max_nonce: int      # request size (must outlive the detection window)
    tenant: str         # ring key (also the request data)


def generate_proc_storm(seed: int, episodes: int,
                        kinds: Sequence[str] = PROC_EPISODES,
                        nonce_range=(600_000, 1_200_000),
                        ) -> List[ProcEpisode]:
    """Deterministic process-storm schedule: same seed, same storm."""
    rng = random.Random(seed)
    out = []
    for i in range(episodes):
        out.append(ProcEpisode(
            kind=rng.choice(list(kinds)),
            fault_at=round(rng.uniform(0.05, 0.3), 3),
            max_nonce=rng.randrange(*nonce_range),
            tenant=f"storm{seed}#{i}"))
    return out


async def run_proc_episode(cluster, ep: ProcEpisode, params,
                           retry=None, reply_timeout_s: float = 60.0,
                           ) -> dict:
    """Execute one :class:`ProcEpisode` against a live
    :class:`~..apps.procs.ProcCluster` and HEAL afterwards (respawn the
    killed/fenced replica or router, wait for re-admission), so
    episodes compose into an arbitrarily long storm.

    The fault is raw signal injection; DETECTION is entirely the
    router's missed-beat watch — no kill hook exists anywhere in the
    process topology. Returns a record dict (kind, victim, elapsed,
    reply) after asserting the reply arrived exactly once (the retry
    plane's one-conn-at-a-time contract) and ORACLE-EXACT.
    """
    import time as _time
    from ..apps.client import submit_with_retry
    from ..apps.procs import read_beats, read_membership, resolve_owner
    from ..bitcoin.hash import scan_min
    from ..utils.config import RetryParams
    retry = retry or RetryParams(attempts=24, timeout_s=3.0,
                                 backoff_s=0.2, backoff_cap_s=1.0)
    owner = resolve_owner(cluster.statedir, ep.tenant)
    assert owner is not None, "no advertised ring before the episode"
    rid = owner[0]
    t0 = _time.monotonic()
    task = asyncio.create_task(submit_with_retry(
        f"ring:{cluster.statedir}", ep.tenant, ep.max_nonce, 0, params,
        retry))
    await asyncio.sleep(ep.fault_at)
    victim = f"replica{rid}" if ep.kind != "kill_router" else "router"
    if ep.kind == "kill_replica":
        cluster.kill_replica(rid)
    elif ep.kind == "stop_replica":
        cluster.stop_replica(rid)
    else:
        cluster.kill_router()
    fault_t = _time.monotonic()

    async def measure_rejoin() -> float:
        """Seconds from the fault until ALL the cluster's miner agents
        are serving on SURVIVING live replicas — the handoff dead air
        the fence-push channel (ISSUE 13 satellite) cuts from
        epoch-detection latency (~0.8 s) to ~one beat past the
        router's missed-beat window. Requiring the FULL population
        (``cluster.m``), not just one joined miner, keeps the
        measurement honest when the victim held an agent while
        another replica's agent never moved — a bare >=1 would record
        the router's fence latency and never the displaced agent's
        rejoin (review finding: the fence-push proof would pass
        vacuously on seeds whose victim was agent-free)."""
        want = max(1, getattr(cluster, "m", 1))
        while True:
            m = read_membership(cluster.statedir)
            if m is not None and rid not in m.live:
                live = {r: v["incarnation"] for r, v in m.live.items()}
                joined = sum(
                    b.miners for b in read_beats(cluster.statedir)
                    if b.rid in live and b.serving
                    and b.incarnation == live[b.rid])
                if joined >= want:
                    return _time.monotonic() - fault_t
            await asyncio.sleep(0.02)

    rejoin_task = None
    if ep.kind in ("kill_replica", "stop_replica"):
        rejoin_task = asyncio.create_task(measure_rejoin())
    try:
        got = await asyncio.wait_for(task, reply_timeout_s)
    except BaseException:
        # A reply timeout must not orphan the membership poller — it
        # would keep spinning until loop teardown and bury the real
        # failure under "Task was destroyed but it is pending".
        if rejoin_task is not None:
            rejoin_task.cancel()
        raise
    rejoin_s = None
    if rejoin_task is not None:
        try:
            rejoin_s = round(await asyncio.wait_for(
                rejoin_task, reply_timeout_s), 3)
        except asyncio.TimeoutError:
            rejoin_task.cancel()
    want = scan_min(ep.tenant, 0, ep.max_nonce + 1)
    assert got is not None, f"{ep} never answered"
    assert got[:2] == want, (ep, got, want)
    # Heal: bring the topology back to full strength for the next
    # episode (fenced SIGSTOP victims are woken first so they can
    # observe the fence and exit for respawn).
    fenced_exit = None
    if ep.kind == "stop_replica":
        cluster.cont_replica(rid)
        deadline = _time.monotonic() + 20.0
        proc = cluster.procs.get(victim)
        while proc is not None and proc.poll() is None \
                and _time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        fenced_exit = proc.poll() if proc is not None else None
    if ep.kind == "kill_router":
        cluster.respawn_router()
    else:
        cluster.spawn_replica(rid)
    return {"kind": ep.kind, "victim": victim, "reply": got,
            "fenced_exit": fenced_exit, "rejoin_s": rejoin_s,
            "elapsed_s": round(_time.monotonic() - t0, 3)}


async def run_schedule(schedule: Sequence[ChaosEvent],
                       miners: Dict[str, "ChaosMiner"]) -> int:
    """Apply ``schedule`` on the event-loop clock; heal everything after.

    Returns the number of events applied. The ``finally`` block restores
    a fault-free network, releases every wedge, and restarts every dead
    miner, so callers can assert post-storm convergence unconditionally.
    """
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    applied = 0
    try:
        for ev in sorted(schedule, key=lambda e: (e.at, e.action)):
            await asyncio.sleep(max(0.0, t0 + ev.at - loop.time()))
            logger.info("chaos: t+%.2fs %s %s %s", loop.time() - t0,
                        ev.action, ev.subject, ev.value or "")
            await _apply_event(ev, miners)
            applied += 1
    finally:
        faults.reset_all_faults()
        for m in miners.values():
            m.unwedge()
            m.go_honest()
        for m in miners.values():
            if not m.alive:
                await m.restart()
    return applied
