"""Global fault-injection knobs, keyed by endpoint side.

Mirrors the reference knob set (ref: lspnet/staff.go:20-116): four drop
percentages (client/server × read/write), a delay percentage (fixed 500 ms),
payload shortening/lengthening percentages, and a corruption flag. Knobs are
process-global and read on every packet, so tests can flip them mid-stream.
Plain attribute reads/writes are GIL-atomic, which is all the reference's
atomics bought it.
"""

from __future__ import annotations

import logging
import random

from ..utils.metrics import registry as _registry

log = logging.getLogger("lspnet")

# Hoisted metric handle (ISSUE 17 audit, same fix sniff got in PR 3):
# partition_conn sits on chaos-episode control paths that can fire per
# scheduled event; the name->handle lookup happens once at import, not
# per call.
_MET_PARTITIONS_OPENED = _registry().counter("net.partitions_opened")

DELAY_MILLIS = 500  # fixed injected delay, matches ref lspnet/conn.go:113


class _Knobs:
    client_read_drop = 0
    client_write_drop = 0
    server_read_drop = 0
    server_write_drop = 0
    shorten_percent = 0
    lengthen_percent = 0
    delay_percent = 0
    corrupted = False
    debug = False
    # One-sided partitions of single connections (no reference analog;
    # chaos plane). Conn ids are server-scoped, so both sets are applied
    # at SERVER endpoints only: ``partition_read`` drops every inbound
    # packet whose ConnID is in the set (the server goes deaf to that
    # peer), ``partition_write`` drops every outbound packet addressed to
    # it (the peer goes deaf to the server). Membership in exactly one
    # set is a one-sided partition: traffic flows the other way untouched.
    partition_read: frozenset = frozenset()
    partition_write: frozenset = frozenset()


knobs = _Knobs()


def set_client_read_drop_percent(p: int) -> None:
    if 0 <= p <= 100:
        knobs.client_read_drop = p


def set_client_write_drop_percent(p: int) -> None:
    if 0 <= p <= 100:
        knobs.client_write_drop = p


def set_server_read_drop_percent(p: int) -> None:
    if 0 <= p <= 100:
        knobs.server_read_drop = p


def set_server_write_drop_percent(p: int) -> None:
    if 0 <= p <= 100:
        knobs.server_write_drop = p


def set_read_drop_percent(p: int) -> None:
    set_client_read_drop_percent(p)
    set_server_read_drop_percent(p)


def set_write_drop_percent(p: int) -> None:
    set_client_write_drop_percent(p)
    set_server_write_drop_percent(p)


def set_msg_shortening_percent(p: int) -> None:
    if 0 <= p <= 100:
        knobs.shorten_percent = p


def set_msg_lengthening_percent(p: int) -> None:
    if 0 <= p <= 100:
        knobs.lengthen_percent = p


def set_delay_message_percent(p: int) -> None:
    if 0 <= p <= 100:
        knobs.delay_percent = p


def set_msg_corrupted(corrupted: bool) -> None:
    knobs.corrupted = corrupted


def reset_drop_percent() -> None:
    set_read_drop_percent(0)
    set_write_drop_percent(0)


def partition_conn(conn_id: int, *, inbound: bool = True,
                   outbound: bool = True) -> None:
    """Partition one connection at the server endpoint: ``inbound`` drops
    what the server would receive from it, ``outbound`` what the server
    would send to it. One flag = a one-sided partition (the LSP layer
    keeps heartbeating into the void, which is exactly the asymmetric
    failure the chaos suite wants)."""
    opened = False
    if inbound and conn_id not in knobs.partition_read:
        knobs.partition_read = knobs.partition_read | {conn_id}
        opened = True
    if outbound and conn_id not in knobs.partition_write:
        knobs.partition_write = knobs.partition_write | {conn_id}
        opened = True
    # Metrics plane: per-packet partition DROPS are counted in net.py;
    # this counts partition EPISODES — only when a direction actually
    # opens, so re-applying an existing partition doesn't make one long
    # partition read as flapping in a snapshot.
    if opened:
        _MET_PARTITIONS_OPENED.inc()


def heal_conn(conn_id: int, *, inbound: bool = True,
              outbound: bool = True) -> None:
    """Undo :func:`partition_conn`, per direction (defaults to both)."""
    if inbound:
        knobs.partition_read = knobs.partition_read - {conn_id}
    if outbound:
        knobs.partition_write = knobs.partition_write - {conn_id}


def heal_all_partitions() -> None:
    knobs.partition_read = frozenset()
    knobs.partition_write = frozenset()


def reset_all_faults() -> None:
    reset_drop_percent()
    knobs.shorten_percent = 0
    knobs.lengthen_percent = 0
    knobs.delay_percent = 0
    knobs.corrupted = False
    heal_all_partitions()


def enable_debug_logs(enable: bool) -> None:
    knobs.debug = enable


def sometimes(percentage: int) -> bool:
    # Early out at 0 (the steady-state value of every knob): the datapath
    # calls this three times per packet, and an RNG draw that can only
    # answer False is pure per-packet overhead (ISSUE 17). Identical
    # outcome distribution for every percentage.
    if percentage <= 0:
        return False
    return random.randrange(100) < percentage
