"""Simulated network: UDP endpoints with test-controlled fault injection.

The framework's equivalent of the reference ``lspnet`` package
(/root/reference/p1/src/github.com/cmu440/lspnet): every LSP endpoint sends
and receives through this layer, and tests inject faults — per-side read/write
drops, fixed 500 ms delays, payload shortening/lengthening, first-byte
corruption — plus a packet sniffer that counts sent/dropped Data and Ack
packets. All "multi-node" testing runs real localhost UDP through these knobs.
"""

from .faults import (
    set_read_drop_percent, set_write_drop_percent,
    set_client_read_drop_percent, set_client_write_drop_percent,
    set_server_read_drop_percent, set_server_write_drop_percent,
    set_msg_shortening_percent, set_msg_lengthening_percent,
    set_msg_corrupted, set_delay_message_percent,
    reset_drop_percent, reset_all_faults, enable_debug_logs,
    partition_conn, heal_conn, heal_all_partitions,
)
from .sniff import start_sniff, stop_sniff, SniffResult
from .net import (UDPEndpoint, listen_udp, dial_udp, join_host_port,
                  split_host_port)

__all__ = [
    "set_read_drop_percent", "set_write_drop_percent",
    "set_client_read_drop_percent", "set_client_write_drop_percent",
    "set_server_read_drop_percent", "set_server_write_drop_percent",
    "set_msg_shortening_percent", "set_msg_lengthening_percent",
    "set_msg_corrupted", "set_delay_message_percent",
    "reset_drop_percent", "reset_all_faults", "enable_debug_logs",
    "partition_conn", "heal_conn", "heal_all_partitions",
    "start_sniff", "stop_sniff", "SniffResult",
    "UDPEndpoint", "listen_udp", "dial_udp",
    "join_host_port", "split_host_port",
]
