"""Deterministic in-process transport over the REAL LSP core (ISSUE 17).

The deterministic-schedule explorer (``analysis/schedcheck``) needs a
transport with no sockets, no wall-clock timers, and no scheduling of
its own — every message delivery must be an event-loop step the
explorer's picker orders. Before the sans-io split this forced a SHIM:
plain queues impersonating the LSP surface, so the explorer never
touched the protocol code. Now each conn is a pair of
:class:`~..lsp.core.ConnCore` state machines — the byte-identical
engine ``_engine.py`` drives in production — pumped synchronously:

    chan.write(payload)
      └ client core .write  → wire frame in its outbox
          └ wire.decode + integrity_check      (the real parse path)
              └ server core .on_message → deliver → read_queue   + ack
                  └ wire.decode → client core .on_ack  (window slides)

The whole exchange runs inside the caller's synchronous ``write`` — one
explorer-visible step per app write, exactly like the old shim — but the
window law, reorder ring, ack discipline, and integrity check en route
are the production code, so dbmcheck explores the real protocol.
Determinism: the in-process link is lossless and ordered, so the pump
always drains (data → ack → done, no retransmit state left behind); the
cores get a zero clock (no RTT samples, no syscalls) and their epoch
timer is simply never driven — no timers means no retransmits, no
heartbeats, no loss detection, which is the explorer's trade.

Semantics preserved from the real stack (the scheduler depends on each):

- ``read()`` yields ``(conn_id, payload)`` in delivery order, and
  ``(conn_id, exc)`` exactly once when a peer's endpoint closes — the
  drop event ``Scheduler._on_drop`` consumes.
- ``read_nowait()`` (ISSUE 11) mirrors ``AsyncServer.read_nowait``:
  the next already-delivered item without an event-loop hop, or None —
  the scheduler's batched recv drain uses it.
- ``write(conn_id, ...)`` raises :class:`~..lsp.errors.ConnectionClosed`
  on a closed/unknown conn (``Scheduler._write`` catches ``LspError``).
- ``close_conn(conn_id)`` (the QoS shed path) kills the peer endpoint:
  its pending/later ``read()`` raises, like a dying LSP conn — and the
  server read stream gets NO drop event for a close it initiated
  (matching ``AsyncServer.close_conn``'s reaper, which removes the conn
  without posting one; the peer's own ``close()`` is what posts drops).

Scale notes (ISSUE 11): any number of DetServers can share one loop —
no module or loop-global state exists; conn ids are per-server (a
channel is bound to its server, so overlapping ids across servers are
fine), which is what the replica scenarios rely on. Every per-message
operation is O(1) per conn (ring slots, queue puts) — nothing scans
the conn table per delivery or per tick, so a 10k-conn storm costs
10k× one message, not 10k× the table. The ``writes``/``_read_log``
capture lists the scenario FIFO checks read are O(messages) MEMORY,
so the load harness constructs ``DetServer(record=False)`` to shed
them; scenarios keep the default recording.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple, Union

from ..lsp import wire
from ..lsp.core import ConnCore, integrity_check
from ..lsp.errors import ConnectionClosed
from ..lsp.params import Params

__all__ = ["DetServer", "DetChannel"]

ReadItem = Tuple[int, Union[bytes, Exception]]


def _zero_clock() -> float:
    return 0.0


class DetChannel:
    """One peer endpoint (a miner's or client's side of a conn), backed
    by its own :class:`ConnCore`.

    Duck-types the slice of ``AsyncClient`` the apps consume: async
    ``read()``, sync ``write(payload)``, async ``close()``.
    """

    def __init__(self, server: "DetServer", conn_id: int):
        self._server = server
        self.conn_id = conn_id
        self._inbox: asyncio.Queue = asyncio.Queue()
        self.closed = False
        #: Every payload this endpoint wrote, in order (scenario checks;
        #: empty when the owning server was built ``record=False``).
        self.sent: list = []
        #: The peer-side protocol state machine. Both cores of a pair
        #: start UP with the assigned conn id (the Connect handshake is
        #: the server demux's job in production, not the conn engine's).
        self.core = ConnCore(
            server._params, conn_id,
            deliver=self._inbox.put_nowait,
            clock=_zero_clock,
        )

    async def read(self) -> bytes:
        if self.closed and self._inbox.empty():
            raise ConnectionClosed(f"conn {self.conn_id} closed")
        item = await self._inbox.get()
        if isinstance(item, Exception):
            # Leave the poison pill for any later read.
            self._inbox.put_nowait(item)
            raise item
        return item

    def write(self, payload: bytes) -> None:
        if self.closed:
            raise ConnectionClosed(f"conn {self.conn_id} closed")
        if self._server._record:
            self.sent.append(payload)
        self.core.write(payload)
        self._server._pump(self.conn_id)

    async def close(self) -> None:
        """Peer-initiated close: the server side observes a drop."""
        if not self.closed:
            self._kill()
            self._server._on_peer_closed(self.conn_id)

    def _kill(self) -> None:
        self.closed = True
        self.core.abort()
        self._server._abort_server_core(self.conn_id)
        self._inbox.put_nowait(
            ConnectionClosed(f"conn {self.conn_id} closed"))


class DetServer:
    """Deterministic AsyncServer stand-in: same read/write/close_conn
    surface, each conn a live :class:`ConnCore` pair (see module doc).

    ``record=False`` drops the ``writes``/``_read_log``/``sent``
    capture (O(messages) memory the invariant checks consume) for the
    10k-conn load harness; delivery semantics are identical.
    """

    def __init__(self, record: bool = True) -> None:
        self._read_queue: asyncio.Queue = asyncio.Queue()
        self._chans: Dict[int, DetChannel] = {}
        self._cores: Dict[int, ConnCore] = {}
        self._next_conn_id = 1
        self._record = record
        self._params = Params()
        #: (conn_id, payload) of every server-side write, in order.
        self.writes: list = []
        #: (conn_id, payload) of every peer write, in DELIVERY order —
        #: the arrival sequence scenario FIFO checks compare against.
        self._read_log: list = []

    # ------------------------------------------------------------ wiring

    def connect(self) -> DetChannel:
        """A new peer conn (miner or client); returns its endpoint."""
        conn_id = self._next_conn_id
        self._next_conn_id += 1
        chan = DetChannel(self, conn_id)
        self._chans[conn_id] = chan
        self._cores[conn_id] = ConnCore(
            self._params, conn_id,
            deliver=lambda payload, cid=conn_id: self._deliver(cid, payload),
            clock=_zero_clock,
        )
        return chan

    def _pump(self, conn_id: int) -> None:
        """Exchange wire frames between the conn's two cores until both
        outboxes drain (lossless link: data → ack → done). Runs the real
        parse + integrity path on every frame."""
        chan_core = self._chans[conn_id].core
        server_core = self._cores[conn_id]
        progress = True
        while progress:
            progress = False
            for src, dst in ((chan_core, server_core),
                             (server_core, chan_core)):
                outbox = src.outbox
                if not outbox:
                    continue
                progress = True
                frames = outbox[:]
                outbox.clear()
                for raw in frames:
                    msg = wire.decode(raw)
                    if integrity_check(msg):
                        dst.on_message(msg)

    def _deliver(self, conn_id: int, payload: bytes) -> None:
        if self._record:
            self._read_log.append((conn_id, payload))
        self._read_queue.put_nowait((conn_id, payload))

    def _on_peer_closed(self, conn_id: int) -> None:
        if conn_id in self._chans:
            self._read_queue.put_nowait(
                (conn_id, ConnectionClosed(f"conn {conn_id} dropped")))

    def _abort_server_core(self, conn_id: int) -> None:
        core = self._cores.get(conn_id)
        if core is not None:
            core.abort()

    # ------------------------------------------- AsyncServer surface

    async def read(self) -> ReadItem:
        return await self._read_queue.get()

    def read_nowait(self) -> Optional[ReadItem]:
        """The next already-delivered item, or None — no loop hop.
        Mirrors ``AsyncServer.read_nowait`` for the scheduler's batched
        recv drain (ISSUE 11)."""
        try:
            return self._read_queue.get_nowait()
        except asyncio.QueueEmpty:
            return None

    def write(self, conn_id: int, payload: bytes) -> None:
        chan = self._chans.get(conn_id)
        if chan is None or chan.closed:
            raise ConnectionClosed(
                f"conn {conn_id} does not exist or is closed")
        if self._record:
            self.writes.append((conn_id, payload))
        self._cores[conn_id].write(payload)
        self._pump(conn_id)

    def close_conn(self, conn_id: int) -> None:
        chan = self._chans.get(conn_id)
        if chan is None:
            raise ConnectionClosed(f"conn {conn_id} does not exist")
        if not chan.closed:
            chan._kill()

    def sent_to(self, conn_id: int) -> list:
        """Payloads written to one conn, in order (scenario checks;
        O(total writes) — a capture reader, never a hot path)."""
        return [p for c, p in self.writes if c == conn_id]
