"""Deterministic in-process transport shim (dbmcheck, ISSUE 8).

The real stack — UDP endpoints, the LSP sliding-window engine, its
epoch timers — is what the conformance and chaos suites exercise. The
deterministic-schedule explorer (``analysis/schedcheck``) needs the
OPPOSITE trade: no sockets, no retransmission state, no timers of its
own, just the scheduler-visible surface of :class:`..lsp.server.
AsyncServer` and :class:`..lsp.client.AsyncClient` over plain asyncio
queues — so every message delivery is an event-loop step the explorer's
picker orders, and the only state machines under test are the CONTROL
PLANE's (scheduler, QoS, miner pipeline), not the transport's.

Semantics preserved from the real stack (the scheduler depends on each):

- ``read()`` yields ``(conn_id, payload)`` in delivery order, and
  ``(conn_id, exc)`` exactly once when a peer's endpoint closes — the
  drop event ``Scheduler._on_drop`` consumes.
- ``read_nowait()`` (ISSUE 11) mirrors ``AsyncServer.read_nowait``:
  the next already-delivered item without an event-loop hop, or None —
  the scheduler's batched recv drain uses it.
- ``write(conn_id, ...)`` raises :class:`~..lsp.errors.ConnectionClosed`
  on a closed/unknown conn (``Scheduler._write`` catches ``LspError``).
- ``close_conn(conn_id)`` (the QoS shed path) kills the peer endpoint:
  its pending/later ``read()`` raises, like a dying LSP conn — and the
  server read stream gets NO drop event for a close it initiated
  (matching ``AsyncServer.close_conn``'s reaper, which removes the conn
  without posting one; the peer's own ``close()`` is what posts drops).

Scale notes (ISSUE 11): any number of DetServers can share one loop —
no module or loop-global state exists; conn ids are per-server (a
channel is bound to its server, so overlapping ids across servers are
fine), which is what the replica scenarios rely on. Every per-message
operation is O(1) per conn (dict lookups, queue puts) — nothing scans
the conn table per delivery or per tick, so a 10k-conn storm costs
10k× one message, not 10k× the table. The ``writes``/``_read_log``
capture lists the scenario FIFO checks read are O(messages) MEMORY,
so the load harness constructs ``DetServer(record=False)`` to shed
them; scenarios keep the default recording.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple, Union

from ..lsp.errors import ConnectionClosed

__all__ = ["DetServer", "DetChannel"]

ReadItem = Tuple[int, Union[bytes, Exception]]


class DetChannel:
    """One peer endpoint (a miner's or client's side of a conn).

    Duck-types the slice of ``AsyncClient`` the apps consume: async
    ``read()``, sync ``write(payload)``, async ``close()``.
    """

    def __init__(self, server: "DetServer", conn_id: int):
        self._server = server
        self.conn_id = conn_id
        self._inbox: asyncio.Queue = asyncio.Queue()
        self.closed = False
        #: Every payload this endpoint wrote, in order (scenario checks;
        #: empty when the owning server was built ``record=False``).
        self.sent: list = []

    async def read(self) -> bytes:
        if self.closed and self._inbox.empty():
            raise ConnectionClosed(f"conn {self.conn_id} closed")
        item = await self._inbox.get()
        if isinstance(item, Exception):
            # Leave the poison pill for any later read.
            self._inbox.put_nowait(item)
            raise item
        return item

    def write(self, payload: bytes) -> None:
        if self.closed:
            raise ConnectionClosed(f"conn {self.conn_id} closed")
        if self._server._record:
            self.sent.append(payload)
        self._server._deliver(self.conn_id, payload)

    async def close(self) -> None:
        """Peer-initiated close: the server side observes a drop."""
        if not self.closed:
            self._kill()
            self._server._on_peer_closed(self.conn_id)

    def _kill(self) -> None:
        self.closed = True
        self._inbox.put_nowait(
            ConnectionClosed(f"conn {self.conn_id} closed"))


class DetServer:
    """Deterministic AsyncServer stand-in: same read/write/close_conn
    surface, backed by per-conn :class:`DetChannel` endpoints.

    ``record=False`` drops the ``writes``/``_read_log``/``sent``
    capture (O(messages) memory the invariant checks consume) for the
    10k-conn load harness; delivery semantics are identical.
    """

    def __init__(self, record: bool = True) -> None:
        self._read_queue: asyncio.Queue = asyncio.Queue()
        self._chans: Dict[int, DetChannel] = {}
        self._next_conn_id = 1
        self._record = record
        #: (conn_id, payload) of every server-side write, in order.
        self.writes: list = []
        #: (conn_id, payload) of every peer write, in DELIVERY order —
        #: the arrival sequence scenario FIFO checks compare against.
        self._read_log: list = []

    # ------------------------------------------------------------ wiring

    def connect(self) -> DetChannel:
        """A new peer conn (miner or client); returns its endpoint."""
        chan = DetChannel(self, self._next_conn_id)
        self._chans[chan.conn_id] = chan
        self._next_conn_id += 1
        return chan

    def _deliver(self, conn_id: int, payload: bytes) -> None:
        if self._record:
            self._read_log.append((conn_id, payload))
        self._read_queue.put_nowait((conn_id, payload))

    def _on_peer_closed(self, conn_id: int) -> None:
        if conn_id in self._chans:
            self._read_queue.put_nowait(
                (conn_id, ConnectionClosed(f"conn {conn_id} dropped")))

    # ------------------------------------------- AsyncServer surface

    async def read(self) -> ReadItem:
        return await self._read_queue.get()

    def read_nowait(self) -> Optional[ReadItem]:
        """The next already-delivered item, or None — no loop hop.
        Mirrors ``AsyncServer.read_nowait`` for the scheduler's batched
        recv drain (ISSUE 11)."""
        try:
            return self._read_queue.get_nowait()
        except asyncio.QueueEmpty:
            return None

    def write(self, conn_id: int, payload: bytes) -> None:
        chan = self._chans.get(conn_id)
        if chan is None or chan.closed:
            raise ConnectionClosed(
                f"conn {conn_id} does not exist or is closed")
        if self._record:
            self.writes.append((conn_id, payload))
        chan._inbox.put_nowait(payload)

    def close_conn(self, conn_id: int) -> None:
        chan = self._chans.get(conn_id)
        if chan is None:
            raise ConnectionClosed(f"conn {conn_id} does not exist")
        if not chan.closed:
            chan._kill()

    def sent_to(self, conn_id: int) -> list:
        """Payloads written to one conn, in order (scenario checks;
        O(total writes) — a capture reader, never a hot path)."""
        return [p for c, p in self.writes if c == conn_id]
