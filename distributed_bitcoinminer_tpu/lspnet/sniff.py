"""Packet sniffer: counts sent/dropped Data and Ack packets while enabled.

The backoff tests grade retransmission timing by counting packets on the wire
(ref: lspnet/sniff.go:9-60, used by lsp2_test.go TestExpBackOff).
"""

from __future__ import annotations

from dataclasses import dataclass

_TYPE_DATA = 1
_TYPE_ACK = 2


@dataclass
class SniffResult:
    num_sent_acks: int = 0
    num_dropped_acks: int = 0
    num_sent_data: int = 0
    num_dropped_data: int = 0


_sniffing = False
_result = SniffResult()


def start_sniff() -> None:
    global _sniffing, _result
    _result = SniffResult()
    _sniffing = True


def stop_sniff() -> SniffResult:
    global _sniffing
    _sniffing = False
    return _result


def is_sniffing() -> bool:
    return _sniffing


def record(msg_type: int, sent: bool) -> None:
    if msg_type == _TYPE_DATA:
        if sent:
            _result.num_sent_data += 1
        else:
            _result.num_dropped_data += 1
    elif msg_type == _TYPE_ACK:
        if sent:
            _result.num_sent_acks += 1
        else:
            _result.num_dropped_acks += 1
