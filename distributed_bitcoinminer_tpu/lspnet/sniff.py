"""Packet sniffer: counts sent/dropped Data and Ack packets while enabled.

The backoff tests grade retransmission timing by counting packets on the wire
(ref: lspnet/sniff.go:9-60, used by lsp2_test.go TestExpBackOff).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.metrics import registry as _registry

_TYPE_DATA = 1
_TYPE_ACK = 2

# Registry mirror of the sniff counters, handles hoisted to module scope:
# record() runs per packet while a sniff window is open (the
# timing-sensitive backoff tests), so per-call registry/label lookups are
# the one avoidable cost (same rule as lspnet/net.py).
_M = _registry()
_MET_SNIFFED = {
    (_TYPE_DATA, True): _M.counter("net.sniffed", type="data",
                                   outcome="sent"),
    (_TYPE_DATA, False): _M.counter("net.sniffed", type="data",
                                    outcome="dropped"),
    (_TYPE_ACK, True): _M.counter("net.sniffed", type="ack",
                                  outcome="sent"),
    (_TYPE_ACK, False): _M.counter("net.sniffed", type="ack",
                                   outcome="dropped"),
}


@dataclass
class SniffResult:
    num_sent_acks: int = 0
    num_dropped_acks: int = 0
    num_sent_data: int = 0
    num_dropped_data: int = 0


_sniffing = False
_result = SniffResult()


def start_sniff() -> None:
    global _sniffing, _result
    _result = SniffResult()
    _sniffing = True


def stop_sniff() -> SniffResult:
    global _sniffing
    _sniffing = False
    return _result


def is_sniffing() -> bool:
    return _sniffing


def record(msg_type: int, sent: bool) -> None:
    # The sniff counters below are the graded backoff-test contract and
    # stay exactly as they were; the registry mirror makes the same counts
    # visible in a metrics snapshot while a sniff window is open.
    if msg_type == _TYPE_DATA:
        if sent:
            _result.num_sent_data += 1
        else:
            _result.num_dropped_data += 1
        _MET_SNIFFED[(msg_type, sent)].inc()
    elif msg_type == _TYPE_ACK:
        if sent:
            _result.num_sent_acks += 1
        else:
            _result.num_dropped_acks += 1
        _MET_SNIFFED[(msg_type, sent)].inc()
