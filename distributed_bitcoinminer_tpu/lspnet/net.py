"""Asyncio UDP endpoints with fault injection on the send and receive paths.

Equivalent of the reference's ``UDPConn`` wrapper (ref: lspnet/conn.go,
lspnet/net.go): endpoints opened with :func:`listen_udp` are the "server"
side and those opened with :func:`dial_udp` are the "client" side, which
selects which drop knobs apply. Fault behavior matches the reference:

- read drop: inbound datagram silently discarded before the protocol sees it;
- write drop: outbound datagram discarded but reported as sent;
- delay: outbound datagram delivered 500 ms late;
- shorten/lengthen/corrupt: applied to Data messages only, mutating the
  payload while leaving Size/Checksum stale so the receiver's integrity gate
  must catch it;
- sniffer: counts sent/dropped Data/Ack packets at write time.

Batched syscalls (ISSUE 17): with ``DBM_MMSG`` on (the default) and
``recvmmsg``/``sendmmsg`` present, :func:`listen_udp`/:func:`dial_udp`
return a :class:`MmsgEndpoint` — a raw nonblocking socket on the loop's
readable callback instead of an asyncio datagram transport. One readable
callback is ONE ``recvmmsg`` of up to ``DBM_MMSG_BATCH`` datagrams;
outbound frames queue and flush in ONE ``sendmmsg`` per loop iteration
(``call_soon`` runs the flush after the pump that produced the burst —
the "flush at pump-exit" point, like the engine's ``DBM_RECV_BATCH``
drain). The fault pipeline is shared code either way: both endpoints
funnel inbound datagrams through :meth:`UDPEndpoint._ingress` and
outbound through the same ``send -> _send_now`` chain, so drop/delay/
mutate/sniff semantics are byte-identical. Fallback is graceful and
per-endpoint: non-Linux, missing libc symbols, or a non-IPv4 address
just uses the stock transport (``net.syscalls`` then counts one per
datagram, which is what it truly costs).
"""

from __future__ import annotations

import asyncio
import base64
import json
import socket as _socket

from .faults import DELAY_MILLIS, knobs, log, sometimes
from . import sniff
from ..lsp import _mmsg
from ..utils._env import int_env as _int_env
from ..utils.metrics import registry as _registry

# Transport fault metrics (utils/metrics.py), module-scope handles for the
# per-packet paths. These RIDE ALONGSIDE the sniff counters — the sniffer's
# start/stop/result contract (graded by the backoff tests) is untouched.
_M = _registry()
_MET_DROPS = {
    (True, "read"): _M.counter("net.drops", point="server_read"),
    (False, "read"): _M.counter("net.drops", point="client_read"),
    (True, "write"): _M.counter("net.drops", point="server_write"),
    (False, "write"): _M.counter("net.drops", point="client_write"),
}
_MET_PARTITION = {"read": _M.counter("net.partition_drops", dir="read"),
                  "write": _M.counter("net.partition_drops", dir="write")}
_MET_DELAYS = _M.counter("net.delays")
# Syscall economics (ISSUE 17): syscalls and datagrams per direction, so
# syscalls/msg is computable from counters alone (the bench probe's
# contract). The stock path truly is 1:1; the mmsg path counts one
# syscall per recvmmsg/sendmmsg burst.
_MET_SYSCALLS = {"recv": _M.counter("net.syscalls", dir="recv"),
                 "send": _M.counter("net.syscalls", dir="send")}
_MET_DATAGRAMS = {"recv": _M.counter("net.datagrams", dir="recv"),
                  "send": _M.counter("net.datagrams", dir="send")}
_MET_BYTES = {"recv": _M.counter("net.bytes", dir="recv"),
              "send": _M.counter("net.bytes", dir="send")}


def join_host_port(host: str, port: str | int) -> str:
    """Go ``net.JoinHostPort`` semantics (ref: lspnet/net.go:81-84): a
    host containing a colon (IPv6 literal) is bracketed."""
    if ":" in host:
        return f"[{host}]:{port}"
    return f"{host}:{port}"


def split_host_port(hostport: str) -> tuple[str, str]:
    """Go ``net.SplitHostPort`` semantics (ref: lspnet/net.go:86-89):
    ``host:port`` / ``[ipv6]:port`` -> (host, port); malformed input
    raises ValueError with Go's diagnostic phrasing. An empty host is
    allowed (``:6060`` means all interfaces / localhost by context),
    exactly as in Go.
    """
    if hostport.startswith("["):
        end = hostport.find("]")
        if end < 0:
            raise ValueError(f"address {hostport}: missing ']' in address")
        host = hostport[1:end]
        rest = hostport[end + 1:]
        if not rest.startswith(":"):
            raise ValueError(f"address {hostport}: missing port in address")
        port = rest[1:]
        if ":" in port:
            raise ValueError(
                f"address {hostport}: too many colons in address")
    else:
        host, sep, port = hostport.partition(":")
        if not sep:
            raise ValueError(f"address {hostport}: missing port in address")
        if ":" in host or ":" in port:
            raise ValueError(
                f"address {hostport}: too many colons in address")
    for ch, msg in (("[", "unexpected '[' in address"),
                    ("]", "unexpected ']' in address")):
        if ch in host or ch in port:
            raise ValueError(f"address {hostport}: {msg}")
    return host, port


def _mutate_data_packet(data: bytes, obj: dict) -> bytes:
    """Apply shorten/lengthen/corrupt to a Data message (ref: lspnet/conn.go:143-175).

    ``obj`` is the already-parsed JSON of ``data`` (parsed once by the caller).
    """
    shorten = sometimes(knobs.shorten_percent)
    lengthen = sometimes(knobs.lengthen_percent)
    corrupt = knobs.corrupted
    if not (shorten or lengthen or corrupt):
        return data
    try:
        payload = bytearray(base64.b64decode(obj["Payload"]) if obj.get("Payload") else b"")
    except Exception:  # noqa: BLE001 — non-LSP traffic passes through untouched
        return data
    if shorten:
        payload = payload[: len(payload) // 2]
    elif lengthen:
        payload += bytes([2, 3, 4])
    elif corrupt:
        if len(payload) == 0:
            payload = bytearray([0xFF])
        else:
            payload[0] = payload[0] ^ 0xFF
    obj["Payload"] = base64.b64encode(bytes(payload)).decode("ascii")
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def _parse_packet(data: bytes) -> tuple[int, dict | None]:
    try:
        obj = json.loads(data)
        return int(obj.get("Type", -1)), obj
    except Exception:  # noqa: BLE001
        return -1, None


def _packet_conn_id(data: bytes) -> int | None:
    """ConnID of an LSP packet, or None for non-LSP traffic."""
    obj = _parse_packet(data)[1]
    try:
        return int(obj["ConnID"]) if obj is not None else None
    except (KeyError, TypeError, ValueError):
        return None


class _Protocol(asyncio.DatagramProtocol):
    """Binds to its UDPEndpoint after construction (the endpoint wraps the
    transport, which only exists once the protocol has been created)."""

    def __init__(self):
        self._ep: UDPEndpoint | None = None
        self._pending: list[tuple[bytes, tuple]] = []
        self._lost = False

    def bind(self, ep: "UDPEndpoint") -> None:
        self._ep = ep
        for data, addr in self._pending:
            ep._ingress(data, addr)
        self._pending.clear()
        if self._lost:
            ep._recv_queue.put_nowait(None)

    def datagram_received(self, data: bytes, addr) -> None:
        # Stock path: asyncio made one recvfrom syscall for this datagram.
        _MET_SYSCALLS["recv"].inc()
        if self._ep is None:
            self._pending.append((data, addr))
        else:
            self._ep._ingress(data, addr)

    def connection_lost(self, exc) -> None:
        if self._ep is None:
            self._lost = True
        else:
            self._ep._recv_queue.put_nowait(None)


class UDPEndpoint:
    """One UDP socket with fault injection. Not thread-safe; owned by one loop."""

    def __init__(self, transport: asyncio.DatagramTransport | None,
                 is_server: bool):
        self._transport = transport
        self.is_server = is_server
        self._recv_queue: asyncio.Queue = asyncio.Queue()
        self._closed = False
        self._delay_tasks: set[asyncio.Task] = set()

    @property
    def sockname(self):
        return self._transport.get_extra_info("sockname")

    def _ingress(self, data: bytes, addr) -> None:
        """Read-side fault pipeline, shared by the stock protocol callback
        and the mmsg readable callback (ref: lspnet/conn.go read faults)."""
        _MET_DATAGRAMS["recv"].inc()
        _MET_BYTES["recv"].inc(len(data))
        if self.is_server and knobs.partition_read and \
                _packet_conn_id(data) in knobs.partition_read:
            if knobs.debug:
                log.info("PARTITION dropping read packet of length %d",
                         len(data))
            _MET_PARTITION["read"].inc()
            return
        drop = knobs.server_read_drop if self.is_server else knobs.client_read_drop
        if sometimes(drop):
            if knobs.debug:
                log.info("DROPPING read packet of length %d", len(data))
            _MET_DROPS[(self.is_server, "read")].inc()
            return
        self._recv_queue.put_nowait((data, addr))

    async def recv(self) -> tuple[bytes, tuple] | None:
        """Next surviving inbound datagram, or None once the socket is closed."""
        if self._closed and self._recv_queue.empty():
            return None
        item = await self._recv_queue.get()
        return item

    def recv_nowait(self) -> tuple[bytes, tuple] | None:
        """An already-queued inbound datagram without awaiting, or None.

        The burst-drain idiom (ISSUE 17): one ``recvmmsg`` enqueues up to
        a whole batch at once, so the engines' receive loops pay ONE
        awaited ``recv()`` (a loop round-trip) per burst and drain the
        rest synchronously. The closed sentinel is left in place for the
        next awaited ``recv()`` to consume — popping it here would eat
        the only close notification."""
        try:
            item = self._recv_queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if item is None:
            self._recv_queue.put_nowait(None)
            return None
        return item

    def send(self, data: bytes, addr=None) -> None:
        """Send one datagram through the fault pipeline (ref: lspnet/conn.go:104-190)."""
        if self._closed:
            return
        if sometimes(knobs.delay_percent):
            if knobs.debug:
                log.info("DELAYING written packet of length %d", len(data))
            _MET_DELAYS.inc()
            task = asyncio.get_running_loop().create_task(self._send_later(data, addr))
            self._delay_tasks.add(task)
            task.add_done_callback(self._delay_tasks.discard)
            return
        self._send_now(data, addr)

    async def _send_later(self, data: bytes, addr) -> None:
        await asyncio.sleep(DELAY_MILLIS / 1000.0)
        if not self._closed:
            self._send_now(data, addr)

    def _send_now(self, data: bytes, addr) -> None:
        if self.is_server and knobs.partition_write and \
                _packet_conn_id(data) in knobs.partition_write:
            if knobs.debug:
                log.info("PARTITION dropping written packet of length %d",
                         len(data))
            _MET_PARTITION["write"].inc()
            return
        # Only pay the JSON parse when a knob or the sniffer needs the type.
        inspect = (sniff.is_sniffing() or knobs.shorten_percent
                   or knobs.lengthen_percent or knobs.corrupted)
        mtype, obj = _parse_packet(data) if inspect else (-1, None)
        drop = knobs.server_write_drop if self.is_server else knobs.client_write_drop
        if sometimes(drop):
            if knobs.debug:
                log.info("DROPPING written packet of length %d", len(data))
            _MET_DROPS[(self.is_server, "write")].inc()
            if sniff.is_sniffing():
                sniff.record(mtype, sent=False)
            return
        if sniff.is_sniffing():
            sniff.record(mtype, sent=True)
        if inspect and mtype == 1 and obj is not None:
            data = _mutate_data_packet(data, obj)
        self._raw_send(data, addr)

    def _raw_send(self, data: bytes, addr) -> None:
        """Post-fault-pipeline transmission: stock = one sendto syscall."""
        _MET_SYSCALLS["send"].inc()
        _MET_DATAGRAMS["send"].inc()
        _MET_BYTES["send"].inc(len(data))
        self._transport.sendto(data, addr)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for task in list(self._delay_tasks):
            task.cancel()
        self._transport.close()


class MmsgEndpoint(UDPEndpoint):
    """The batched-syscall endpoint (ISSUE 17): a raw nonblocking UDP
    socket driven by ``loop.add_reader``, recv and send both one syscall
    per burst via :mod:`..lsp._mmsg`. Same fault pipeline, same
    ``recv()``/``send()`` surface as the stock endpoint."""

    def __init__(self, sock: _socket.socket, is_server: bool, batch: int):
        super().__init__(None, is_server)
        self._sock = sock
        self._mm = _mmsg.MmsgSocket(sock.fileno(), batch)
        self._batch = batch
        self._loop = asyncio.get_running_loop()
        self._send_pending: list[tuple[bytes, tuple | None]] = []
        self._flush_scheduled = False
        self._writer_armed = False
        # Cached: the stock transport answers sockname after close too
        # (the fenced-replica exit path reads .port post-shutdown).
        self._sockname = sock.getsockname()
        self._loop.add_reader(sock.fileno(), self._on_readable)

    @property
    def sockname(self):
        return self._sockname

    def _on_readable(self) -> None:
        # One recvmmsg per readable callback. More queued than one batch
        # holds? The level-triggered selector re-fires the callback, each
        # firing one syscall — the burst size IS the amortization.
        if self._closed:
            return
        try:
            got = self._mm.recv_burst()
        except OSError:
            # e.g. ECONNREFUSED surfaced by ICMP on a connected socket
            # after peer death — the stock path routes this to
            # error_received and drops it; so do we.
            return
        if not got:
            return
        _MET_SYSCALLS["recv"].inc()
        for data, addr in got:
            self._ingress(data, addr)

    def _raw_send(self, data: bytes, addr) -> None:
        # Queue, and flush ONCE per loop iteration: call_soon runs after
        # the currently-draining pump, so every frame the pump produced
        # (acks for a whole recv burst, a window's worth of data) goes
        # out in one sendmmsg.
        self._send_pending.append((data, addr))
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_send)

    def _flush_send(self) -> None:
        self._flush_scheduled = False
        if self._closed:
            self._send_pending.clear()
            return
        pending = self._send_pending
        while pending:
            try:
                sent = self._mm.send_burst(pending)
            except BlockingIOError:
                # Kernel send buffer full: resume when writable.
                _MET_SYSCALLS["send"].inc()
                self._arm_writer()
                return
            except OSError:
                # Async ICMP error (dead peer) charged to the head
                # datagram; drop it like error_received and move on.
                _MET_SYSCALLS["send"].inc()
                del pending[:1]
                continue
            _MET_SYSCALLS["send"].inc()
            _MET_DATAGRAMS["send"].inc(sent)
            _MET_BYTES["send"].inc(sum(len(d) for d, _ in pending[:sent]))
            del pending[:sent]

    def _arm_writer(self) -> None:
        if not self._writer_armed:
            self._writer_armed = True
            self._loop.add_writer(self._sock.fileno(), self._on_writable)

    def _on_writable(self) -> None:
        self._loop.remove_writer(self._sock.fileno())
        self._writer_armed = False
        self._flush_send()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for task in list(self._delay_tasks):
            task.cancel()
        fd = self._sock.fileno()
        if fd >= 0:
            self._loop.remove_reader(fd)
            if self._writer_armed:
                self._loop.remove_writer(fd)
        self._send_pending.clear()
        self._sock.close()
        # The stock path posts this sentinel from connection_lost.
        self._recv_queue.put_nowait(None)


def _try_mmsg_endpoint(local: tuple | None, remote: tuple | None,
                       is_server: bool) -> MmsgEndpoint | None:
    """A batched endpoint when the knob, platform, and address allow;
    None means the caller takes the stock transport."""
    if _int_env("DBM_MMSG", 1) == 0 or not _mmsg.available():
        return None
    sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
    try:
        sock.setblocking(False)
        if local is not None:
            sock.bind(local)
        if remote is not None:
            sock.connect(remote)
        batch = max(1, _int_env("DBM_MMSG_BATCH", 32))
        return MmsgEndpoint(sock, is_server, batch)
    except OSError:
        sock.close()
        return None


async def listen_udp(host: str = "127.0.0.1", port: int = 0) -> UDPEndpoint:
    """Open a server-side endpoint (ref: lspnet/net.go ListenUDP)."""
    ep = _try_mmsg_endpoint((host, port), None, is_server=True)
    if ep is not None:
        return ep
    loop = asyncio.get_running_loop()
    transport, protocol = await loop.create_datagram_endpoint(
        _Protocol, local_addr=(host, port))
    ep = UDPEndpoint(transport, is_server=True)
    protocol.bind(ep)
    return ep


async def dial_udp(host: str, port: int) -> UDPEndpoint:
    """Open a client-side endpoint connected to (host, port) (ref: lspnet/net.go DialUDP)."""
    ep = _try_mmsg_endpoint(None, (host, port), is_server=False)
    if ep is not None:
        return ep
    loop = asyncio.get_running_loop()
    transport, protocol = await loop.create_datagram_endpoint(
        _Protocol, remote_addr=(host, port))
    ep = UDPEndpoint(transport, is_server=False)
    protocol.bind(ep)
    return ep
