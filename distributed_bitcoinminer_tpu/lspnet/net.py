"""Asyncio UDP endpoints with fault injection on the send and receive paths.

Equivalent of the reference's ``UDPConn`` wrapper (ref: lspnet/conn.go,
lspnet/net.go): endpoints opened with :func:`listen_udp` are the "server"
side and those opened with :func:`dial_udp` are the "client" side, which
selects which drop knobs apply. Fault behavior matches the reference:

- read drop: inbound datagram silently discarded before the protocol sees it;
- write drop: outbound datagram discarded but reported as sent;
- delay: outbound datagram delivered 500 ms late;
- shorten/lengthen/corrupt: applied to Data messages only, mutating the
  payload while leaving Size/Checksum stale so the receiver's integrity gate
  must catch it;
- sniffer: counts sent/dropped Data/Ack packets at write time.
"""

from __future__ import annotations

import asyncio
import base64
import json

from .faults import DELAY_MILLIS, knobs, log, sometimes
from . import sniff
from ..utils.metrics import registry as _registry

# Transport fault metrics (utils/metrics.py), module-scope handles for the
# per-packet paths. These RIDE ALONGSIDE the sniff counters — the sniffer's
# start/stop/result contract (graded by the backoff tests) is untouched.
_M = _registry()
_MET_DROPS = {
    (True, "read"): _M.counter("net.drops", point="server_read"),
    (False, "read"): _M.counter("net.drops", point="client_read"),
    (True, "write"): _M.counter("net.drops", point="server_write"),
    (False, "write"): _M.counter("net.drops", point="client_write"),
}
_MET_PARTITION = {"read": _M.counter("net.partition_drops", dir="read"),
                  "write": _M.counter("net.partition_drops", dir="write")}
_MET_DELAYS = _M.counter("net.delays")


def join_host_port(host: str, port: str | int) -> str:
    """Go ``net.JoinHostPort`` semantics (ref: lspnet/net.go:81-84): a
    host containing a colon (IPv6 literal) is bracketed."""
    if ":" in host:
        return f"[{host}]:{port}"
    return f"{host}:{port}"


def split_host_port(hostport: str) -> tuple[str, str]:
    """Go ``net.SplitHostPort`` semantics (ref: lspnet/net.go:86-89):
    ``host:port`` / ``[ipv6]:port`` -> (host, port); malformed input
    raises ValueError with Go's diagnostic phrasing. An empty host is
    allowed (``:6060`` means all interfaces / localhost by context),
    exactly as in Go.
    """
    if hostport.startswith("["):
        end = hostport.find("]")
        if end < 0:
            raise ValueError(f"address {hostport}: missing ']' in address")
        host = hostport[1:end]
        rest = hostport[end + 1:]
        if not rest.startswith(":"):
            raise ValueError(f"address {hostport}: missing port in address")
        port = rest[1:]
        if ":" in port:
            raise ValueError(
                f"address {hostport}: too many colons in address")
    else:
        host, sep, port = hostport.partition(":")
        if not sep:
            raise ValueError(f"address {hostport}: missing port in address")
        if ":" in host or ":" in port:
            raise ValueError(
                f"address {hostport}: too many colons in address")
    for ch, msg in (("[", "unexpected '[' in address"),
                    ("]", "unexpected ']' in address")):
        if ch in host or ch in port:
            raise ValueError(f"address {hostport}: {msg}")
    return host, port


def _mutate_data_packet(data: bytes, obj: dict) -> bytes:
    """Apply shorten/lengthen/corrupt to a Data message (ref: lspnet/conn.go:143-175).

    ``obj`` is the already-parsed JSON of ``data`` (parsed once by the caller).
    """
    shorten = sometimes(knobs.shorten_percent)
    lengthen = sometimes(knobs.lengthen_percent)
    corrupt = knobs.corrupted
    if not (shorten or lengthen or corrupt):
        return data
    try:
        payload = bytearray(base64.b64decode(obj["Payload"]) if obj.get("Payload") else b"")
    except Exception:  # noqa: BLE001 — non-LSP traffic passes through untouched
        return data
    if shorten:
        payload = payload[: len(payload) // 2]
    elif lengthen:
        payload += bytes([2, 3, 4])
    elif corrupt:
        if len(payload) == 0:
            payload = bytearray([0xFF])
        else:
            payload[0] = payload[0] ^ 0xFF
    obj["Payload"] = base64.b64encode(bytes(payload)).decode("ascii")
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def _parse_packet(data: bytes) -> tuple[int, dict | None]:
    try:
        obj = json.loads(data)
        return int(obj.get("Type", -1)), obj
    except Exception:  # noqa: BLE001
        return -1, None


def _packet_conn_id(data: bytes) -> int | None:
    """ConnID of an LSP packet, or None for non-LSP traffic."""
    obj = _parse_packet(data)[1]
    try:
        return int(obj["ConnID"]) if obj is not None else None
    except (KeyError, TypeError, ValueError):
        return None


class _Protocol(asyncio.DatagramProtocol):
    """Binds to its UDPEndpoint after construction (the endpoint wraps the
    transport, which only exists once the protocol has been created)."""

    def __init__(self):
        self._ep: UDPEndpoint | None = None
        self._pending: list[tuple[bytes, tuple]] = []
        self._lost = False

    def bind(self, ep: "UDPEndpoint") -> None:
        self._ep = ep
        for data, addr in self._pending:
            self._deliver(data, addr)
        self._pending.clear()
        if self._lost:
            ep._recv_queue.put_nowait(None)

    def _deliver(self, data: bytes, addr) -> None:
        ep = self._ep
        if ep.is_server and knobs.partition_read and \
                _packet_conn_id(data) in knobs.partition_read:
            if knobs.debug:
                log.info("PARTITION dropping read packet of length %d",
                         len(data))
            _MET_PARTITION["read"].inc()
            return
        drop = knobs.server_read_drop if ep.is_server else knobs.client_read_drop
        if sometimes(drop):
            if knobs.debug:
                log.info("DROPPING read packet of length %d", len(data))
            _MET_DROPS[(ep.is_server, "read")].inc()
            return
        ep._recv_queue.put_nowait((data, addr))

    def datagram_received(self, data: bytes, addr) -> None:
        if self._ep is None:
            self._pending.append((data, addr))
        else:
            self._deliver(data, addr)

    def connection_lost(self, exc) -> None:
        if self._ep is None:
            self._lost = True
        else:
            self._ep._recv_queue.put_nowait(None)


class UDPEndpoint:
    """One UDP socket with fault injection. Not thread-safe; owned by one loop."""

    def __init__(self, transport: asyncio.DatagramTransport, is_server: bool):
        self._transport = transport
        self.is_server = is_server
        self._recv_queue: asyncio.Queue = asyncio.Queue()
        self._closed = False
        self._delay_tasks: set[asyncio.Task] = set()

    @property
    def sockname(self):
        return self._transport.get_extra_info("sockname")

    async def recv(self) -> tuple[bytes, tuple] | None:
        """Next surviving inbound datagram, or None once the socket is closed."""
        if self._closed and self._recv_queue.empty():
            return None
        item = await self._recv_queue.get()
        return item

    def send(self, data: bytes, addr=None) -> None:
        """Send one datagram through the fault pipeline (ref: lspnet/conn.go:104-190)."""
        if self._closed:
            return
        if sometimes(knobs.delay_percent):
            if knobs.debug:
                log.info("DELAYING written packet of length %d", len(data))
            _MET_DELAYS.inc()
            task = asyncio.get_running_loop().create_task(self._send_later(data, addr))
            self._delay_tasks.add(task)
            task.add_done_callback(self._delay_tasks.discard)
            return
        self._send_now(data, addr)

    async def _send_later(self, data: bytes, addr) -> None:
        await asyncio.sleep(DELAY_MILLIS / 1000.0)
        if not self._closed:
            self._send_now(data, addr)

    def _send_now(self, data: bytes, addr) -> None:
        if self.is_server and knobs.partition_write and \
                _packet_conn_id(data) in knobs.partition_write:
            if knobs.debug:
                log.info("PARTITION dropping written packet of length %d",
                         len(data))
            _MET_PARTITION["write"].inc()
            return
        # Only pay the JSON parse when a knob or the sniffer needs the type.
        inspect = (sniff.is_sniffing() or knobs.shorten_percent
                   or knobs.lengthen_percent or knobs.corrupted)
        mtype, obj = _parse_packet(data) if inspect else (-1, None)
        drop = knobs.server_write_drop if self.is_server else knobs.client_write_drop
        if sometimes(drop):
            if knobs.debug:
                log.info("DROPPING written packet of length %d", len(data))
            _MET_DROPS[(self.is_server, "write")].inc()
            if sniff.is_sniffing():
                sniff.record(mtype, sent=False)
            return
        if sniff.is_sniffing():
            sniff.record(mtype, sent=True)
        if inspect and mtype == 1 and obj is not None:
            data = _mutate_data_packet(data, obj)
        self._transport.sendto(data, addr)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for task in list(self._delay_tasks):
            task.cancel()
        self._transport.close()


async def listen_udp(host: str = "127.0.0.1", port: int = 0) -> UDPEndpoint:
    """Open a server-side endpoint (ref: lspnet/net.go ListenUDP)."""
    loop = asyncio.get_running_loop()
    transport, protocol = await loop.create_datagram_endpoint(
        _Protocol, local_addr=(host, port))
    ep = UDPEndpoint(transport, is_server=True)
    protocol.bind(ep)
    return ep


async def dial_udp(host: str, port: int) -> UDPEndpoint:
    """Open a client-side endpoint connected to (host, port) (ref: lspnet/net.go DialUDP)."""
    loop = asyncio.get_running_loop()
    transport, protocol = await loop.create_datagram_endpoint(
        _Protocol, remote_addr=(host, port))
    ep = UDPEndpoint(transport, is_server=False)
    protocol.bind(ep)
    return ep
