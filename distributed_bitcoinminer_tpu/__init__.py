"""distributed_bitcoinminer_tpu — a TPU-native distributed hash-search framework.

A ground-up rebuild of the capabilities of the CMU 15-440 P1 reference system
(`alexsun705/distributed_bitcoinMiner`): a reliable UDP transport ("LSP"), a
fault-injecting simulated network, and a three-role distributed arg-min
hash-search application (scheduler / miner / client) — with the compute plane
redesigned TPU-first (JAX / XLA / Pallas / shard_map over a device Mesh).

Two planes:

- **Control plane** (``lsp``, ``lspnet``, ``apps``): Python asyncio actors
  speaking a wire format byte-compatible with the Go reference
  (JSON-encoded LSP messages over UDP), so stock reference harnesses remain
  valid counterparties.
- **Compute plane** (``ops``, ``parallel``, ``models``): a jitted,
  mesh-sharded, Pallas-backed SHA-256 arg-min search program. The nonce range
  is the "sequence" axis: blockwise chunks within a core (Pallas grid),
  lane-vectorized hashing within a block, mesh-sharded ranges across cores
  with an on-device lexicographic-min collective.
"""

__version__ = "0.1.0"
