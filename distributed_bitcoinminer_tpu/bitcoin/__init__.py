"""Application layer: hash-search wire protocol + the compute op oracle.

Wire-compatible with the reference ``bitcoin`` package
(/root/reference/p1/src/github.com/cmu440/bitcoin).
"""

from .message import Message, MsgType, new_join, new_request, new_result
from .hash import hash_op, scan_min, scan_until, MAX_U64

__all__ = ["Message", "MsgType", "new_join", "new_request", "new_result",
           "hash_op", "scan_min", "scan_until", "MAX_U64"]
