"""Join / Request / Result application messages.

JSON layout matches Go ``encoding/json`` of the reference struct
(ref: bitcoin/message.go:18-49): all fields always present, in struct order,
``Lower``/``Upper``/``Hash``/``Nonce`` are uint64 numbers.

Difficulty extension (this framework only): a Request may carry a
``Target`` field — "stop at the first nonce whose hash is strictly below
this" (BASELINE config 5). It is appended AFTER the reference fields and
only when set, so a target-less message is byte-identical to the stock
encoding, and a stock Go endpoint parsing a target-ful one simply drops
the unknown key (``encoding/json`` ignores fields with no struct match)
and performs a full arg-min scan — a valid, if slower, answer to the same
Request. ``target == 0`` means "no target": no uint64 hash is ``< 0``, so
zero could never qualify a nonce anyway.

Trace extension (ISSUE 10, same mechanics as ``Target``): a miner→server
Result may carry a ``Span`` object — the chunk's device-timing span
(utils/trace.py phase vocabulary) that the scheduler stitches into the
request's trace. Appended only when set (``DBM_TRACE=1``) so a span-less
message keeps stock bytes bit-for-bit; a stock endpoint drops the
unknown key. Parsing tolerates ANY malformed value by dropping it to
None — an observability field must never kill a message that carries a
valid answer.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass


def _go_json_string(s: str) -> str:
    """Encode a string exactly like Go ``encoding/json`` (HTML-escaping on,
    non-ASCII emitted as raw UTF-8, U+2028/U+2029 escaped)."""
    out = json.dumps(s, ensure_ascii=False)
    out = out.replace("<", "\\u003c").replace(">", "\\u003e").replace("&", "\\u0026")
    out = out.replace("\u2028", "\\u2028").replace("\u2029", "\\u2029")
    return out


class MsgType(enum.IntEnum):
    JOIN = 0     # miner -> server: register for work
    REQUEST = 1  # client -> server and server -> miner: search [lower, upper]
    RESULT = 2   # miner -> server and server -> client: (min hash, argmin nonce)


@dataclass
class Message:
    type: MsgType = MsgType.JOIN
    data: str = ""
    lower: int = 0
    upper: int = 0
    hash: int = 0
    nonce: int = 0
    target: int = 0   # extension; 0 = absent (stock bytes)
    span: dict = None  # trace extension; None = absent (stock bytes)
    rate: int = 0     # JOIN rate-hint extension; 0 = absent (stock bytes)

    def to_json(self) -> bytes:
        tail = f',"Target":{self.target}' if self.target else ""
        if self.span:
            tail += ',"Span":%s' % json.dumps(
                self.span, sort_keys=True, separators=(",", ":"))
        if self.rate:
            tail += f',"Rate":{self.rate}'
        return (
            '{"Type":%d,"Data":%s,"Lower":%d,"Upper":%d,"Hash":%d,"Nonce":%d%s}'
            % (int(self.type), _go_json_string(self.data), self.lower, self.upper,
               self.hash, self.nonce, tail)
        ).encode("utf-8")

    @classmethod
    def from_json(cls, raw: bytes) -> "Message":
        obj = json.loads(raw)
        # Valid JSON that isn't an object ([1,2], "x", 5) or carries a
        # non-string Data must raise ValueError like malformed bytes do:
        # an AttributeError here escapes the recv loops' `except
        # ValueError: continue` and kills the whole endpoint, not one
        # message (code-review r4).
        if not isinstance(obj, dict) or not isinstance(obj.get("Data", ""),
                                                       str):
            raise ValueError("not a message object")

        def u64(key: str) -> int:
            # Go json.Unmarshal into uint64 errors on out-of-range,
            # fractional, or non-numeric values and the reference endpoints
            # skip unparsable messages; raising ValueError here reaches the
            # same `except ValueError: continue` in every caller. The
            # isinstance check must come before any int() conversion:
            # int(None)/int([1]) raise TypeError and int(float('inf'))
            # OverflowError, which would escape those guards and kill the
            # endpoint, not the message; and without the range check a
            # poison Request (e.g. Target = 2^64) would crash each miner's
            # c_uint64/uint32 conversion in turn and drain the whole pool
            # (code-review r4).
            value = obj.get(key, 0)
            if isinstance(value, bool) or not isinstance(value, int) \
                    or not 0 <= value < (1 << 64):
                raise ValueError(f"{key} is not a uint64")
            return value

        type_value = obj.get("Type", 0)
        if isinstance(type_value, bool) or not isinstance(type_value, int):
            raise ValueError("Type is not an integer")
        # Span is observability-only: a malformed value (non-dict, junk
        # from a hostile peer) is dropped, never an error — the message
        # still carries a valid answer the merge must not lose.
        span = obj.get("Span")
        if not isinstance(span, dict):
            span = None
        # Rate is a scheduling HINT (ISSUE 14 rate-hint JOIN): like Span,
        # a malformed value from a hostile or buggy peer drops to 0 (no
        # hint) rather than killing a JOIN that is otherwise valid — the
        # scheduler treats an unhinted miner exactly like a stock one.
        rate = obj.get("Rate", 0)
        if isinstance(rate, bool) or not isinstance(rate, int) \
                or not 0 <= rate < (1 << 64):
            rate = 0
        return cls(
            type=MsgType(type_value),
            data=obj.get("Data", ""),
            lower=u64("Lower"),
            upper=u64("Upper"),
            hash=u64("Hash"),
            nonce=u64("Nonce"),
            target=u64("Target"),
            span=span,
            rate=rate,
        )

    def __str__(self) -> str:
        # Same pretty-print as the reference (ref: bitcoin/message.go:52-62).
        if self.type == MsgType.REQUEST:
            return f"[Request {self.data} {self.lower} {self.upper}]"
        if self.type == MsgType.RESULT:
            return f"[Result {self.hash} {self.nonce}]"
        return "[Join]"


def new_join(rate: int = 0) -> Message:
    """``rate``: measured throughput hint in nonces/s (ISSUE 14 mesh
    plane) — a cold 1B-nps pod announces its width at JOIN so the
    scheduler's rate EWMA starts warm instead of feeding it mouse-sized
    chunks. 0 (the default, and every stock miner) serializes to
    reference-identical bytes; the hint is advisory and bounded/decayed
    scheduler-side until real Results confirm it."""
    return Message(type=MsgType.JOIN, rate=rate)


def new_request(data: str, lower: int, upper: int, target: int = 0) -> Message:
    return Message(type=MsgType.REQUEST, data=data, lower=lower, upper=upper,
                   target=target)


def new_result(hash_value: int, nonce: int, target: int = 0,
               span: dict = None) -> Message:
    """``target``: until-speaking miners echo the Request's target so the
    scheduler can tell which responders honored the extension (a stock
    miner drops the key; 0 serializes to reference-identical bytes).
    ``span``: the chunk's device-timing span (``DBM_TRACE=1`` miners;
    None serializes to reference-identical bytes)."""
    return Message(type=MsgType.RESULT, hash=hash_value, nonce=nonce,
                   target=target, span=span)
