"""Join / Request / Result application messages.

JSON layout matches Go ``encoding/json`` of the reference struct
(ref: bitcoin/message.go:18-49): all fields always present, in struct order,
``Lower``/``Upper``/``Hash``/``Nonce`` are uint64 numbers.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass


def _go_json_string(s: str) -> str:
    """Encode a string exactly like Go ``encoding/json`` (HTML-escaping on,
    non-ASCII emitted as raw UTF-8, U+2028/U+2029 escaped)."""
    out = json.dumps(s, ensure_ascii=False)
    out = out.replace("<", "\\u003c").replace(">", "\\u003e").replace("&", "\\u0026")
    out = out.replace("\u2028", "\\u2028").replace("\u2029", "\\u2029")
    return out


class MsgType(enum.IntEnum):
    JOIN = 0     # miner -> server: register for work
    REQUEST = 1  # client -> server and server -> miner: search [lower, upper]
    RESULT = 2   # miner -> server and server -> client: (min hash, argmin nonce)


@dataclass
class Message:
    type: MsgType = MsgType.JOIN
    data: str = ""
    lower: int = 0
    upper: int = 0
    hash: int = 0
    nonce: int = 0

    def to_json(self) -> bytes:
        return (
            '{"Type":%d,"Data":%s,"Lower":%d,"Upper":%d,"Hash":%d,"Nonce":%d}'
            % (int(self.type), _go_json_string(self.data), self.lower, self.upper,
               self.hash, self.nonce)
        ).encode("utf-8")

    @classmethod
    def from_json(cls, raw: bytes) -> "Message":
        obj = json.loads(raw)
        return cls(
            type=MsgType(obj.get("Type", 0)),
            data=obj.get("Data", ""),
            lower=int(obj.get("Lower", 0)),
            upper=int(obj.get("Upper", 0)),
            hash=int(obj.get("Hash", 0)),
            nonce=int(obj.get("Nonce", 0)),
        )

    def __str__(self) -> str:
        # Same pretty-print as the reference (ref: bitcoin/message.go:52-62).
        if self.type == MsgType.REQUEST:
            return f"[Request {self.data} {self.lower} {self.upper}]"
        if self.type == MsgType.RESULT:
            return f"[Result {self.hash} {self.nonce}]"
        return "[Join]"


def new_join() -> Message:
    return Message(type=MsgType.JOIN)


def new_request(data: str, lower: int, upper: int) -> Message:
    return Message(type=MsgType.REQUEST, data=data, lower=lower, upper=upper)


def new_result(hash_value: int, nonce: int) -> Message:
    return Message(type=MsgType.RESULT, hash=hash_value, nonce=nonce)
