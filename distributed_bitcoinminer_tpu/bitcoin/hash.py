"""The compute op, host oracle tier.

``hash_op(msg, nonce)`` = big-endian uint64 of the first 8 bytes of
``sha256(f"{msg} {nonce}")`` with the nonce rendered as ASCII decimal
(ref: bitcoin/hash.go:13-17). This is the bit-exactness oracle for the JAX and
Pallas tiers in ``ops/``; the device kernels must agree with it on every nonce.
"""

from __future__ import annotations

import hashlib

MAX_U64 = (1 << 64) - 1


def hash_op(msg: str, nonce: int) -> int:
    digest = hashlib.sha256(f"{msg} {nonce}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


def scan_until(msg: str, lower: int, upper: int,
               target: int) -> tuple[int, int, bool]:
    """CPU-oracle difficulty scan: ``(hash, nonce, found)``.

    Ascending scan of the inclusive range; stops at the FIRST nonce whose
    hash is strictly below ``target`` (found=True). When no nonce
    qualifies, degrades to the exact arg-min (found=False) — the same
    contract as ``models.NonceSearcher.search_until`` and the tiers under
    it, which this function is the bit-exactness oracle for.
    """
    best_hash = MAX_U64
    best_nonce = lower
    for n in range(lower, upper + 1):
        h = hash_op(msg, n)
        if h < target:
            return h, n, True
        if h < best_hash:
            best_hash, best_nonce = h, n
    return best_hash, best_nonce, False


def scan_min(msg: str, lower: int, upper: int) -> tuple[int, int]:
    """CPU-oracle arg-min scan over the inclusive range [lower, upper].

    Mirrors the reference miner's hot loop (ref: bitcoin/miner/miner.go:52-59):
    strict ``<`` comparison, so the earliest nonce wins ties. One scan
    loop serves both modes: target 0 can never hit (no uint64 hash is
    ``< 0``), the same dereplication as ``dbm_scan_min`` native-side.
    """
    return scan_until(msg, lower, upper, 0)[:2]
