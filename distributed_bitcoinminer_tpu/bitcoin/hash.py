"""The compute op, host oracle tier.

``hash_op(msg, nonce)`` = big-endian uint64 of the first 8 bytes of
``sha256(f"{msg} {nonce}")`` with the nonce rendered as ASCII decimal
(ref: bitcoin/hash.go:13-17). This is the bit-exactness oracle for the JAX and
Pallas tiers in ``ops/``; the device kernels must agree with it on every nonce.
"""

from __future__ import annotations

import hashlib

MAX_U64 = (1 << 64) - 1


def hash_op(msg: str, nonce: int) -> int:
    digest = hashlib.sha256(f"{msg} {nonce}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


def scan_min(msg: str, lower: int, upper: int) -> tuple[int, int]:
    """CPU-oracle arg-min scan over the inclusive range [lower, upper].

    Mirrors the reference miner's hot loop (ref: bitcoin/miner/miner.go:52-59):
    strict ``<`` comparison, so the earliest nonce wins ties.
    """
    best_hash = MAX_U64
    best_nonce = lower
    for n in range(lower, upper + 1):
        h = hash_op(msg, n)
        if h < best_hash:
            best_hash, best_nonce = h, n
    return best_hash, best_nonce
