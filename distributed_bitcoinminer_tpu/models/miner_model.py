"""The flagship model: exact SHA-256 arg-min search over a nonce range.

Host orchestration around :func:`ops.search.search_span` — the TPU-native
replacement for the reference miner's scalar hot loop
(ref: bitcoin/miner/miner.go:52-59). The "sequence axis" of this framework is
the nonce range; it is scaled by:

1. digit-class splitting (decimal width must be static per device call);
2. aligned 10^k blocks (top digits constant -> absorbed into a host
   midstate; k <= 9 low digits formatted on device in uint32);
3. a device-side fori_loop scan per block (no host round-trip inside);
4. (parallel/) mesh sharding of blocks across devices with a collective
   lexicographic-min merge.

Results are bit-identical to the Go reference, including ties (earliest
nonce wins everywhere).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ..bitcoin.hash import MAX_U64
from ..ops import searchop
from ..ops.search import (devloop_cap, devloop_span, devloop_span_until,
                          pow2_bucket, search_span, search_span_segmin,
                          search_span_until)
from ..ops.sha256_host import sha256_midstate
from ..ops.sha256_jnp import build_hoist, build_tail_template
from ..utils._env import str_env as _str_env
from ..utils.metrics import registry as _registry
from ..utils.trace import observe_launch as _observe_launch

_SENTINEL = (0xFFFFFFFF, 0xFFFFFFFF)
#: Row cap per coalesced launch: a batch wider than this splits into
#: several launches (keeps the pow2 signature set small and one launch's
#: compile bounded). 64 rows is ~8x the default coalescer lane cap.
_BATCH_ROWS_MAX = 64
#: Devloop amortization floor (ISSUE 19): a chunk whose estimated scan
#: time is below this falls back to the stock batched path — a mouse
#: chunk's win comes from the coalescer, not from saving a handful of
#: already-cheap launches, and keeping mice on the stock path keeps the
#: coalescer population (and its metrics) unchanged. Sized to the mouse
#: boundary: a 2^14-lane mouse estimates ~1.5 ms on the CPU tier, so
#: 2 ms keeps every mouse on the stock path while 2^16-lane-and-up
#: chunks — where the launch amortization is already measurable
#: (``detail.devloop``) — stay on the loop.
_DEVLOOP_MIN_EST_S = 2e-3
#: EWMA blend for the devloop nonces/s estimate the floor divides by.
_DEVLOOP_EWMA = 0.3


def devloop_enabled() -> bool:
    """Whether argmin dispatch uses the device-resident span loop
    (ISSUE 19). Default ON: one launch per 10^k block, one <= 20-byte
    carry fetch per span. ``DBM_DEVLOOP=0`` restores the stock pow2
    sub-dispatch path bit-for-bit (the knob-off matrix leg pins it)."""
    return _str_env("DBM_DEVLOOP", "1") != "0"


def devloop_until_enabled() -> bool:
    """Whether difficulty mode ALSO rides the device-resident loop.
    Staged separately (``DBM_DEVLOOP_UNTIL``, default OFF): until's
    early-exit/prefix-release semantics are the subtler contract, so it
    follows the argmin rollout rather than leading it."""
    return _str_env("DBM_DEVLOOP_UNTIL", "0") == "1"


class _DevloopHandle:
    """Opaque :meth:`NonceSearcher.dispatch` handle for a devloop span:
    the single device-resident carry (plus accounting the finalize side
    and the trace plane read). ``nbytes`` is the size of the ONE host
    transfer finalize will perform."""

    __slots__ = ("carry", "subs", "lanes", "nbytes", "t0")

    def __init__(self, carry, subs: int, lanes: int, nbytes: int, t0: float):
        self.carry = carry
        self.subs = subs          # in-kernel sub-window count (trace "subs")
        self.lanes = lanes        # valid lanes covered (nps estimate)
        self.nbytes = nbytes      # bytes fetched at finalize
        self.t0 = t0              # dispatch wall-clock start

# Model-layer metrics (utils/metrics.py): midstate/hoist cache behavior
# (a miss pays the scalar hoist build; production traffic should be nearly
# all hits), block dispatch counts, and pallas->jnp until-tier degradation
# events — previously visible only as one log line and a bench field.
_M = _registry()
_MET_PLAN_HIT = _M.counter("model.midstate_cache", result="hit")
_MET_PLAN_MISS = _M.counter("model.midstate_cache", result="miss")
_MET_HOIST_ON = _M.counter("model.hoist_plans", enabled="true")
_MET_HOIST_OFF = _M.counter("model.hoist_plans", enabled="false")
_MET_BLOCKS = _M.counter("model.blocks_dispatched")
_MET_DEGRADED = _M.counter("model.until_degraded")
# Batched-dispatch plane (ISSUE 9): every DEVICE LAUNCH (one jitted
# dispatch — the unit the coalescer amortizes; bench.py's
# dispatches-per-mouse reads this), coalesced launches specifically,
# their row widths, and batch-stack cache behavior.
_MET_LAUNCHES = _M.counter("model.device_launches")
_MET_BATCH_LAUNCHES = _M.counter("model.coalesced_launches")
_MET_BATCH_ROWS = _M.counter("model.coalesced_rows")
_MET_STACK_HIT = _M.counter("model.batch_stack_cache", result="hit")
_MET_STACK_MISS = _M.counter("model.batch_stack_cache", result="miss")


def default_tier() -> str:
    """Device-kernel tier from ``DBM_COMPUTE``: ``pallas`` selects the
    Mosaic kernel; the *searcher-level* values that config.make_searcher
    also reads from the same variable (``auto``/``jax``/``host``) mean
    "not a tier request" and resolve by platform — the Mosaic kernel on a
    real chip, where it benches fastest (see BASELINE.md measured
    results), the XLA tier anywhere else (off-chip pallas would run in
    the Mosaic simulator at interpreter speed). ``jnp`` pins the XLA tier
    explicitly. (Round-3 fix lineage: ``DBM_COMPUTE=jax`` used to leak
    through as an unknown tier and crash the miner's first search.)"""
    value = _str_env("DBM_COMPUTE", "auto").lower()
    if value in ("", "auto", "jax", "host"):
        from ..utils.config import CHIP_PLATFORMS, jax_devices_robust
        on_chip = jax_devices_robust()[0].platform in CHIP_PLATFORMS
        return "pallas" if on_chip else "jnp"
    return value  # 'jnp'/'pallas', or unknown -> NonceSearcher raises


def _digit_classes(lower: int, upper: int):
    """Split [lower, upper] at decimal-width boundaries (static width per
    device call). Yields (digits, lo, hi) inclusive sub-ranges."""
    for d in range(1, 21):
        class_lo = 0 if d == 1 else 10 ** (d - 1)
        class_hi = 10 ** d - 1
        lo = max(lower, class_lo)
        hi = min(upper, class_hi)
        if lo <= hi:
            yield d, lo, hi


@dataclass
class _BlockPlan:
    """One aligned 10^k block of the search, ready for device dispatch."""
    base: int          # nonce value of lane i=0 (block_base)
    lo_i: int          # first valid lane
    hi_i: int          # last valid lane
    midstate: tuple    # 8 x uint32 after absorbing data + " " + top_digits
    template: np.ndarray
    rem: int
    k: int
    #: Lane-invariant precompute (ops.sha256_jnp.HoistPlan): deep midstate
    #: after the constant head rounds, precombined K+W, constant schedule
    #: terms. None when DBM_HOIST=0 pins the original entry path.
    hoist: object = None

    @property
    def hoist_ops(self):
        """jit-operand dict of the hoist (None when disabled)."""
        return self.hoist.ops if self.hoist is not None else None


class _StackCache:
    """Bounded LRU of stacked batch operands (ISSUE 9).

    A coalesced launch gathers R rows' per-midstate plans — midstate
    (R, 8), template (R, nblocks, 16), hoist operands — into one
    stacked jit operand set. Steady-state mice traffic repeats the same
    (data, block) populations launch after launch, so the np.stack
    gather runs once per distinct population instead of once per
    launch. Keys are value-identifying ``(data, top, k)`` tuples (the
    same identity the per-searcher midstate cache uses), never object
    ids, so a rebuilt searcher with identical data still hits.
    Lock-guarded: the cache is PROCESS-wide while each MinerWorker
    serializes only its OWN worker threads — two in-process miners
    (the bench probes, the e2e tests) dispatch concurrently, and an
    unguarded get()'s ``move_to_end`` racing another thread's
    put()-eviction of the same LRU-oldest key is a KeyError that
    would kill the miner mid-request (code review).
    """

    def __init__(self, size: int = 32):
        import threading
        self.size = size
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            hit = self._d.get(key)
            if hit is not None:
                self._d.move_to_end(key)
        (_MET_STACK_HIT if hit is not None else _MET_STACK_MISS).inc()
        return hit

    def put(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.size:
                self._d.popitem(last=False)


#: Process-wide stack cache: populations repeat ACROSS searchers (the
#: whole point of mixed-message batching), so the memo cannot live on
#: one searcher — and therefore cannot rely on any single worker's
#: executor serialization (hence the internal lock).
_STACKS = _StackCache()


class NonceSearcher:
    """Exact arg-min hash search for one message, chunk-schedulable.

    ``batch`` is the lane count per device step; on TPU use >= 2**20 to keep
    the VPU busy, on CPU tests a few thousand. ``tier`` selects the device
    kernel: ``jnp`` (rolled fori_loop compression) or ``pallas`` (fully
    unrolled register-resident Mosaic kernel); None reads ``DBM_COMPUTE``.
    """

    #: Whether :meth:`dispatch` may serve the devloop shape. The mesh
    #: model has its own devloop plumbing (one launch per block across
    #: the whole mesh); the plain sharded model inherits this dispatch,
    #: where a single-device devloop would silently ignore the mesh —
    #: it pins False.
    _supports_devloop = True

    def __init__(self, data: str, batch: int = 1 << 20,
                 tier: str | None = None, hoist: bool | None = None):
        self.data = data
        self.batch = batch
        self.tier = tier if tier is not None else default_tier()
        if self.tier not in ("jnp", "pallas"):
            raise ValueError(f"unknown compute tier {self.tier!r}")
        self._prefix = data.encode("utf-8") + b" "
        self._midstate_cache: dict[str, tuple] = {}
        #: Sticky fallback: pallas until-tier failed to lower/run once ->
        #: this searcher serves difficulty mode from the jnp tier.
        self._until_degraded = False
        #: Lane-invariant hoist (deep midstate + constant schedule terms);
        #: DBM_HOIST=0 is the safety valve back to the original entry path.
        self.use_hoist = (hoist if hoist is not None
                          else _str_env("DBM_HOIST", "1") != "0")
        #: Difficulty-mode sub-dispatch lookahead: with DBM_UNTIL_PIPELINE=1
        #: (default) sub k+1 is dispatched BEFORE sub k's result is forced,
        #: hiding dispatch+fetch latency behind compute; 0 restores the
        #: strictly serial force order. Either way results are FORCED in
        #: ascending order, so first-hit-wins semantics are untouched — a
        #: speculatively dispatched later sub is simply discarded when an
        #: earlier sub hits (its scan is idempotent).
        self._until_lookahead = (
            1 if _str_env("DBM_UNTIL_PIPELINE", "1") != "0" else 0)
        #: Devloop nonces/s EWMA (est-seconds fallback floor); None until
        #: the first devloop span finalizes — the first span always takes
        #: the devloop path and seeds the estimate.
        self._devloop_nps: float | None = None
        #: In-kernel sub-window count of the LAST dispatch — the trace
        #: plane stamps it as the span's ``subs`` field (ISSUE 19
        #: satellite: a devloop span reports one launch, not zero-width
        #: per-sub phases). None when the last dispatch was stock-shaped.
        self.last_dispatch_subs: int | None = None

    def _plan_block(self, d: int, k: int, block_base: int, lo: int, hi: int) -> _BlockPlan:
        top = str(block_base)[: d - k] if d > k else ""
        key = (top, k)
        cached = self._midstate_cache.get(key)
        if cached is None:
            _MET_PLAN_MISS.inc()
            (_MET_HOIST_ON if self.use_hoist else _MET_HOIST_OFF).inc()
            prefix = self._prefix + top.encode("ascii")
            midstate, tail = sha256_midstate(prefix)
            template = build_tail_template(tail, k, len(prefix) + k)
            # The hoist is part of the cache entry: its scalar-numpy round
            # extension + schedule precombination run once per midstate,
            # not once per dispatched block.
            hoist = (build_hoist(midstate, template, len(tail), k)
                     if self.use_hoist else None)
            cached = (midstate, template, len(tail), hoist)
            self._midstate_cache[key] = cached
        else:
            _MET_PLAN_HIT.inc()
        _MET_BLOCKS.inc()
        midstate, template, rem, hoist = cached
        return _BlockPlan(
            base=block_base,
            lo_i=max(lo, block_base) - block_base,
            hi_i=min(hi, block_base + 10 ** k - 1) - block_base,
            midstate=midstate, template=template, rem=rem, k=k,
            hoist=hoist)

    def plan(self, lower: int, upper: int):
        """All aligned blocks covering [lower, upper], ascending."""
        for d, lo, hi in _digit_classes(lower, upper):
            k = min(d, 9)
            span = 10 ** k
            base = (lo // span) * span
            while base <= hi:
                yield self._plan_block(d, k, base, lo, hi)
                base += span

    def _sub_dispatches(self, plan: _BlockPlan,
                        per_step: int | None = None) -> list[tuple[int, int]]:
        """Descending-pow2 decomposition of one block's dispatch.

        Returns contiguous ``(i0, nbatches)`` sub-dispatches covering
        exactly ``ceil(span / per)`` steps, every ``nbatches`` a power of
        two. The first ``i0`` is batch-aligned BELOW lo_i, so the step
        count must be sized from it (not lo_i) or the top lanes of the
        block go unscanned.

        Why a decomposition instead of one rounded-up dispatch: ``nbatches``
        is a static jit argument, so it must stay within a small value set
        or every odd-sized range pays a fresh ~20-40 s XLA compile — but
        rounding the count UP to one power of two (rounds 1-2) made the
        device scan up to 2x the requested range in masked-overscan lanes.
        The bench geometry (65 steps -> 128) ran at 222-265M nonces/s
        while the raw kernel measured 560-630M/s (round-3 finding).
        Splitting 65 into 64+1 keeps the pow2 signature set AND the exact
        lane count; the <= log2(n) extra dispatches pipeline behind each
        other, and sub-results merge in :meth:`finalize` exactly like
        blocks do (ascending, strict-less, earliest nonce on ties).

        One helper shared by every dispatch path (single-device + mesh,
        argmin + difficulty) so the sizing rule can't drift between
        them; the decomposition itself is ``parallel.partition.
        pow2_subs`` — ONE copy of the pow2 policy for this path and the
        mesh plane's window chains alike.
        """
        from ..parallel.partition import pow2_subs
        per = per_step if per_step is not None else self.batch
        i0 = (plan.lo_i // self.batch) * self.batch
        span = plan.hi_i - i0 + 1
        n = (span + per - 1) // per
        return [(i0 + off * per, p) for off, p in pow2_subs(n)]

    def search_block(self, plan: _BlockPlan) -> list:
        """Dispatch one block as pow2 sub-dispatches; returns a list of
        (hi, lo, idx) device-scalar triples, ascending by span.

        Each sub-dispatch runs under the compile observer
        (utils/trace.py): the launch's static signature — the exact
        tuple the jit-static lint guards — is what the recompile-storm
        alarm watches, and a fresh signature's first-call elapsed is the
        compile estimate."""
        subs = self._sub_dispatches(plan)
        _MET_LAUNCHES.inc(len(subs))
        out = []
        if self.tier == "pallas":
            from ..ops.sha256_pallas import pallas_argmin

            # devices()[0] is the default device — exactly where this
            # un-sharded call will be placed — so its platform is the
            # right interpret signal here (the mesh path derives it from
            # the mesh instead); off-TPU the kernel runs in the Mosaic
            # TPU simulator, on the chip it lowers through Mosaic.
            for i0, nbatches in subs:
                with _observe_launch(("pallas_argmin", plan.rem, plan.k,
                                      self.batch, nbatches)):
                    out.append(pallas_argmin(
                        np.asarray(plan.midstate, dtype=np.uint32),
                        plan.template,
                        np.uint32(i0), np.uint32(plan.lo_i),
                        np.uint32(plan.hi_i),
                        rem=plan.rem, k=plan.k,
                        total=self.batch * nbatches,
                        platform=self._platform(), hoist=plan.hoist_ops))
            return out
        for i0, nbatches in subs:
            with _observe_launch(("search_span", plan.rem, plan.k,
                                  self.batch, nbatches)):
                out.append(search_span(
                    np.asarray(plan.midstate, dtype=np.uint32),
                    plan.template,
                    np.uint32(i0), np.uint32(plan.lo_i),
                    np.uint32(plan.hi_i),
                    plan.hoist_ops,
                    rem=plan.rem, k=plan.k, batch=self.batch,
                    nbatches=nbatches))
        return out

    def dispatch(self, lower: int, upper: int) -> list:
        """Dispatch every block of the range WITHOUT forcing results.

        Returns an opaque list of (base, device-triple) pairs for
        :meth:`finalize`. JAX dispatch is asynchronous, so a caller can
        enqueue several ranges back-to-back and keep the device busy while
        earlier results transfer — the host<->device overlap knob
        (SURVEY §7 "double-buffer chunks"; bench measures it automatically
        whenever a searcher exposes dispatch/finalize). As of ISSUE 4 the
        production consumer is the miner's pipelined executor
        (apps/miner.MinerWorker, ``DBM_PIPELINE``), which dispatches chunk
        k+1 here while chunk k sits in :meth:`finalize`.
        """
        if lower > upper:
            raise ValueError("empty range")
        self.last_dispatch_subs = None
        if self._devloop_ok():
            lanes = upper - lower + 1
            if self._devloop_eligible(lanes):
                return self._devloop_dispatch(
                    list(self.plan(lower, upper)), lanes)
        return [(plan.base, triple)
                for plan in self.plan(lower, upper)
                for triple in self.search_block(plan)]

    # ---------------------------------------------- devloop dispatch shape

    def _devloop_ok(self) -> bool:
        """Devloop gating: the knob, the model's support flag, and — on
        the pallas tier — the separate persistent-grid rollout knob
        (``DBM_DEVLOOP_PALLAS``; with it off a pallas searcher keeps the
        stock path rather than silently switching tiers)."""
        if not (devloop_enabled() and self._supports_devloop):
            return False
        if self.tier == "pallas":
            from ..ops.sha256_pallas import devloop_pallas_enabled
            return devloop_pallas_enabled()
        return True

    def _devloop_eligible(self, lanes: int) -> bool:
        """Est-seconds amortization floor (see ``_DEVLOOP_MIN_EST_S``).
        Unknown throughput (first span) estimates optimistically: the
        span seeds the EWMA either way."""
        if self._devloop_nps is None or self._devloop_nps <= 0:
            return True
        return lanes / self._devloop_nps >= _DEVLOOP_MIN_EST_S

    def _devloop_dispatch(self, plans: list, lanes: int) -> _DevloopHandle:
        """Chain every block of the span through the device-resident
        loop: ONE jitted launch per 10^k block (vs one per pow2 sub),
        the searchop carry threading device-side across blocks. Nothing
        is forced here; :meth:`finalize` fetches the final 20-byte
        carry once."""
        import time

        t0 = time.monotonic()
        carry = searchop.carry_init()
        subs = 0
        for plan in plans:
            i0 = (plan.lo_i // self.batch) * self.batch
            nsub = (plan.hi_i - i0 + 1 + self.batch - 1) // self.batch
            cap = devloop_cap(nsub)
            subs += nsub
            base_hi = np.uint32(plan.base >> 32)
            base_lo = np.uint32(plan.base & 0xFFFFFFFF)
            _MET_LAUNCHES.inc()
            if self.tier == "pallas":
                from ..ops.sha256_pallas import pallas_devloop_span
                with _observe_launch(("pallas_devloop_span", plan.rem,
                                      plan.k, self.batch, cap)):
                    carry = pallas_devloop_span(
                        np.asarray(plan.midstate, dtype=np.uint32),
                        plan.template, carry,
                        np.uint32(i0), np.uint32(plan.lo_i),
                        np.uint32(plan.hi_i), np.int32(nsub),
                        base_hi, base_lo,
                        rem=plan.rem, k=plan.k, batch=self.batch,
                        cap=cap, platform=self._platform(),
                        hoist=plan.hoist_ops)
            else:
                with _observe_launch(("devloop_span", plan.rem, plan.k,
                                      self.batch, cap)):
                    carry = devloop_span(
                        np.asarray(plan.midstate, dtype=np.uint32),
                        plan.template, carry,
                        np.uint32(i0), np.uint32(plan.lo_i),
                        np.uint32(plan.hi_i), np.int32(nsub),
                        base_hi, base_lo, plan.hoist_ops,
                        rem=plan.rem, k=plan.k, batch=self.batch,
                        cap=cap)  # dbmlint: ok[jit-static] devloop_cap pow2
        self.last_dispatch_subs = subs
        return _DevloopHandle(carry, subs, lanes,
                              4 * searchop.CARRY_WORDS, t0)

    def _devloop_finalize(self, handle: _DevloopHandle,
                          lower: int) -> tuple[int, int]:
        """Force a devloop span: ONE device_get of the 5-word carry."""
        import time

        import jax

        words = jax.device_get(handle.carry)
        elapsed = time.monotonic() - handle.t0
        if elapsed > 0 and handle.lanes:
            nps = handle.lanes / elapsed
            self._devloop_nps = (
                nps if self._devloop_nps is None else
                (1 - _DEVLOOP_EWMA) * self._devloop_nps
                + _DEVLOOP_EWMA * nps)
        return searchop.decode_argmin(words, lower)

    def _devloop_until_ok(self) -> bool:
        """Whether difficulty mode rides the devloop: its own staging
        knob AND the argmin devloop gate (``DBM_DEVLOOP=0`` is the one
        master off-switch). On the pallas tier the stock until path is
        kept — not a silent jnp-devloop swap — until the persistent-grid
        knob opts in."""
        return (devloop_until_enabled() and devloop_enabled()
                and self._supports_devloop
                and (self.tier == "jnp" or self._devloop_ok()))

    def _devloop_until_chain(self, plans: list, t_hi: int, t_lo: int,
                             use_pallas: bool) -> np.ndarray:
        """Chain a span's blocks through the devloop difficulty launch
        and fetch the final 8-word carry ONCE. Early exit needs no host
        round-trip: a hit sets ``carry[0]`` on device and every later
        launch in the chain sees it and falls straight through (jnp:
        while cond goes false at step 0; pallas: live grid clamps to
        one step)."""
        import jax

        carry = searchop.until_carry_init()
        subs = 0
        for plan in plans:
            i0 = (plan.lo_i // self.batch) * self.batch
            nsub = (plan.hi_i - i0 + 1 + self.batch - 1) // self.batch
            cap = devloop_cap(nsub)
            subs += nsub
            base_hi = np.uint32(plan.base >> 32)
            base_lo = np.uint32(plan.base & 0xFFFFFFFF)
            _MET_LAUNCHES.inc()
            if use_pallas:
                from ..ops.sha256_pallas import pallas_devloop_span_until
                with _observe_launch(("pallas_devloop_until", plan.rem,
                                      plan.k, self.batch, cap)):
                    carry = pallas_devloop_span_until(
                        np.asarray(plan.midstate, dtype=np.uint32),
                        plan.template, carry,
                        np.uint32(i0), np.uint32(plan.lo_i),
                        np.uint32(plan.hi_i),
                        np.uint32(t_hi), np.uint32(t_lo),
                        np.int32(nsub), base_hi, base_lo,
                        rem=plan.rem, k=plan.k, batch=self.batch,
                        cap=cap, platform=self._platform(),
                        hoist=plan.hoist_ops)
            else:
                with _observe_launch(("devloop_span_until", plan.rem,
                                      plan.k, self.batch, cap)):
                    carry = devloop_span_until(
                        np.asarray(plan.midstate, dtype=np.uint32),
                        plan.template, carry,
                        np.uint32(i0), np.uint32(plan.lo_i),
                        np.uint32(plan.hi_i),
                        np.uint32(t_hi), np.uint32(t_lo),
                        np.int32(nsub), base_hi, base_lo,
                        plan.hoist_ops,
                        rem=plan.rem, k=plan.k, batch=self.batch,
                        cap=cap)  # dbmlint: ok[jit-static] devloop_cap pow2
        self.last_dispatch_subs = subs
        return jax.device_get(carry)

    def _devloop_search_until(self, lower: int, upper: int,
                              target: int) -> tuple[int, int, bool]:
        """Difficulty mode over the device-resident chain: one fetch per
        span, exact prefix-release semantics (the carry's first-hit
        plane keeps the LOWEST qualifying 64-bit nonce across chained
        folds). A pallas fault — at dispatch or at the fetch — latches
        the sticky until degradation and reruns the identical chain on
        the jnp tier (idempotent scan, same contract as the stock
        path's per-sub fallback)."""
        t_hi, t_lo = target >> 32, target & 0xFFFFFFFF
        plans = list(self.plan(lower, upper))
        use_pallas = (self.tier == "pallas" and not self._until_degraded)
        try:
            words = self._devloop_until_chain(plans, t_hi, t_lo,
                                              use_pallas)
        except Exception:
            if not use_pallas:
                raise
            self._degrade_until("pallas devloop until tier")
            words = self._devloop_until_chain(plans, t_hi, t_lo, False)
        found, f_nonce, best_hash, best_nonce = searchop.decode_until(
            words, lower)
        if found:
            from ..bitcoin.hash import hash_op
            return (hash_op(self.data, f_nonce), f_nonce, True)
        return (best_hash, best_nonce, False)

    def finalize(self, results: list, lower: int) -> tuple[int, int]:
        """Force dispatched block results and merge on host in ascending
        order (strict less keeps the earliest nonce on ties).

        ONE batched ``device_get`` fetches every triple: scalar-by-scalar
        ``int()`` conversion cost a full device round-trip per scalar —
        ~65 ms each over this image's axon tunnel, which capped the bench
        at 229M nonces/s while the identical dispatch measured 420M
        (round-3 finding).

        A devloop handle (ISSUE 19) short-circuits the merge entirely:
        the device already holds the span's argmin in a 5-word carry, so
        the fetch is 20 bytes and the "merge" is a decode.
        """
        import jax

        if isinstance(results, _DevloopHandle):
            return self._devloop_finalize(results, lower)
        fetched = jax.device_get([triple for _, triple in results])
        best_hash, best_nonce = MAX_U64, lower
        seen = False
        for (base, _), (hi, lo, idx) in zip(results, fetched):
            hi, lo, idx = int(hi), int(lo), int(idx)
            if (hi, lo) == _SENTINEL and idx == 0xFFFFFFFF:
                continue
            h = (hi << 32) | lo
            if not seen or h < best_hash:
                best_hash, best_nonce, seen = h, base + idx, True
        return best_hash, best_nonce

    def search(self, lower: int, upper: int) -> tuple[int, int]:
        """Exact (min_hash, argmin_nonce) over the inclusive range."""
        return self.finalize(self.dispatch(lower, upper), lower)

    # ------------------------------------------------ batched dispatch

    def coalesce_key(self) -> tuple:
        """Searchers with equal keys may share a coalesced launch: same
        kernel tier, same lane batch (a static geometry component), and
        the same hoist setting (group membership additionally requires
        equal (rem, k, nblocks, nbatches) per row — the planner splits
        on those)."""
        return (type(self), self.tier, self.batch, self.use_hoist)

    def dispatch_batch(self, entries: list):
        """Dispatch MANY independent argmin jobs — possibly for
        DIFFERENT messages — as few coalesced device launches (ISSUE 9:
        cross-request batched dispatch), without forcing results.

        ``entries`` is ``[(searcher, lower, upper), ...]``; ``self`` is
        entries[0]'s searcher (the miner calls through it). Every job's
        blocks decompose into pow2 sub-dispatch rows exactly like
        :meth:`dispatch`; rows are grouped by their static geometry
        ``(rem, k, nblocks, nbatches)`` — a group is one launch of
        :func:`ops.search.search_span_segmin` (or the gated pallas
        batch entry) with the row count pow2-bucketed and per-(job,
        block) segment ids, so the device answers a SEGMENT-min per
        (job, block) instead of one global argmin. Mixed messages cost
        one midstate-cache lookup per block (the plans are already
        cached) plus a stack-cache lookup per launch.

        Returns an opaque handle for :meth:`finalize_batch`, or None
        when this batch cannot coalesce (incompatible searchers, or the
        pallas tier with ``DBM_COALESCE_PALLAS`` off) — the caller then
        degrades to per-job dispatch. Results are BIT-IDENTICAL to
        per-job :meth:`search` either way (pinned by tests/test_batch).
        """
        key0 = self.coalesce_key()
        for s, lower, upper in entries:
            if not isinstance(s, NonceSearcher) or \
                    s.coalesce_key() != key0:
                return None
            if lower > upper:
                raise ValueError("empty range")
        if self.tier == "pallas":
            from ..ops.sha256_pallas import batch_enabled
            if not batch_enabled():
                return None
        # Rows grouped by static launch geometry. Group keys include the
        # hoist operand key set so a structural mismatch (e.g. plans
        # built under different DBM_HOIST_DEEP settings) can never share
        # a stacked operand.
        groups: dict = {}
        for ei, (s, lower, upper) in enumerate(entries):
            for plan in s.plan(lower, upper):
                hoist_keys = (frozenset(plan.hoist_ops)
                              if plan.hoist is not None else None)
                # per_step pinned to the SINGLE-device step: the segmin
                # launch scans nbatches*batch lanes per row, so a
                # subclass whose default _sub_dispatches sizes steps for
                # a WIDER plane (the mesh models' batch*n_devices) would
                # hand this path under-covering rows — observed as wrong
                # argmins when the coalescer batched sharded searchers
                # on a multi-device box (ISSUE 14 regression fix, pinned
                # by tests/test_mesh.py::test_sharded_dispatch_batch_covers).
                for i0, nbatches in s._sub_dispatches(plan,
                                                      per_step=s.batch):
                    gkey = (plan.rem, plan.k, plan.template.shape[0],
                            nbatches, hoist_keys)
                    groups.setdefault(gkey, []).append((ei, s, plan, i0))
        launches = []
        for (rem, k, _nb, nbatches, hoist_keys), rows in groups.items():
            for at in range(0, len(rows), _BATCH_ROWS_MAX):
                launches.append(self._launch_rows(
                    rows[at:at + _BATCH_ROWS_MAX],
                    rem=rem, k=k, nbatches=nbatches,
                    hoist_keys=hoist_keys))
        return (len(entries), [lower for _, lower, _ in entries], launches)

    def _launch_rows(self, rows: list, *, rem: int, k: int, nbatches: int,
                     hoist_keys=None):
        """One coalesced launch: stack the rows' plans (via the
        process-wide stack cache), assign per-(job, block) segment ids
        (ascending with row order — the segment reduce relies on it),
        pad the row count to a pow2 bucket with empty-window rows, and
        dispatch. Returns ``(seg_meta, device_triple)``."""
        n = len(rows)
        nrows = pow2_bucket(n)
        seg_meta: list = []          # seg id -> (entry_index, block base)
        seg_ids: dict = {}
        segs = []
        for ei, _s, plan, _i0 in rows:
            skey = (ei, plan.base)
            sid = seg_ids.get(skey)
            if sid is None:
                sid = seg_ids[skey] = len(seg_meta)
                seg_meta.append((ei, plan.base))
            segs.append(sid)
        # hoist_keys (the group's operand-key structure) is part of the
        # cache identity: the group key separates LAUNCHES on it, so a
        # cached stack from a different hoist structure (e.g. plans
        # built before a DBM_HOIST_DEEP flip) must never be served to
        # this one (code review).
        stack_key = (rem, k, nbatches, nrows, hoist_keys, tuple(
            (s.data, plan.base // 10 ** k)
            for _ei, s, plan, _i0 in rows))
        stacked = _STACKS.get(stack_key)
        if stacked is None:
            plans = [r[2] for r in rows] + [rows[-1][2]] * (nrows - n)
            midstates = np.stack([np.asarray(p.midstate, dtype=np.uint32)
                                  for p in plans])
            templates = np.stack([p.template for p in plans])
            hoists = None
            if plans[0].hoist is not None:
                hoists = {name: np.stack(
                    [np.asarray(p.hoist_ops[name], dtype=np.uint32)
                     for p in plans]) for name in plans[0].hoist_ops}
            stacked = (midstates, templates, hoists)
            _STACKS.put(stack_key, stacked)
        midstates, templates, hoists = stacked
        pad = nrows - n
        i0s = np.asarray([r[3] for r in rows] + [0] * pad, dtype=np.uint32)
        # Padded rows carry an inverted valid window: every lane masks
        # to the sentinel, which never wins a segment min; their seg id
        # is the last bucket slot (>= every real id, keeping the seg
        # vector sorted).
        lo_is = np.asarray([r[2].lo_i for r in rows] + [1] * pad,
                           dtype=np.uint32)
        hi_is = np.asarray([r[2].hi_i for r in rows] + [0] * pad,
                           dtype=np.uint32)
        seg = np.asarray(segs + [nrows - 1] * pad, dtype=np.int32)
        _MET_LAUNCHES.inc()
        _MET_BATCH_LAUNCHES.inc()
        _MET_BATCH_ROWS.inc(n)
        if self.tier == "pallas":
            from ..ops.sha256_pallas import pallas_segmin
            with _observe_launch(("pallas_segmin", rem, k, self.batch,
                                  nbatches, nrows)):
                triple = pallas_segmin(
                    midstates, templates, i0s, lo_is, hi_is, seg,
                    rem=rem, k=k, total=self.batch * nbatches,
                    nrows=nrows, platform=self._platform(),
                    hoists=hoists)
        else:
            with _observe_launch(("search_span_segmin", rem, k, self.batch,
                                  nbatches, nrows)):
                triple = search_span_segmin(
                    midstates, templates, i0s, lo_is, hi_is, seg, hoists,
                    rem=rem, k=k, batch=self.batch, nbatches=nbatches)
        return seg_meta, triple

    def finalize_batch(self, handle) -> list:
        """Force a batched dispatch with ONE device fetch and merge per
        job on the host: each job's per-(block, launch) segment results
        merge under the lexicographic ``(hash, nonce)`` min — the same
        rule :meth:`finalize` applies via its ascending strict-less walk
        (earliest nonce wins hash ties). Returns one ``(min_hash,
        argmin_nonce)`` pair per entry, in entry order; a job whose
        every segment came back sentinel (cannot happen for non-empty
        ranges, but mirrors :meth:`finalize`) answers ``(MAX_U64,
        lower)``."""
        import jax

        n_entries, lowers, launches = handle
        fetched = jax.device_get([triple for _, triple in launches])
        cands: list[list] = [[] for _ in range(n_entries)]
        for (seg_meta, _), (seg_hi, seg_lo, seg_idx) in zip(launches,
                                                            fetched):
            for sid, (ei, base) in enumerate(seg_meta):
                hi, lo, idx = (int(seg_hi[sid]), int(seg_lo[sid]),
                               int(seg_idx[sid]))
                if (hi, lo) == _SENTINEL and idx == 0xFFFFFFFF:
                    continue
                cands[ei].append(((hi << 32) | lo, base + idx))
        return [min(c) if c else (MAX_U64, lowers[ei])
                for ei, c in enumerate(cands)]

    def _degrade_until(self, what: str = "pallas until tier") -> None:
        """Sticky pallas->jnp until-tier degradation: a Mosaic lowering or
        runtime regression in the until kernel (its SMEM-flag skip is a
        newer construct than the battle-tested argmin kernel) must not
        take difficulty mode down with it — the jnp tier answers the
        identical contract. Sticky per searcher so one sub's failure
        doesn't retry the broken lowering for every sub of every later
        block. ``what`` names the failing shape in the log (the sharded
        model reuses this bookkeeping)."""
        import logging
        logging.getLogger("dbm.model").exception(
            "%s failed; degrading this searcher to the jnp until tier",
            what)
        _MET_DEGRADED.inc()
        self._until_degraded = True

    def _until_sub(self, plan: _BlockPlan, i0: int, nbatches: int,
                   t_hi: int, t_lo: int):
        """Dispatch one difficulty-target sub WITHOUT forcing the result;
        overridden by the mesh-sharded model. Returns an opaque handle for
        :meth:`_until_force` — splitting dispatch from force is what lets
        ``_until_block`` pipeline sub k+1's dispatch behind sub k's fetch.
        The handle resolves to the 5-tuple
        ``(found, f_idx, best_hi, best_lo, best_idx)`` of
        :func:`ops.search.search_span_until` (the qualifying HASH is
        recomputed by ``_until_block`` with the host oracle — one shared
        contract for both tiers). Both tiers early-exit inside the
        dispatch: the jnp tier per while_loop batch, the pallas tier per
        grid step via the SMEM found-flag skip (r4), so even the largest
        pow2 sub costs only ~one step of compute past the first hit."""
        _MET_LAUNCHES.inc()
        if self.tier == "pallas" and not self._until_degraded:
            from ..ops.sha256_pallas import pallas_until

            try:
                # Lowering/compile failures surface at the call; runtime
                # kernel faults surface at the force — _until_force
                # catches those (same degradation either way).
                with _observe_launch(("pallas_until", plan.rem, plan.k,
                                      self.batch, nbatches)):
                    return ("pallas", pallas_until(
                        np.asarray(plan.midstate, dtype=np.uint32),
                        plan.template,
                        np.uint32(i0), np.uint32(plan.lo_i),
                        np.uint32(plan.hi_i),
                        np.uint32(t_hi), np.uint32(t_lo),
                        rem=plan.rem, k=plan.k,
                        total=self.batch * nbatches,
                        platform=self._platform(), hoist=plan.hoist_ops))
            except Exception:
                self._degrade_until()
        with _observe_launch(("search_span_until", plan.rem, plan.k,
                              self.batch, nbatches)):
            return ("jnp", search_span_until(
                np.asarray(plan.midstate, dtype=np.uint32), plan.template,
                np.uint32(i0), np.uint32(plan.lo_i), np.uint32(plan.hi_i),
                np.uint32(t_hi), np.uint32(t_lo), plan.hoist_ops,
                rem=plan.rem, k=plan.k, batch=self.batch,
                nbatches=nbatches))

    def _until_force(self, plan: _BlockPlan, i0: int, nbatches: int,
                     t_hi: int, t_lo: int, handle):
        """Force one sub's handle to host ints. A pallas RUNTIME fault
        lands here (dispatch is async): degrade and recompute this sub on
        the jnp tier — re-scanning the identical range is idempotent."""
        import jax

        kind, result = handle
        try:
            # One batched fetch per sub (see finalize: per-scalar int()
            # costs a tunnel round-trip each).
            return jax.device_get(result)
        except Exception:
            # Key on the HANDLE's tier, not the sticky flag: with
            # pipelining, sub k+1 was dispatched as pallas before sub k's
            # fault latched degradation, and its force must also fall
            # back instead of re-raising. The recompute dispatches jnp
            # (flag is set), so there is no recursion.
            if kind != "pallas":
                raise
            if not self._until_degraded:
                self._degrade_until()
            return jax.device_get(
                self._until_sub(plan, i0, nbatches, t_hi, t_lo)[1])

    def _until_block(self, plan: _BlockPlan, t_hi: int, t_lo: int):
        """Difficulty-target scan of one block: the pow2 sub-dispatches are
        FORCED in ascending order, so the device early-exit composes with
        a host early-exit between subs and the first qualifying nonce
        globally is the first sub's first hit. With pipelining (default,
        ``DBM_UNTIL_PIPELINE``) sub k+1 is dispatched before sub k's
        result is fetched, so the device computes while the host merges —
        pure speculation: if sub k hits, sub k+1's in-flight scan is
        discarded unread (it covers strictly higher nonces, so it can
        never change the answer). Returns host ints
        ``(found, f_hash, f_idx, best_hi, best_lo, best_idx)`` — f_hash is
        recomputed from the host oracle (the device tiers report only the
        qualifying INDEX; one host sha256 is exact and free at this
        frequency)."""
        sent = (*_SENTINEL, 0xFFFFFFFF)
        best, seen = sent, False
        subs = self._sub_dispatches(plan)
        inflight: list = []
        qi = 0
        while qi < len(subs) or inflight:
            while qi < len(subs) and len(inflight) <= self._until_lookahead:
                i0, nbatches = subs[qi]
                qi += 1
                inflight.append((i0, nbatches, self._until_sub(
                    plan, i0, nbatches, t_hi, t_lo)))
            i0, nbatches, handle = inflight.pop(0)
            found, f_idx, b_hi, b_lo, b_idx = self._until_force(
                plan, i0, nbatches, t_hi, t_lo, handle)
            trip = (int(b_hi), int(b_lo), int(b_idx))
            # Strict lex-less on (hi, lo): subs ascend, so ties keep the
            # earlier (lower-nonce) sub, matching finalize's rule. The
            # ``seen`` flag (not a sentinel compare) admits a real
            # all-ones hash, same as finalize.
            if trip != sent and (not seen or trip[:2] < best[:2]):
                best, seen = trip, True
            if int(found):
                from ..bitcoin.hash import hash_op
                h = hash_op(self.data, plan.base + int(f_idx))
                return (1, h, int(f_idx), *best)
        return (0, 0, 0, *best)

    def _platform(self) -> str:
        """Platform of the default device — where un-sharded dispatches
        are placed (the mesh model reads its mesh instead)."""
        from ..utils.config import jax_devices_robust
        return jax_devices_robust()[0].platform

    def search_until(self, lower: int, upper: int,
                     target: int) -> tuple[int, int, bool]:
        """Difficulty-target mode: (hash, nonce, found).

        Scans blocks in ascending nonce order, early-exiting on device at
        the first batch holding ``hash < target`` and returning the first
        (lowest-nonce) qualifying hash; when the whole range misses the
        target, falls back to the exact argmin (found=False).
        """
        if lower > upper:
            raise ValueError("empty range")
        if self._devloop_until_ok():
            return self._devloop_search_until(lower, upper, target)
        t_hi, t_lo = target >> 32, target & 0xFFFFFFFF
        best_hash, best_nonce, seen = MAX_U64, lower, False
        for plan in self.plan(lower, upper):
            found, f_hash, f_idx, b_hi, b_lo, b_idx = \
                self._until_block(plan, t_hi, t_lo)
            if int(found):
                return (f_hash, plan.base + int(f_idx), True)
            hi, lo, idx = int(b_hi), int(b_lo), int(b_idx)
            if (hi, lo, idx) != (*_SENTINEL, 0xFFFFFFFF):
                h = (hi << 32) | lo
                if not seen or h < best_hash:
                    best_hash, best_nonce, seen = h, plan.base + idx, True
        return best_hash, best_nonce, False
