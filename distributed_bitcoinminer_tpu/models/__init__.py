"""Model layer: the flagship nonce-search program and its host orchestration."""

from .miner_model import NonceSearcher

__all__ = ["NonceSearcher"]
