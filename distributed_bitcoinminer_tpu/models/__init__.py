"""Model layer: the flagship nonce-search program and its host orchestration."""

from .miner_model import NonceSearcher
from .sharded import MeshNonceSearcher, ShardedNonceSearcher

__all__ = ["NonceSearcher", "ShardedNonceSearcher", "MeshNonceSearcher"]
