"""Mesh-sharded flagship model: the multi-core TPU miner compute plane.

Extends :class:`NonceSearcher` so each aligned ``10^k`` block is cut into
``n_devices`` contiguous spans scanned in one ``shard_map`` dispatch with an
on-device collective merge (see ``parallel/mesh_search.py``). This is the
"one v4-8 pod joins as one very wide miner" design from the north star:
the LSP protocol above is unchanged; only the compute plane widens.
"""

from __future__ import annotations

import numpy as np

from ..parallel.mesh_search import (device_spans, make_mesh,
                                    sharded_search_span,
                                    sharded_search_span_until)
from ..utils.trace import observe_launch as _observe_launch
from .miner_model import NonceSearcher


class ShardedNonceSearcher(NonceSearcher):
    """Exact arg-min hash search sharded over a 1-D device mesh.

    ``batch`` is the per-device lane count per step; the per-block work is
    ``n_devices * batch * nbatches`` lanes.

    The two-phase ``dispatch``/``finalize`` split (the miner pipeline's
    contract, ISSUE 4) is inherited from :class:`NonceSearcher` verbatim:
    ``dispatch`` routes through this class's ``search_block`` override, so
    each handle is a replicated ``shard_map`` triple that ``finalize``'s
    single batched ``device_get`` forces exactly like the single-device
    tier — a pipelined miner overlaps whole-mesh dispatches the same way
    it overlaps single-device ones (pinned by
    tests/test_pipeline.py::test_sharded_dispatch_finalize_equivalence).
    """

    def __init__(self, data: str, batch: int = 1 << 20, mesh=None,
                 tier: str | None = None, hoist: bool | None = None):
        super().__init__(data, batch, tier=tier, hoist=hoist)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = self.mesh.devices.size

    def search_block(self, plan):
        """Pow2 sub-dispatches (see ``NonceSearcher._sub_dispatches``), each
        a ``shard_map`` over the whole mesh with per-device contiguous
        spans; returns a list of replicated (hi, lo, idx) triples."""
        out = []
        for i0, nbatches in self._sub_dispatches(plan):
            i0_d = device_spans(i0, self.n_devices, self.batch, nbatches)
            with _observe_launch(("sharded_search_span", self.tier,
                                  plan.rem, plan.k, self.batch, nbatches,
                                  self.n_devices)):
                out.append(sharded_search_span(
                    np.asarray(plan.midstate, dtype=np.uint32),
                    plan.template,
                    i0_d, plan.lo_i, plan.hi_i, plan.hoist_ops,
                    mesh=self.mesh, rem=plan.rem, k=plan.k,
                    batch=self.batch, nbatches=nbatches, tier=self.tier))
        return out

    def _sub_dispatches(self, plan, per_step=None):
        """Default ``per_step`` covers the whole mesh (one step = one lane
        batch on EVERY device) — the ONE site fixing mesh granularity for
        both the argmin and difficulty decompositions."""
        if per_step is None:
            per_step = self.batch * self.n_devices
        return super()._sub_dispatches(plan, per_step=per_step)

    def _until_sub(self, plan, i0, nbatches, t_hi, t_lo):
        """Sharded difficulty-target sub-dispatch (VERDICT r2 task 6): each
        device early-exits on its own contiguous span; the collective merge
        preserves the global first-qualifying-nonce rule (see
        ``parallel.mesh_search.sharded_search_span_until``). Unforced —
        returns a ``(tier, result)`` handle for ``_until_force`` (the
        pipelined dispatch contract of miner_model._until_block). Same
        sticky pallas->jnp until-tier degradation as the single-device
        model: a lowering failure in the newer SMEM-flag kernel must not
        take difficulty mode down."""
        i0_d = device_spans(i0, self.n_devices, self.batch, nbatches)
        tier = "jnp" if self._until_degraded else self.tier
        try:
            with _observe_launch(("sharded_search_span_until", tier,
                                  plan.rem, plan.k, self.batch, nbatches,
                                  self.n_devices)):
                return (tier, sharded_search_span_until(
                    np.asarray(plan.midstate, dtype=np.uint32),
                    plan.template,
                    i0_d, plan.lo_i, plan.hi_i, t_hi, t_lo,
                    plan.hoist_ops,
                    mesh=self.mesh, rem=plan.rem, k=plan.k,
                    batch=self.batch, nbatches=nbatches, tier=tier))
        except Exception:
            if tier != "pallas":
                raise
            self._degrade_until("sharded pallas until tier")
            return self._until_sub(plan, i0, nbatches, t_hi, t_lo)
