"""Mesh-sharded flagship model: the multi-core TPU miner compute plane.

Extends :class:`NonceSearcher` so each aligned ``10^k`` block is cut into
``n_devices`` contiguous spans scanned in one ``shard_map`` dispatch with an
on-device collective merge (see ``parallel/mesh_search.py``). This is the
"one v4-8 pod joins as one very wide miner" design from the north star:
the LSP protocol above is unchanged; only the compute plane widens.
"""

from __future__ import annotations

import numpy as np

from ..bitcoin.hash import MAX_U64
from ..ops import searchop
from ..ops.search import devloop_cap
from ..parallel.mesh_search import (device_spans, make_mesh,
                                    mesh_carry_init, mesh_devloop_span,
                                    mesh_devloop_span_until,
                                    mesh_search_span,
                                    mesh_search_span_until,
                                    mesh_until_carry_init,
                                    sharded_search_span,
                                    sharded_search_span_until)
from ..parallel.partition import device_windows, pow2_subs
from ..utils.trace import observe_launch as _observe_launch
from .miner_model import _DevloopHandle, _MET_LAUNCHES, NonceSearcher


class ShardedNonceSearcher(NonceSearcher):
    """Exact arg-min hash search sharded over a 1-D device mesh.

    ``batch`` is the per-device lane count per step; the per-block work is
    ``n_devices * batch * nbatches`` lanes.

    The two-phase ``dispatch``/``finalize`` split (the miner pipeline's
    contract, ISSUE 4) is inherited from :class:`NonceSearcher` verbatim:
    ``dispatch`` routes through this class's ``search_block`` override, so
    each handle is a replicated ``shard_map`` triple that ``finalize``'s
    single batched ``device_get`` forces exactly like the single-device
    tier — a pipelined miner overlaps whole-mesh dispatches the same way
    it overlaps single-device ones (pinned by
    tests/test_pipeline.py::test_sharded_dispatch_finalize_equivalence).
    """

    #: Inherits NonceSearcher.dispatch, where the single-device devloop
    #: launch would silently scan on ONE device of the mesh — pinned off;
    #: the mesh plane below carries its own whole-mesh devloop.
    _supports_devloop = False

    def __init__(self, data: str, batch: int = 1 << 20, mesh=None,
                 tier: str | None = None, hoist: bool | None = None):
        super().__init__(data, batch, tier=tier, hoist=hoist)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = self.mesh.devices.size

    def search_block(self, plan):
        """Pow2 sub-dispatches (see ``NonceSearcher._sub_dispatches``), each
        a ``shard_map`` over the whole mesh with per-device contiguous
        spans; returns a list of replicated (hi, lo, idx) triples."""
        out = []
        for i0, nbatches in self._sub_dispatches(plan):
            i0_d = device_spans(i0, self.n_devices, self.batch, nbatches)
            with _observe_launch(("sharded_search_span", self.tier,
                                  plan.rem, plan.k, self.batch, nbatches,
                                  self.n_devices)):
                out.append(sharded_search_span(
                    np.asarray(plan.midstate, dtype=np.uint32),
                    plan.template,
                    i0_d, plan.lo_i, plan.hi_i, plan.hoist_ops,
                    mesh=self.mesh, rem=plan.rem, k=plan.k,
                    batch=self.batch, nbatches=nbatches, tier=self.tier))
        return out

    def _sub_dispatches(self, plan, per_step=None):
        """Default ``per_step`` covers the whole mesh (one step = one lane
        batch on EVERY device) — the ONE site fixing mesh granularity for
        both the argmin and difficulty decompositions."""
        if per_step is None:
            per_step = self.batch * self.n_devices
        return super()._sub_dispatches(plan, per_step=per_step)

    def _until_sub(self, plan, i0, nbatches, t_hi, t_lo):
        """Sharded difficulty-target sub-dispatch (VERDICT r2 task 6): each
        device early-exits on its own contiguous span; the collective merge
        preserves the global first-qualifying-nonce rule (see
        ``parallel.mesh_search.sharded_search_span_until``). Unforced —
        returns a ``(tier, result)`` handle for ``_until_force`` (the
        pipelined dispatch contract of miner_model._until_block). Same
        sticky pallas->jnp until-tier degradation as the single-device
        model: a lowering failure in the newer SMEM-flag kernel must not
        take difficulty mode down."""
        i0_d = device_spans(i0, self.n_devices, self.batch, nbatches)
        tier = "jnp" if self._until_degraded else self.tier
        try:
            with _observe_launch(("sharded_search_span_until", tier,
                                  plan.rem, plan.k, self.batch, nbatches,
                                  self.n_devices)):
                return (tier, sharded_search_span_until(
                    np.asarray(plan.midstate, dtype=np.uint32),
                    plan.template,
                    i0_d, plan.lo_i, plan.hi_i, t_hi, t_lo,
                    plan.hoist_ops,
                    mesh=self.mesh, rem=plan.rem, k=plan.k,
                    batch=self.batch, nbatches=nbatches, tier=tier))
        except Exception:
            if tier != "pallas":
                raise
            self._degrade_until("sharded pallas until tier")
            return self._until_sub(plan, i0, nbatches, t_hi, t_lo)


class MeshNonceSearcher(ShardedNonceSearcher):
    """The ISSUE 14 mesh plane: one whole-mesh span dispatch, ONE
    ``(hash, nonce)`` pair crossing the host.

    Differences from :class:`ShardedNonceSearcher` (which it replaces
    as the multi-device default under ``DBM_MESH=1``):

    - **Per-core stripe windows**: each 10^k block's valid lane window
      is cut into ``n_devices`` contiguous EVEN windows (the scheduler
      stripe-plan shape applied inside the miner,
      ``parallel.partition.device_windows``) instead of fixed
      batch-aligned device spans masked by a global window — so every
      core's VALID work stays balanced to within one lane batch, where
      a tail-of-block window previously left leading devices hashing
      fully masked lanes.
    - **Carry-chained launches**: the running best rides ON DEVICE as a
      replicated carry vector threaded through every pow2 sub and every
      block (``parallel.mesh_search.mesh_search_span``); the on-device
      lexicographic min-hash all-reduce folds each launch's mesh-merged
      candidate — with the block base already combined into a GLOBAL
      64-bit nonce — into it. ``finalize`` fetches the final carry ONCE:
      exactly one (hash, nonce) result crosses the host per span,
      however many blocks/subs the span decomposes into (today's tier
      fetches one partial triple per sub).
    - **Operand placement by rule table**: every launch's operands
      travel as one named pytree placed by
      ``parallel.partition.MESH_PARTITION_RULES``.

    The two-phase ``dispatch``/``finalize`` contract is unchanged
    (``dispatch`` returns the final carry handle with every launch
    enqueued asynchronously; ``finalize`` forces it), so the miner
    pipeline overlaps whole-mesh spans exactly like before. The
    coalescer's ``dispatch_batch`` is inherited: coalesced mice ride
    the single-device segmin path (correct, narrower — mice on a pod
    are not what the pod is for).
    """

    #: Re-enabled (the sharded parent pins it off): this model's own
    #: dispatch/search_until own the devloop shape — one whole-mesh
    #: launch per 10^k block over the same stripe windows.
    _supports_devloop = True

    def _mesh_devloop_block(self, plan, carry, t_hi=None, t_lo=None,
                            tier: str | None = None):
        """ONE whole-mesh devloop launch covering the block: every
        device walks its stripe window's sub-steps inside the kernel
        (vs one launch per pow2 sub in :meth:`_mesh_block`). Returns
        ``(new_carry, steps)`` — steps is the in-kernel sub count the
        trace plane reports."""
        tier = tier if tier is not None else self.tier
        i0_d, lo_d, hi_d, steps = device_windows(
            plan.lo_i, plan.hi_i, self.n_devices, self.batch)
        cap = devloop_cap(steps)
        ops = {"carry": carry,
               "midstate": np.asarray(plan.midstate, dtype=np.uint32),
               "template": plan.template,
               "i0_d": i0_d, "lo_d": lo_d, "hi_d": hi_d,
               "nsub": np.int32(steps),
               "base_hi": np.uint32(plan.base >> 32),
               "base_lo": np.uint32(plan.base & 0xFFFFFFFF)}
        if plan.hoist_ops is not None:
            ops["hoist"] = plan.hoist_ops
        _MET_LAUNCHES.inc()
        if t_hi is not None:
            ops["target_hi"] = t_hi
            ops["target_lo"] = t_lo
            with _observe_launch(("mesh_devloop_until", tier, plan.rem,
                                  plan.k, self.batch, cap,
                                  self.n_devices)):
                carry = mesh_devloop_span_until(
                    ops, mesh=self.mesh, rem=plan.rem, k=plan.k,
                    batch=self.batch, cap=cap,
                    tier=tier)  # dbmlint: ok[jit-static] two-valued jnp|pallas set (ctor-validated) + devloop_cap pow2
        else:
            with _observe_launch(("mesh_devloop_span", tier, plan.rem,
                                  plan.k, self.batch, cap,
                                  self.n_devices)):
                carry = mesh_devloop_span(
                    ops, mesh=self.mesh, rem=plan.rem, k=plan.k,
                    batch=self.batch, cap=cap,
                    tier=tier)  # dbmlint: ok[jit-static] two-valued jnp|pallas set (ctor-validated) + devloop_cap pow2
        return carry, steps

    def _mesh_block(self, plan, carry, t_hi=None, t_lo=None,
                    tier: str | None = None):
        """Chain one block's pow2 sub-launches onto ``carry`` over the
        per-core stripe windows; returns the new carry (unforced)."""
        tier = tier if tier is not None else self.tier
        i0_d, lo_d, hi_d, steps = device_windows(
            plan.lo_i, plan.hi_i, self.n_devices, self.batch)
        until = t_hi is not None
        base = {"base_hi": np.uint32(plan.base >> 32),
                "base_lo": np.uint32(plan.base & 0xFFFFFFFF)}
        for off, p in pow2_subs(steps):
            _MET_LAUNCHES.inc()
            ops = {"carry": carry,
                   "midstate": np.asarray(plan.midstate, dtype=np.uint32),
                   "template": plan.template,
                   "i0_d": i0_d + np.uint32(off * self.batch),
                   "lo_d": lo_d, "hi_d": hi_d, **base}
            if plan.hoist_ops is not None:
                ops["hoist"] = plan.hoist_ops
            if until:
                ops["target_hi"] = t_hi
                ops["target_lo"] = t_lo
                with _observe_launch(("mesh_search_span_until", tier,
                                      plan.rem, plan.k, self.batch, p,
                                      self.n_devices)):
                    carry = mesh_search_span_until(
                        ops, mesh=self.mesh, rem=plan.rem, k=plan.k,
                        batch=self.batch, nbatches=p,
                        tier=tier)  # dbmlint: ok[jit-static] two-valued jnp|pallas set (ctor-validated), resolved per block for the sticky until degradation
            else:
                with _observe_launch(("mesh_search_span", tier,
                                      plan.rem, plan.k, self.batch, p,
                                      self.n_devices)):
                    carry = mesh_search_span(
                        ops, mesh=self.mesh, rem=plan.rem, k=plan.k,
                        batch=self.batch, nbatches=p,
                        tier=tier)  # dbmlint: ok[jit-static] two-valued jnp|pallas set (ctor-validated), resolved per block for the sticky until degradation
        return carry

    def dispatch(self, lower: int, upper: int):
        """Enqueue the whole span as one carry chain; the handle is the
        final carry (a single replicated device value). Under the
        devloop (ISSUE 19) each block is ONE whole-mesh launch instead
        of a pow2-sub chain; the per-span host cost — one 20-byte carry
        fetch — is unchanged, only the launch count drops."""
        if lower > upper:
            raise ValueError("empty range")
        self.last_dispatch_subs = None
        if self._devloop_ok():
            lanes = upper - lower + 1
            if self._devloop_eligible(lanes):
                return self._mesh_devloop_dispatch(lower, upper, lanes)
        carry = mesh_carry_init()
        for plan in self.plan(lower, upper):
            carry = self._mesh_block(plan, carry)
        return carry

    def _mesh_devloop_dispatch(self, lower: int, upper: int,
                               lanes: int) -> _DevloopHandle:
        """Whole-mesh devloop span: one launch per block, the searchop
        carry (the SAME 5-word layout the stock mesh chain threads)
        riding replicated across launches."""
        import time

        t0 = time.monotonic()
        carry = mesh_carry_init()
        subs = 0
        for plan in self.plan(lower, upper):
            carry, steps = self._mesh_devloop_block(plan, carry)
            subs += steps
        self.last_dispatch_subs = subs
        return _DevloopHandle(carry, subs, lanes,
                              4 * searchop.CARRY_WORDS, t0)

    def finalize(self, handle, lower: int) -> tuple[int, int]:
        """ONE host fetch per span: the 5-word carry. The ``seen`` word
        mirrors finalize's seen-flag (a real all-ones hash is kept; an
        all-sentinel span — impossible for a non-empty range — answers
        like an empty scan)."""
        import jax

        if isinstance(handle, _DevloopHandle):
            return self._devloop_finalize(handle, lower)
        v = jax.device_get(handle)
        if not int(v[4]):
            return (MAX_U64, lower)
        return ((int(v[0]) << 32) | int(v[1]),
                (int(v[2]) << 32) | int(v[3]))

    def search(self, lower: int, upper: int) -> tuple[int, int]:
        return self.finalize(self.dispatch(lower, upper), lower)

    def search_until(self, lower: int, upper: int,
                     target: int) -> tuple[int, int, bool]:
        """Difficulty mode on the carry chain: one fetch per BLOCK (the
        inter-block early exit — a hit skips every later block's scan
        entirely), with the per-device in-kernel early exit inside each
        launch. Within a block all subs chain before the fetch, so the
        first-hit rule rides the carry's min-qualifying-nonce merge
        rather than fetch order. Same sticky pallas->jnp degradation as
        the sharded model: a failing Mosaic until kernel recomputes the
        block on the jnp tier from the block-start carry (idempotent
        re-scan)."""
        import jax

        if lower > upper:
            raise ValueError("empty range")
        if self._devloop_until_ok():
            return self._mesh_devloop_search_until(lower, upper, target)
        t_hi = np.uint32(target >> 32)
        t_lo = np.uint32(target & 0xFFFFFFFF)
        carry = mesh_until_carry_init()
        v = None
        for plan in self.plan(lower, upper):
            block_start = carry
            tier = "jnp" if self._until_degraded else self.tier
            try:
                carry = self._mesh_block(plan, carry, t_hi, t_lo,
                                         tier=tier)
                v = jax.device_get(carry)
            except Exception:
                if tier != "pallas":
                    raise
                self._degrade_until("mesh pallas until tier")
                carry = self._mesh_block(plan, block_start, t_hi, t_lo,
                                         tier="jnp")
                v = jax.device_get(carry)
            if int(v[0]):
                from ..bitcoin.hash import hash_op
                f_nonce = (int(v[1]) << 32) | int(v[2])
                return (hash_op(self.data, f_nonce), f_nonce, True)
        if v is not None and int(v[7]):
            return ((int(v[3]) << 32) | int(v[4]),
                    (int(v[5]) << 32) | int(v[6]), False)
        return (MAX_U64, lower, False)

    def _mesh_devloop_until_chain(self, plans, t_hi, t_lo,
                                  tier: str) -> np.ndarray:
        """Chain every block's devloop difficulty launch and fetch the
        8-word carry ONCE per span (vs once per block on the stock
        chain — the devloop's found-carry pass-through makes the
        per-block fetch unnecessary: launches after a hit fall straight
        through on device)."""
        import jax

        carry = mesh_until_carry_init()
        subs = 0
        for plan in plans:
            carry, steps = self._mesh_devloop_block(plan, carry, t_hi,
                                                    t_lo, tier=tier)
            subs += steps
        self.last_dispatch_subs = subs
        return jax.device_get(carry)

    def _mesh_devloop_search_until(self, lower: int, upper: int,
                                   target: int) -> tuple[int, int, bool]:
        """Difficulty mode on the devloop chain: one fetch per span.
        A pallas fault anywhere in the chain latches the sticky until
        degradation and reruns the whole span on the jnp tier (the scan
        is idempotent, same recovery rule as the stock per-block
        path)."""
        t_hi = np.uint32(target >> 32)
        t_lo = np.uint32(target & 0xFFFFFFFF)
        plans = list(self.plan(lower, upper))
        tier = "jnp" if self._until_degraded else self.tier
        try:
            words = self._mesh_devloop_until_chain(plans, t_hi, t_lo,
                                                   tier)
        except Exception:
            if tier != "pallas":
                raise
            self._degrade_until("mesh pallas devloop until tier")
            words = self._mesh_devloop_until_chain(plans, t_hi, t_lo,
                                                   "jnp")
        found, f_nonce, best_hash, best_nonce = searchop.decode_until(
            words, lower)
        if found:
            from ..bitcoin.hash import hash_op
            return (hash_op(self.data, f_nonce), f_nonce, True)
        return (best_hash, best_nonce, False)
