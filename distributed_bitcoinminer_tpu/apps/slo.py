"""Declarative SLOs + multi-window burn-rate alerts over the rollup.

The rollup plane (``apps/rollup.py``, ISSUE 18) gives the cluster ONE
snapshot; this module gives it an OPINION: a small set of declarative
service-level objectives evaluated over that snapshot, each with an
error budget and multi-window burn-rate alerting (the SRE-workbook
scheme: page only when the budget is burning fast over BOTH a short and
a long window — the short window gates on sustained current pain, the
long window keeps one transient blip from paging).

Objectives (defaults mirror the loadharness gates):

- **reply_availability** — fraction of decided requests answered rather
  than shed: ``results_sent / (results_sent + qos_shed)``; target
  ``DBM_SLO_AVAIL`` (default 0.99, error budget 1%).
- **queue_wait_p99** — fraction of admitted requests whose queue wait
  exceeded ``DBM_SLO_P99_S`` seconds (default 60, the mini-load leg's
  ``--assert-p99 60`` bar), read from the merged cumulative-``le``
  ``sched.queue_wait_s`` buckets; the budget is 1% by the definition of
  a p99 objective.
- **shed_rate** — fraction of admission decisions shed:
  ``qos_shed / (qos_grants + qos_shed)`` at most ``DBM_SLO_SHED``
  (default 0.25 — the loadharness storm gates treat ≤25% shed under
  deliberate overload as healthy back-pressure).

All three are ratios of MONOTONIC counters (histogram buckets are
cumulative too), so windowed error fractions are two-point deltas — the
tracker keeps a small ring of (t, cumulative) samples, no per-request
state. Burn rate is ``windowed_error_fraction / budget``; an alert
fires on the transition into "both windows burning ≥ DBM_SLO_BURN"
(default 4.0 — budget exhausted 4x faster than allowed), names the
objective AND the worst-offending process (highest per-process error
fraction from the per-proc rows), and is recorded as a flight-recorder
event so the crash/alarm artifact stream carries it.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils._env import float_env as _float_env
from .rollup import hist_quantile

__all__ = ["Objective", "default_objectives", "SloTracker"]


class Objective:
    """One SLO: a name, an error budget, and how to read (bad, total)
    cumulative pairs out of a rollup document / per-proc row."""

    def __init__(self, name: str, budget: float,
                 cluster_fn: Callable[[dict], Tuple[float, float]],
                 proc_fn: Callable[[dict], Optional[Tuple[float, float]]],
                 describe: str = ""):
        self.name = name
        self.budget = max(1e-9, float(budget))
        self._cluster_fn = cluster_fn
        self._proc_fn = proc_fn
        self.describe = describe

    def cumulative(self, doc: dict) -> Tuple[float, float]:
        """(bad, total), both monotonic, from a rollup document."""
        try:
            bad, total = self._cluster_fn(doc)
            return max(0.0, float(bad)), max(0.0, float(total))
        except Exception:  # noqa: BLE001 — a torn doc must not kill it
            return 0.0, 0.0

    def proc_error_frac(self, proc_entry: dict) -> Optional[float]:
        """This process's lifetime error fraction (offender ranking)."""
        try:
            pair = self._proc_fn(proc_entry)
        except Exception:  # noqa: BLE001
            return None
        if pair is None:
            return None
        bad, total = pair
        return (bad / total) if total > 0 else None


def _counter_family(doc: dict, family: str) -> float:
    pref = family + "{"
    section = (doc.get("cluster") or {}).get("counters") or {}
    return float(sum(v for k, v in section.items()
                     if k == family or k.startswith(pref)))


def _avail_cluster(doc: dict) -> Tuple[float, float]:
    shed = _counter_family(doc, "sched.qos_shed")
    sent = _counter_family(doc, "sched.results_sent")
    return shed, shed + sent


def _avail_proc(p: dict) -> Optional[Tuple[float, float]]:
    d = p.get("detail") or {}
    if "results" not in d and "shed" not in d:
        return None
    shed = float(d.get("shed", 0))
    return shed, shed + float(d.get("results", 0))


def _shed_cluster(doc: dict) -> Tuple[float, float]:
    shed = _counter_family(doc, "sched.qos_shed")
    grants = _counter_family(doc, "sched.qos_grants")
    return shed, shed + grants


def _p99_threshold_pair(hist: Optional[dict],
                        limit_s: float) -> Tuple[float, float]:
    if not hist or not hist.get("count"):
        return 0.0, 0.0
    total = float(hist["count"])
    good = 0.0
    for bound, cum in zip(hist.get("le") or [], hist.get("counts") or []):
        if bound <= limit_s:
            good = float(cum)
        else:
            break
    return total - good, total


def _wait_cluster(doc: dict, limit_s: float) -> Tuple[float, float]:
    hist = ((doc.get("cluster") or {}).get("histograms") or {}) \
        .get("sched.queue_wait_s")
    return _p99_threshold_pair(hist, limit_s)


def _wait_proc(p: dict, limit_s: float) -> Optional[Tuple[float, float]]:
    # Per-proc rows carry the p99 headline, not full buckets: rank by
    # whether the process's own p99 bound clears the limit.
    d = p.get("detail") or {}
    p99 = d.get("queue_wait_p99_s")
    if p99 is None:
        return None
    return (1.0, 1.0) if (p99 > limit_s) else (0.0, 1.0)


def default_objectives() -> List[Objective]:
    """The built-in objective set, targets from ``DBM_SLO_*`` knobs."""
    avail = min(1.0 - 1e-9, max(0.0, _float_env("DBM_SLO_AVAIL", 0.99)))
    p99_s = max(1e-3, _float_env("DBM_SLO_P99_S", 60.0))
    shed = max(1e-9, min(1.0, _float_env("DBM_SLO_SHED", 0.25)))
    return [
        Objective("reply_availability", 1.0 - avail,
                  _avail_cluster, _avail_proc,
                  f"replies answered vs shed >= {avail:g}"),
        Objective("queue_wait_p99", 0.01,
                  lambda doc: _wait_cluster(doc, p99_s),
                  lambda p: _wait_proc(p, p99_s),
                  f"queue wait p99 <= {p99_s:g}s"),
        Objective("shed_rate", shed,
                  _shed_cluster, _avail_proc,
                  f"admission shed rate <= {shed:g}"),
    ]


class SloTracker:
    """Multi-window burn-rate tracking over successive rollup documents.

    Feed every rollup refresh to :meth:`observe`; it returns the alerts
    that FIRED on that observation (transitions into burning) and keeps
    :meth:`status` current for the console's budget bars. Long window =
    ``DBM_SLO_WINDOW_S`` (default 300s), short window = long/12 (the
    5m:1h ratio of the classic fast-burn pair), alert threshold =
    ``DBM_SLO_BURN`` (default 4.0x budget rate) on BOTH windows.
    """

    def __init__(self, objectives: Optional[List[Objective]] = None,
                 window_s: Optional[float] = None,
                 burn: Optional[float] = None, recorder=None):
        self.objectives = (objectives if objectives is not None
                           else default_objectives())
        self.window_s = max(1.0, window_s if window_s is not None
                            else _float_env("DBM_SLO_WINDOW_S", 300.0))
        self.short_s = max(0.5, self.window_s / 12.0)
        self.burn = max(1.0, burn if burn is not None
                        else _float_env("DBM_SLO_BURN", 4.0))
        self._recorder = recorder
        self._hist: deque = deque()       # (t, {name: (bad, total)})
        self._burning: Dict[str, bool] = {}
        self._status: List[dict] = []

    # ------------------------------------------------------------ windows

    def _window_frac(self, name: str, now: float,
                     span_s: float) -> Optional[float]:
        """Error fraction of the newest sample vs the oldest one inside
        ``span_s`` (None until the window has two samples or any
        traffic). Cumulative counters make this a pure two-point delta."""
        newest = self._hist[-1][1].get(name) if self._hist else None
        anchor = None
        for t, sample in self._hist:
            if now - t <= span_s + 1e-9:
                anchor = sample.get(name)
                break
        if newest is None or anchor is None or anchor is newest:
            return None
        d_bad = newest[0] - anchor[0]
        d_total = newest[1] - anchor[1]
        if d_total <= 0:
            return None
        return max(0.0, d_bad) / d_total

    def _worst_proc(self, obj: Objective, doc: dict) -> Optional[str]:
        worst, worst_frac = None, -1.0
        for p in doc.get("procs") or []:
            if p.get("status") == "fenced":
                continue
            frac = obj.proc_error_frac(p)
            if frac is not None and frac > worst_frac:
                worst, worst_frac = p.get("proc"), frac
        return worst

    # ------------------------------------------------------------ observe

    def observe(self, doc: dict,
                now: Optional[float] = None) -> List[dict]:
        """Fold in one rollup document; returns NEWLY-firing alerts."""
        if now is None:
            now = time.time()
        sample = {o.name: o.cumulative(doc) for o in self.objectives}
        self._hist.append((now, sample))
        while self._hist and now - self._hist[0][0] > self.window_s * 1.25:
            self._hist.popleft()
        alerts: List[dict] = []
        status: List[dict] = []
        for obj in self.objectives:
            f_short = self._window_frac(obj.name, now, self.short_s)
            f_long = self._window_frac(obj.name, now, self.window_s)
            b_short = (f_short / obj.budget) if f_short is not None \
                else None
            b_long = (f_long / obj.budget) if f_long is not None else None
            burning = (b_short is not None and b_long is not None
                       and b_short >= self.burn and b_long >= self.burn)
            entry = {"objective": obj.name, "describe": obj.describe,
                     "budget": obj.budget,
                     "error_frac_short": f_short,
                     "error_frac_long": f_long,
                     "burn_short": round(b_short, 3)
                     if b_short is not None else None,
                     "burn_long": round(b_long, 3)
                     if b_long is not None else None,
                     "burning": burning}
            if burning:
                entry["worst"] = self._worst_proc(obj, doc)
                if not self._burning.get(obj.name):
                    alert = dict(entry, event="slo_burn",
                                 window_s=self.window_s,
                                 short_s=self.short_s)
                    alerts.append(alert)
                    self._record(alert)
            self._burning[obj.name] = burning
            status.append(entry)
        self._status = status
        return alerts

    def _record(self, alert: dict) -> None:
        rec = self._recorder
        if rec is None:
            from ..utils.trace import flight_recorder
            rec = flight_recorder()
        try:
            rec.record("slo_burn", objective=alert["objective"],
                       worst=alert.get("worst"),
                       burn_short=alert.get("burn_short"),
                       burn_long=alert.get("burn_long"))
        except Exception:  # noqa: BLE001 — alerting must not crash hosts
            pass

    def status(self) -> List[dict]:
        """Latest per-objective budget state (console budget bars)."""
        return [dict(e) for e in self._status]
