"""O(10k)-tenant control-plane load harness (ISSUE 11).

The bench's compute probes measure the KERNELS; this harness measures
the CONTROL PLANE — what one scheduler process costs per request as
the tenant count grows, with compute removed from the equation:

- Transport is the socket-free :mod:`~..lspnet.detnet` shim in
  non-recording mode (``DetServer(record=False)``): every message is a
  queue put, so 10k conns cost 10k× one message, not sockets, epochs,
  or capture lists.
- Miners are INSTANT actors: each Request is answered immediately with
  a cheap deterministic fake hash (the scheduler never verifies hashes;
  merge/lease/accounting mechanics are identical), plus an honest
  miner-side Span (measured queue/force wall times of the actor) so the
  per-phase trace medians the probes embed stay populated.
- Tenants are one DetChannel each, storming ``requests_per_tenant``
  small unique-keyed requests at t0 and reading until replied or shed
  (a shed closes the conn — the client observes the LSP death exactly
  like production ``submit_with_retry`` would).

What a leg reports: completed/shed counts, wall makespan, admitted
throughput (completed / makespan), reply-latency p50/p99, CPU seconds
per completed request (``time.process_time`` over the leg — the
"per-request CPU cost" acceptance number), and the scheduler-side
trace summary (sampled traces only, by design — the harness runs
traced at ``DBM_TRACE_SAMPLE``-style rates without tracing being the
bottleneck).

Replica legs construct an :class:`~.replicas.ReplicaSet`; the QUEUE
CAPACITY IS SPLIT across replicas (``max_queued / n`` each) so 1-vs-N
comparisons run at EQUAL total admission capacity — equal shed rate by
construction — and the throughput difference is the sharding win, not
a bigger buffer.

``scripts/loadharness.py`` is the CLI (and the tier-1 mini-load leg);
``bench.py detail.load`` sweeps the tenant curve and checks the
result in as the BENCH artifact.
"""

from __future__ import annotations

import asyncio
import time
from statistics import median
from typing import Optional

from ..bitcoin.message import Message, MsgType, new_join, new_request, \
    new_result
from ..lsp.errors import LspError
from ..lspnet.detnet import DetServer
from ..utils.config import CacheParams, LeaseParams, QosParams
from ..utils.trace import SPAN_PHASES

__all__ = ["run_load", "load_curve"]

#: A 64-bit odd multiplier (splitmix64 finalizer constant): the fake
#: miner's answer must be a deterministic function of the chunk so
#: speculative re-issues merge idempotently, and cheap (no SHA-256 —
#: compute is exactly what this harness removes).
_MIX = 0xBF58476D1CE4E5B9
_MASK = (1 << 64) - 1


def _fake_hash(data: str, lower: int) -> int:
    return (hash(data) * _MIX + lower * 0x9E3779B97F4A7C15) & _MASK


async def _fake_miner(chan, trace_spans: bool) -> None:
    """Instant miner actor: JOIN, then answer every Request with the
    fake hash — attaching a measured (honest, if tiny) span when
    ``trace_spans``."""
    chan.write(new_join().to_json())
    while True:
        try:
            payload = await chan.read()
        except LspError:
            return
        arrived = time.monotonic()
        msg = Message.from_json(payload)
        if msg.type != MsgType.REQUEST:
            continue
        h = _fake_hash(msg.data, msg.lower)
        span = None
        if trace_spans:
            done = time.monotonic()
            span = {"queue_s": 0.0, "dispatch_s": 0.0, "wait_s": 0.0,
                    "force_s": round(done - arrived, 9), "gap_s": 0.0}
        try:
            chan.write(new_result(h, msg.lower, msg.target,
                                  span=span).to_json())
        except LspError:
            return


async def _tenant(chan, data: str, count: int, nonces: int,
                  latencies: list, sheds: list) -> None:
    """One tenant: submit ``count`` unique requests back-to-back at
    storm start, then read replies; a dead conn = shed."""
    stamps = []
    try:
        for i in range(count):
            stamps.append(time.monotonic())
            chan.write(new_request(f"{data}#{i}", 0, nonces - 1).to_json())
        got = 0
        while got < count:
            payload = await chan.read()
            msg = Message.from_json(payload)
            if msg.type == MsgType.RESULT:
                latencies.append(time.monotonic() - stamps[got])
                got += 1
    except LspError:
        sheds.append(len(stamps))


def run_load(tenants: int = 1000, replicas: int = 1, miners: int = 4,
             *, requests_per_tenant: int = 1, req_nonces: int = 256,
             max_queued: int = 4096, recv_batch: Optional[int] = None,
             trace_sample: Optional[float] = None,
             qos_lazy: Optional[bool] = None,
             timeout_s: float = 300.0) -> dict:
    """One storm leg; returns the leg's measurement dict.

    ``qos_lazy`` pins the lazy-DRR walk knob for A/B legs (ISSUE 12;
    None = the default, lazy on)."""

    async def leg() -> dict:
        from .replicas import ReplicaSet
        from .scheduler import Scheduler
        server = DetServer(record=False)
        qos_kw = {} if qos_lazy is None else {"lazy": qos_lazy}
        qos = QosParams(enabled=True, max_queued=max(
            1, max_queued // max(1, replicas)), **qos_kw)
        lease = LeaseParams(grace_s=120.0, floor_s=60.0,
                            queue_alarm_s=0.0)
        kw = dict(lease=lease, cache=CacheParams(enabled=False), qos=qos,
                  recv_batch=recv_batch, trace_sample=trace_sample)
        if replicas > 1:
            coord = ReplicaSet(server, replicas, **kw)
        else:
            coord = Scheduler(server, **kw)
        coord_task = asyncio.create_task(coord.run())
        miner_tasks = [asyncio.create_task(
            _fake_miner(server.connect(), trace_spans=True))
            for _ in range(miners)]
        # Let the JOINs land before the storm.
        for _ in range(4):
            await asyncio.sleep(0)
        latencies: list = []
        sheds: list = []
        cpu0 = time.process_time()
        t0 = time.monotonic()
        tenant_tasks = [asyncio.create_task(
            _tenant(server.connect(), f"t{t}", requests_per_tenant,
                    req_nonces, latencies, sheds))
            for t in range(tenants)]
        try:
            await asyncio.wait_for(asyncio.gather(*tenant_tasks),
                                   timeout_s)
            timed_out = False
        except asyncio.TimeoutError:
            timed_out = True
        makespan = time.monotonic() - t0
        cpu_s = time.process_time() - cpu0
        for task in tenant_tasks + miner_tasks + [coord_task]:
            task.cancel()
        total = tenants * requests_per_tenant
        completed = len(latencies)
        latencies.sort()

        def pct(q: float):
            if not latencies:
                return None
            return round(latencies[min(len(latencies) - 1,
                                       int(q * len(latencies)))], 4)

        out = {
            "tenants": tenants,
            "replicas": replicas,
            "miners": miners,
            "requests": total,
            "completed": completed,
            "shed_tenants": len(sheds),
            "shed_rate": round(1 - completed / total, 4) if total else 0.0,
            "makespan_s": round(makespan, 3),
            "admitted_per_s": round(completed / makespan, 1)
            if makespan > 0 else None,
            "p50_s": pct(0.50),
            "p99_s": pct(0.99),
            "cpu_s_per_request": round(cpu_s / completed, 6)
            if completed else None,
            "trace": _trace_summary(coord, replicas),
        }
        if timed_out:
            out["timed_out"] = True
        return out

    return asyncio.run(leg())


def _trace_summary(coord, replicas: int) -> dict:
    """Per-phase medians over the (sampled) traces of a finished leg —
    the same shape as ``bench._Cluster.trace_summary`` so ``detail.load``
    artifacts decompose like the other storm probes'."""
    sched_queue, phases = [], {}
    traces = coord.traces.items()
    for _key, t in traces:
        events = t.to_dict()["events"]
        enq = next((e for e in events if e["event"] == "enqueue"), None)
        disp = next((e for e in events if e["event"] == "dispatch"), None)
        if enq is not None and disp is not None:
            sched_queue.append(disp["t"] - enq["t"])
        for e in events:
            if e["event"] != "miner_span":
                continue
            for ph in SPAN_PHASES:
                v = e.get(ph)
                if isinstance(v, (int, float)):
                    phases.setdefault(ph, []).append(float(v))
    out = {"sampled_traces": len(traces)}
    if sched_queue:
        out["sched_queue_s_p50"] = round(median(sched_queue), 6)
    for ph, xs in sorted(phases.items()):
        out[f"miner_{ph}_p50"] = round(median(xs), 6)
    return out


def _children_cpu_s(pids) -> float:
    """Summed utime+stime of child processes (``/proc/<pid>/stat``) —
    the procs leg's scheduler CPU lives in other processes, so the
    harness's own ``process_time`` would measure nothing."""
    import os
    tick = os.sysconf("SC_CLK_TCK")
    total = 0.0
    for pid in pids:
        try:
            with open(f"/proc/{pid}/stat", encoding="ascii") as fh:
                parts = fh.read().rsplit(") ", 1)[-1].split()
            total += (int(parts[11]) + int(parts[12])) / tick
        except (OSError, ValueError, IndexError):
            continue
    return total


def run_load_procs(tenants: int = 200, replicas: int = 2,
                   miners: int = 4, *, requests_per_tenant: int = 1,
                   req_nonces: int = 256,
                   timeout_s: float = 180.0) -> dict:
    """Multi-process topology leg (ISSUE 12, ``loadharness --procs``):
    the REAL process topology — router + one OS process per replica on
    its own LSP socket + fake (instant-compute) miner agents — driven
    by ring-resolving tenants over real localhost UDP, so ``detail.load``
    can compare in-process vs multi-process replicas at equal tenant
    count. The shape of the returned dict matches :func:`run_load`
    (``cpu_s_per_request`` sums the CHILD processes' CPU from /proc)."""
    import shutil
    import tempfile

    async def leg() -> dict:
        from ..lsp.client import new_async_client
        from ..lsp.params import Params
        from .procs import ProcCluster, resolve_owner
        statedir = tempfile.mkdtemp(prefix="dbm_loadprocs_")
        env = {"DBM_HEALTH_BEAT_S": "0.25", "DBM_HEALTH_MISS_K": "3",
               "DBM_EPOCH_MILLIS": "500", "DBM_EPOCH_LIMIT": "8",
               "DBM_TRACE_SAMPLE": "0.01"}
        params = Params(epoch_limit=8, epoch_millis=500, window_size=32,
                        max_backoff_interval=2)
        cluster = ProcCluster(statedir, replicas=replicas, miners=miners,
                              env=env, fake_miners=True)
        cluster.start()
        latencies: list = []
        sheds: list = []

        async def tenant(name: str, count: int) -> None:
            owner = resolve_owner(statedir, name)
            if owner is None:
                sheds.append(count)
                return
            try:
                client = await new_async_client(owner[1], params)
            except LspError:
                sheds.append(count)
                return
            stamps = []
            try:
                for i in range(count):
                    stamps.append(time.monotonic())
                    client.write(new_request(f"{name}#{i}", 0,
                                             req_nonces - 1).to_json())
                got = 0
                while got < count:
                    msg = Message.from_json(await client.read())
                    if msg.type == MsgType.RESULT:
                        latencies.append(time.monotonic() - stamps[got])
                        got += 1
            except LspError:
                sheds.append(len(stamps))
            finally:
                await client.close()

        try:
            await cluster.wait_live(replicas, timeout_s=30.0,
                                    miners=miners)
            pids = [p.pid for p in cluster.procs.values()]
            cpu0 = _children_cpu_s(pids)
            t0 = time.monotonic()
            tasks = [asyncio.create_task(
                tenant(f"t{t}", requests_per_tenant))
                for t in range(tenants)]
            try:
                await asyncio.wait_for(asyncio.gather(*tasks), timeout_s)
                timed_out = False
            except asyncio.TimeoutError:
                timed_out = True
            makespan = time.monotonic() - t0
            cpu_s = _children_cpu_s(pids) - cpu0
            for task in tasks:
                task.cancel()
        finally:
            cluster.close()
            shutil.rmtree(statedir, ignore_errors=True)
        total = tenants * requests_per_tenant
        completed = len(latencies)
        latencies.sort()

        def pct(q: float):
            if not latencies:
                return None
            return round(latencies[min(len(latencies) - 1,
                                       int(q * len(latencies)))], 4)

        out = {
            "tenants": tenants, "replicas": replicas, "miners": miners,
            "topology": "procs",
            "requests": total, "completed": completed,
            "shed_tenants": len(sheds),
            "shed_rate": round(1 - completed / total, 4) if total
            else 0.0,
            "makespan_s": round(makespan, 3),
            "admitted_per_s": round(completed / makespan, 1)
            if makespan > 0 else None,
            "p50_s": pct(0.50), "p99_s": pct(0.99),
            "cpu_s_per_request": round(cpu_s / completed, 6)
            if completed else None,
            "trace": {"sampled_traces": 0},
        }
        if timed_out:
            out["timed_out"] = True
        return out

    return asyncio.run(leg())


def load_curve(points, replica_counts=(1, 4), rounds: int = 2,
               **kw) -> dict:
    """The BENCH load curve: for each tenant count in ``points`` and
    each replica count, run ``rounds`` interleaved order-swapped legs
    (the repo's storm-probe noise discipline) and report medians.

    Returns ``{"points": [{"tenants": N, "r<k>": {...medians...}}, ...],
    "samples": [...]}``.
    """
    samples = []
    curve = []
    for tenants in points:
        entry: dict = {"tenants": tenants}
        per_rep: dict = {n: [] for n in replica_counts}
        for rnd in range(max(1, rounds)):
            order = (list(replica_counts) if rnd % 2 == 0
                     else list(reversed(replica_counts)))
            for n in order:
                leg = run_load(tenants=tenants, replicas=n, **kw)
                per_rep[n].append(leg)
                samples.append(leg)
        for n, legs in per_rep.items():
            med = {}
            for key in ("makespan_s", "admitted_per_s", "p50_s", "p99_s",
                        "cpu_s_per_request", "shed_rate"):
                vals = [leg[key] for leg in legs
                        if leg.get(key) is not None]
                med[key] = round(median(vals), 6) if vals else None
            med["completed"] = legs[0]["completed"]
            med["trace"] = legs[-1]["trace"]
            entry[f"r{n}"] = med
        curve.append(entry)
    return {"points": curve, "samples": samples}
