"""O(10k)-tenant control-plane load harness (ISSUE 11).

The bench's compute probes measure the KERNELS; this harness measures
the CONTROL PLANE — what one scheduler process costs per request as
the tenant count grows, with compute removed from the equation:

- Transport is the socket-free :mod:`~..lspnet.detnet` shim in
  non-recording mode (``DetServer(record=False)``): every message is a
  queue put, so 10k conns cost 10k× one message, not sockets, epochs,
  or capture lists.
- Miners are INSTANT actors: each Request is answered immediately with
  a cheap deterministic fake hash (verification is pinned OFF in every
  harness leg so the claim check doesn't reject the fakes;
  merge/lease/accounting mechanics are identical), plus an honest
  miner-side Span (measured queue/force wall times of the actor) so the
  per-phase trace medians the probes embed stay populated.
- Tenants are one DetChannel each, storming ``requests_per_tenant``
  small unique-keyed requests at t0 and reading until replied or shed
  (a shed closes the conn — the client observes the LSP death exactly
  like production ``submit_with_retry`` would).

What a leg reports: completed/shed counts, wall makespan, admitted
throughput (completed / makespan), reply-latency p50/p99, CPU seconds
per completed request (``time.process_time`` over the leg — the
"per-request CPU cost" acceptance number), and the scheduler-side
trace summary (sampled traces only, by design — the harness runs
traced at ``DBM_TRACE_SAMPLE``-style rates without tracing being the
bottleneck).

Replica legs construct an :class:`~.replicas.ReplicaSet`; the QUEUE
CAPACITY IS SPLIT across replicas (``max_queued / n`` each) so 1-vs-N
comparisons run at EQUAL total admission capacity — equal shed rate by
construction — and the throughput difference is the sharding win, not
a bigger buffer.

``scripts/loadharness.py`` is the CLI (and the tier-1 mini-load leg);
``bench.py detail.load`` sweeps the tenant curve and checks the
result in as the BENCH artifact.
"""

from __future__ import annotations

import asyncio
import time
from statistics import median
from typing import Optional

from ..bitcoin.message import Message, MsgType, new_join, new_request, \
    new_result
from ..lsp.errors import LspError
from ..lspnet.detnet import DetServer
from ..utils.config import AdaptParams, CacheParams, LeaseParams, \
    QosParams, VerifyParams
from ..utils.trace import SPAN_PHASES

__all__ = ["run_load", "load_curve", "run_adversarial",
           "adversarial_ab", "WORKLOADS", "run_replay",
           "run_replay_procs"]

#: A 64-bit odd multiplier (splitmix64 finalizer constant): the fake
#: miner's answer must be a deterministic function of the chunk so
#: speculative re-issues merge idempotently, and cheap (no SHA-256 —
#: compute is exactly what this harness removes).
_MIX = 0xBF58476D1CE4E5B9
_MASK = (1 << 64) - 1


def _fake_hash(data: str, lower: int) -> int:
    return (hash(data) * _MIX + lower * 0x9E3779B97F4A7C15) & _MASK


async def _fake_miner(chan, trace_spans: bool,
                      rate: float = 0.0) -> None:
    """Instant miner actor: JOIN, then answer every Request with the
    fake hash — attaching a measured (honest, if tiny) span when
    ``trace_spans``. ``rate > 0`` makes it a RATE-LIMITED miner
    (``size / rate`` seconds of 'compute' per chunk, served serially),
    so the adversarial workloads (ISSUE 13) run against a KNOWN
    service capacity instead of whatever the box does."""
    chan.write(new_join().to_json())
    while True:
        try:
            payload = await chan.read()
        except LspError:
            return
        arrived = time.monotonic()
        msg = Message.from_json(payload)
        if msg.type != MsgType.REQUEST:
            continue
        if rate > 0:
            await asyncio.sleep((msg.upper - msg.lower + 1) / rate)
        h = _fake_hash(msg.data, msg.lower)
        span = None
        if trace_spans:
            done = time.monotonic()
            span = {"queue_s": 0.0, "dispatch_s": 0.0, "wait_s": 0.0,
                    "force_s": round(done - arrived, 9), "gap_s": 0.0}
        try:
            chan.write(new_result(h, msg.lower, msg.target,
                                  span=span).to_json())
        except LspError:
            return


async def _tenant(chan, data: str, count: int, nonces: int,
                  latencies: list, sheds: list) -> None:
    """One tenant: submit ``count`` unique requests back-to-back at
    storm start, then read replies; a dead conn = shed."""
    stamps = []
    try:
        for i in range(count):
            stamps.append(time.monotonic())
            chan.write(new_request(f"{data}#{i}", 0, nonces - 1).to_json())
        got = 0
        while got < count:
            payload = await chan.read()
            msg = Message.from_json(payload)
            if msg.type == MsgType.RESULT:
                latencies.append(time.monotonic() - stamps[got])
                got += 1
    except LspError:
        sheds.append(len(stamps))


def run_load(tenants: int = 1000, replicas: int = 1, miners: int = 4,
             *, requests_per_tenant: int = 1, req_nonces: int = 256,
             max_queued: int = 4096, recv_batch: Optional[int] = None,
             trace_sample: Optional[float] = None,
             qos_lazy: Optional[bool] = None,
             capture_path: Optional[str] = None,
             timeout_s: float = 300.0) -> dict:
    """One storm leg; returns the leg's measurement dict.

    ``qos_lazy`` pins the lazy-DRR walk knob for A/B legs (ISSUE 12;
    None = the default, lazy on). ``capture_path`` arms the workload
    capture plane (ISSUE 15) for the leg: the scheduler(s) write the
    storm's workload trace there (flushed and closed with the leg), so
    a synthesized storm becomes a :func:`run_replay` input — the
    round-trip the tier-1 replay leg and ``bench.py detail.replay``
    drive."""

    # Constructed (and closed) OUTSIDE the leg coroutine: an exception
    # escaping the storm must still flush/close the capture and clear
    # its crash-artifact registration (code review — a leaked handle
    # left flight dumps naming a stale file).
    cap = None
    if capture_path is not None:
        from .capture import WorkloadCapture
        cap = WorkloadCapture(path=capture_path)

    async def leg() -> dict:
        from .replicas import ReplicaSet
        from .scheduler import Scheduler
        server = DetServer(record=False)
        qos_kw = {} if qos_lazy is None else {"lazy": qos_lazy}
        qos = QosParams(enabled=True, max_queued=max(
            1, max_queued // max(1, replicas)), **qos_kw)
        lease = LeaseParams(grace_s=120.0, floor_s=60.0,
                            queue_alarm_s=0.0)
        # Adapt pinned OFF: this harness measures the REPLICA plane at
        # known static knobs (BENCH_r06 comparability; the tier-1
        # mini-load gate's completion bar assumes no admission
        # controller) — the static-vs-adaptive A/B lives in
        # run_adversarial, and DBM_ADAPT=1 is the production default
        # since ISSUE 14.
        kw = dict(lease=lease, cache=CacheParams(enabled=False), qos=qos,
                  adapt=AdaptParams(enabled=False),
                  verify=VerifyParams(enabled=False),
                  recv_batch=recv_batch, trace_sample=trace_sample,
                  capture=cap)
        if replicas > 1:
            coord = ReplicaSet(server, replicas, **kw)
        else:
            coord = Scheduler(server, **kw)
        coord_task = asyncio.create_task(coord.run())
        miner_tasks = [asyncio.create_task(
            _fake_miner(server.connect(), trace_spans=True))
            for _ in range(miners)]
        # Let the JOINs land before the storm.
        for _ in range(4):
            await asyncio.sleep(0)
        latencies: list = []
        sheds: list = []
        cpu0 = time.process_time()
        t0 = time.monotonic()
        tenant_tasks = [asyncio.create_task(
            _tenant(server.connect(), f"t{t}", requests_per_tenant,
                    req_nonces, latencies, sheds))
            for t in range(tenants)]
        try:
            await asyncio.wait_for(asyncio.gather(*tenant_tasks),
                                   timeout_s)
            timed_out = False
        except asyncio.TimeoutError:
            timed_out = True
        makespan = time.monotonic() - t0
        cpu_s = time.process_time() - cpu0
        for task in tenant_tasks + miner_tasks + [coord_task]:
            task.cancel()
        total = tenants * requests_per_tenant
        completed = len(latencies)
        latencies.sort()

        def pct(q: float):
            if not latencies:
                return None
            return round(latencies[min(len(latencies) - 1,
                                       int(q * len(latencies)))], 4)

        out = {
            "tenants": tenants,
            "replicas": replicas,
            "miners": miners,
            "requests": total,
            "completed": completed,
            "shed_tenants": len(sheds),
            "shed_rate": round(1 - completed / total, 4) if total else 0.0,
            "makespan_s": round(makespan, 3),
            "admitted_per_s": round(completed / makespan, 1)
            if makespan > 0 else None,
            "p50_s": pct(0.50),
            "p99_s": pct(0.99),
            "cpu_s_per_request": round(cpu_s / completed, 6)
            if completed else None,
            "trace": _trace_summary(coord, replicas),
        }
        if timed_out:
            out["timed_out"] = True
        return out

    try:
        return asyncio.run(leg())
    finally:
        if cap is not None:
            cap.close()


# --------------------------------------------- adversarial workloads

#: The three adversarial workload generators (ISSUE 13; the first half
#: of the ROADMAP trace-replay item — synthesized storms with the
#: shapes measured traffic produces). Arrival is PACED (tenants start
#: uniformly over ``duration_s``), miners are rate-limited so service
#: capacity is a known constant, and the flood factors are chosen so
#: the static control plane is genuinely mis-tuned:
#:
#: - ``mice_stampede``: a sustained small-request flood well past pool
#:   capacity — the static plane queues to ``max_queued`` and serves
#:   every admitted mouse a queue-depth's worth of latency; adaptive
#:   admission converges the intake rate to capacity and keeps the
#:   queue (and p99) near the service floor.
#: - ``tenant_churn``: the same overload carried by SHORT-LIVED
#:   tenants (connect, one request, disconnect) — admission + tenant
#:   GC under maximum state churn.
#: - ``elephant_convoy``: few tenants submitting chunked elephants
#:   back-to-back against a rate-limited pool — the chunk-sizing
#:   controller's territory, and the workload the <=10% completion
#:   regression bound is checked on.
WORKLOADS = {
    "mice_stampede": dict(tenants=1200, duration_s=5.0, nonces=4096,
                          requests_per_tenant=1, miner_rate=200_000.0,
                          wholesale_s=5.0, max_queued=256, churn=False,
                          sequential=False),
    "tenant_churn": dict(tenants=1200, duration_s=5.0, nonces=4096,
                         requests_per_tenant=1, miner_rate=200_000.0,
                         wholesale_s=5.0, max_queued=256, churn=True,
                         sequential=False),
    "elephant_convoy": dict(tenants=3, duration_s=0.0, nonces=1 << 21,
                            requests_per_tenant=2,
                            miner_rate=1_000_000.0, wholesale_s=0.3,
                            max_queued=256, churn=False,
                            sequential=True),
}


async def _paced_tenant(server, name: str, start_s: float, count: int,
                        nonces: int, latencies: list, sheds: list,
                        churn: bool, sequential: bool) -> None:
    """One adversarial-workload tenant: wait for its paced arrival
    slot, connect, then either storm its requests (stampede/churn) or
    submit them SEQUENTIALLY (convoy: next elephant only after the
    previous replied). ``churn`` closes the conn after the last reply
    (short-lived tenant). A dead conn sheds EVERY still-unanswered
    request of this tenant (submitted or not — the conn they would
    ride is gone), and only those: counting already-answered requests
    too would inflate ``shed_requests`` and quietly lower the
    completed-plus-shed-covers-everything bar the load gate asserts."""
    if start_s > 0:
        await asyncio.sleep(start_s)
    chan = server.connect()
    answered = 0
    try:
        if sequential:
            for i in range(count):
                t0 = time.monotonic()
                chan.write(new_request(f"{name}#{i}", 0,
                                       nonces - 1).to_json())
                while True:
                    msg = Message.from_json(await chan.read())
                    if msg.type == MsgType.RESULT:
                        latencies.append(time.monotonic() - t0)
                        answered += 1
                        break
        else:
            stamps = []
            for i in range(count):
                stamps.append(time.monotonic())
                chan.write(new_request(f"{name}#{i}", 0,
                                       nonces - 1).to_json())
            while answered < count:
                msg = Message.from_json(await chan.read())
                if msg.type == MsgType.RESULT:
                    latencies.append(time.monotonic() - stamps[answered])
                    answered += 1
        if churn:
            await chan.close()
    except LspError:
        lost = count - answered
        if lost > 0:
            sheds.append(lost)


def run_adversarial(workload: str, *, adapt: bool = False,
                    tenants: Optional[int] = None,
                    duration_s: Optional[float] = None,
                    miners: int = 4,
                    adapt_params: Optional[AdaptParams] = None,
                    capture_path: Optional[str] = None,
                    timeout_s: float = 120.0) -> dict:
    """One adversarial-workload leg (ISSUE 13), static knobs
    (``adapt=False`` — the defaults every deployment would ship) or
    the self-tuning controllers (``adapt=True``). Everything else —
    transport, miners, arrival schedule — is identical between legs,
    so the A/B isolates the controllers. Returns the ``run_load``
    measurement shape plus the controllers' final state."""
    spec = dict(WORKLOADS[workload])
    n_tenants = tenants if tenants is not None else spec["tenants"]
    duration = duration_s if duration_s is not None \
        else spec["duration_s"]
    # Sheds are the WORKLOAD here, not incidents: muting the per-shed
    # warning keeps hundreds of log lines from distorting the very leg
    # that sheds more (and from burying the CLI's JSON output) — the
    # dbmcheck executor applies the same discipline.
    import logging
    dbm_logger = logging.getLogger("dbm")
    # Outside the leg coroutine for exception-safe close (run_load).
    cap = None
    if capture_path is not None:
        from .capture import WorkloadCapture
        cap = WorkloadCapture(path=capture_path)

    async def leg() -> dict:
        from .scheduler import Scheduler
        server = DetServer(record=False)
        # The CONTROLLED knobs stay at their static defaults in both
        # legs (chunk_s=1.0, small_s=0.25, rate=0) — the adaptive leg
        # starts there and departs on evidence; workload-shape knobs
        # (wholesale bound, queue cap, lease cadence) are harness
        # configuration, identical in both legs.
        qos = QosParams(enabled=True, wholesale_s=spec["wholesale_s"],
                        max_queued=spec["max_queued"])
        lease = LeaseParams(grace_s=120.0, floor_s=60.0, tick_s=0.1,
                            queue_alarm_s=0.0)
        ap = adapt_params if adapt_params is not None else AdaptParams(
            enabled=True, tick_s=0.1)
        coord = Scheduler(server, lease=lease,
                          cache=CacheParams(enabled=False), qos=qos,
                          adapt=ap if adapt
                          else AdaptParams(enabled=False),
                          verify=VerifyParams(enabled=False),
                          capture=cap)
        coord_task = asyncio.create_task(coord.run())
        miner_tasks = [asyncio.create_task(
            _fake_miner(server.connect(), trace_spans=True,
                        rate=spec["miner_rate"]))
            for _ in range(miners)]
        for _ in range(4):
            await asyncio.sleep(0)
        latencies: list = []
        sheds: list = []
        cpu0 = time.process_time()
        t0 = time.monotonic()
        tenant_tasks = [asyncio.create_task(
            _paced_tenant(server, f"t{t}",
                          (t / n_tenants) * duration if duration > 0
                          else 0.0,
                          spec["requests_per_tenant"], spec["nonces"],
                          latencies, sheds, spec["churn"],
                          spec["sequential"]))
            for t in range(n_tenants)]
        try:
            await asyncio.wait_for(asyncio.gather(*tenant_tasks),
                                   timeout_s)
            timed_out = False
        except asyncio.TimeoutError:
            timed_out = True
        makespan = time.monotonic() - t0
        cpu_s = time.process_time() - cpu0
        for task in tenant_tasks + miner_tasks + [coord_task]:
            task.cancel()
        total = n_tenants * spec["requests_per_tenant"]
        completed = len(latencies)
        latencies.sort()

        def pct(q: float):
            if not latencies:
                return None
            return round(latencies[min(len(latencies) - 1,
                                       int(q * len(latencies)))], 4)

        out = {
            "workload": workload,
            "adapt": bool(adapt),
            "tenants": n_tenants,
            "miners": miners,
            "requests": total,
            "completed": completed,
            "shed_tenants": len(sheds),
            "shed_requests": sum(sheds),
            "shed_rate": round(1 - completed / total, 4) if total
            else 0.0,
            "makespan_s": round(makespan, 3),
            "admitted_per_s": round(completed / makespan, 1)
            if makespan > 0 else None,
            "p50_s": pct(0.50),
            "p99_s": pct(0.99),
            "cpu_s_per_request": round(cpu_s / completed, 6)
            if completed else None,
        }
        if adapt and coord.adapt_plane is not None:
            out["adapt_state"] = coord.adapt_plane.state()
        if timed_out:
            out["timed_out"] = True
        return out

    prev_level = dbm_logger.level
    dbm_logger.setLevel(logging.CRITICAL)
    try:
        return asyncio.run(leg())
    finally:
        dbm_logger.setLevel(prev_level)
        if cap is not None:
            cap.close()


def adversarial_ab(workloads=None, rounds: int = 3, **kw) -> dict:
    """The ``detail.adapt`` A/B (ISSUE 13): each adversarial workload
    run static-vs-adaptive over ``rounds`` interleaved order-swapped
    rounds (the repo's storm-probe noise discipline), medians reported
    per leg plus a per-workload comparison summary."""
    workloads = list(workloads) if workloads is not None \
        else list(WORKLOADS)
    out: dict = {"rounds": rounds, "workloads": {}}
    keys = ("makespan_s", "admitted_per_s", "p50_s", "p99_s",
            "cpu_s_per_request", "shed_rate")
    for workload in workloads:
        legs: dict = {False: [], True: []}
        for rnd in range(max(1, rounds)):
            order = (False, True) if rnd % 2 == 0 else (True, False)
            for flag in order:
                legs[flag].append(
                    run_adversarial(workload, adapt=flag, **kw))
        entry: dict = {}
        for flag, name in ((False, "static"), (True, "adaptive")):
            med = {}
            for key in keys:
                vals = [leg[key] for leg in legs[flag]
                        if leg.get(key) is not None]
                med[key] = round(median(vals), 6) if vals else None
            med["completed"] = int(median(
                [leg["completed"] for leg in legs[flag]]))
            entry[name] = med
        entry["adapt_state"] = legs[True][-1].get("adapt_state")
        s, a = entry["static"], entry["adaptive"]
        cmp: dict = {}
        if s["p99_s"] and a["p99_s"]:
            cmp["p99_speedup"] = round(s["p99_s"] / a["p99_s"], 3)
        if s["admitted_per_s"] and a["admitted_per_s"]:
            cmp["admitted_ratio"] = round(
                a["admitted_per_s"] / s["admitted_per_s"], 3)
        if s["makespan_s"] and a["makespan_s"]:
            cmp["makespan_ratio"] = round(
                a["makespan_s"] / s["makespan_s"], 3)
        entry["compare"] = cmp
        entry["samples"] = [
            {k: leg.get(k) for k in
             ("adapt", "completed", "shed_rate", "makespan_s",
              "admitted_per_s", "p50_s", "p99_s")}
            for flag in (False, True) for leg in legs[flag]]
        out["workloads"][workload] = entry
    return out


def _trace_summary(coord, replicas: int) -> dict:
    """Per-phase medians over the (sampled) traces of a finished leg —
    the same shape as ``bench._Cluster.trace_summary`` so ``detail.load``
    artifacts decompose like the other storm probes'."""
    sched_queue, phases = [], {}
    traces = coord.traces.items()
    for _key, t in traces:
        events = t.to_dict()["events"]
        enq = next((e for e in events if e["event"] == "enqueue"), None)
        disp = next((e for e in events if e["event"] == "dispatch"), None)
        if enq is not None and disp is not None:
            sched_queue.append(disp["t"] - enq["t"])
        for e in events:
            if e["event"] != "miner_span":
                continue
            for ph in SPAN_PHASES:
                v = e.get(ph)
                if isinstance(v, (int, float)):
                    phases.setdefault(ph, []).append(float(v))
    out = {"sampled_traces": len(traces)}
    if sched_queue:
        out["sched_queue_s_p50"] = round(median(sched_queue), 6)
    for ph, xs in sorted(phases.items()):
        out[f"miner_{ph}_p50"] = round(median(xs), 6)
    return out


# ----------------------------------------------------- workload replay

#: Captured rate EWMAs above this are a detnet instant miner's measured
#: throughput (microsecond answers read as 10^8+ nps); modeling them as
#: rate-limited sleeps would add loop churn without adding fidelity —
#: the replay miner goes INSTANT instead.
_REPLAY_RATE_CUTOFF = 5e6


def _replay_data(name: str, dc: int) -> str:
    """Replay request key padded toward the captured pow2 data-size
    class (bounded at 128 chars — the class preserves the geometry mix,
    not the bytes)."""
    want = min(max(1, (1 << max(0, dc)) - 1), 128)
    return name + "x" * max(0, want - len(name))


async def _replay_tenant(server, name: str, start_s: float, reqs: list,
                         latencies: list, sheds: list) -> None:
    """One replayed tenant: connect at its captured (speed-warped)
    arrival slot, submit each request at its captured offset from an
    inner writer task while reading replies — a captured tenant may
    interleave submissions and replies arbitrarily, unlike the storm
    tenants' send-all-then-read shape. A dead conn sheds every
    still-unanswered request (the ``_paced_tenant`` accounting rule)."""
    if start_s > 0:
        await asyncio.sleep(start_s)
    chan = server.connect()
    t0 = time.monotonic()
    stamps: list = []
    state = {"answered": 0}
    total = len(reqs)

    async def writer() -> None:
        for i, (dt, n, mode, dc) in enumerate(reqs):
            wait = t0 + dt - time.monotonic()
            if wait > 0:
                await asyncio.sleep(wait)
            stamps.append(time.monotonic())
            try:
                # Difficulty-mode geometry replays with target=1: the
                # scheduler runs the real difficulty path (fan-out,
                # prefix-release bookkeeping) while the fake pool's
                # answers practically never qualify, so the reply is
                # the deterministic barrier arg-min.
                chan.write(new_request(
                    _replay_data(f"{name}#{i}", dc), 0, max(1, n) - 1,
                    1 if mode == "diff" else 0).to_json())
            except LspError:
                return       # shed mid-storm; the reader records it

    wtask = asyncio.create_task(writer())
    try:
        while state["answered"] < total:
            payload = await chan.read()
            msg = Message.from_json(payload)
            if msg.type == MsgType.RESULT:
                latencies.append(
                    time.monotonic() - stamps[state["answered"]])
                state["answered"] += 1
    except LspError:
        lost = total - state["answered"]
        if lost > 0:
            sheds.append(lost)
    finally:
        wtask.cancel()


def run_replay(path: str, *, speed: Optional[float] = None,
               miners: Optional[int] = None,
               max_tenants: Optional[int] = None,
               bounds: Optional[dict] = None,
               timeout_s: float = 300.0) -> dict:
    """Re-drive a captured workload trace through the detnet harness
    (ISSUE 15): the ``replay`` workload.

    Preserves the capture's inter-arrival process per hashed tenant and
    its geometry mix (range size, argmin-vs-difficulty, data-size
    class); models the serving side from the capture's pool snapshots
    (rate EWMAs become rate-limited fake miners; instant-class rates
    stay instant); ``speed`` (default ``DBM_REPLAY_SPEED``) time-warps
    BOTH the arrival clock and the rate-limited service rates, so the
    load factor — the shape — survives the warp. Returns the
    ``run_load`` measurement shape plus the capture's own baseline
    (``capture``) and the side-by-side ``fidelity`` verdict."""
    from .capture import (capture_baseline, fidelity, load_capture,
                          replay_plan, replay_speed)
    cap = load_capture(path)
    plan = replay_plan(cap, max_tenants=max_tenants)
    # Baseline restricted to the REPLAYED tenant window: a max_tenants
    # truncation must compare against the same subset's own numbers,
    # not the full capture's (code review).
    base = capture_baseline(cap, tenants={p["ten"] for p in plan})
    spd = speed if speed is not None else replay_speed()
    if bounds is None and cap.cfg.get("transport") not in (None,
                                                          "DetServer"):
        # Cross-transport replay (a real-LSP capture re-driven on
        # detnet — the primary "measured traffic becomes the test
        # suite" workflow): the latency ratio reflects the transport's
        # own floor, not workload shape, so it is reported UNGATED;
        # arrival pacing, admitted/s, shed shape, and request-count
        # equality still gate (the run_replay_procs rule, reversed).
        bounds = {"p99_ratio": None}
    # Sheds may be the replayed workload (run_adversarial discipline):
    # a shed-heavy capture must not drown the leg in warning lines.
    import logging
    dbm_logger = logging.getLogger("dbm")

    async def leg() -> dict:
        from .scheduler import Scheduler
        server = DetServer(record=False)
        qos = QosParams(
            enabled=bool(cap.cfg.get("qos", True)),
            max_queued=max(1, int(cap.cfg.get("max_queued", 4096))),
            wholesale_s=float(cap.cfg.get("wholesale_s", 5.0)))
        lease = LeaseParams(grace_s=120.0, floor_s=60.0,
                            queue_alarm_s=0.0)
        # Adapt pinned OFF like every other harness leg: fidelity
        # compares scheduler SHAPES at known static knobs.
        # capture=False: a lingering DBM_CAPTURE=1 must NOT arm the
        # env capture here — WorkloadCapture opens its path with 'w',
        # which would truncate the very file being replayed when
        # DBM_CAPTURE_PATH points at it (code review).
        coord = Scheduler(server, lease=lease,
                          cache=CacheParams(enabled=False), qos=qos,
                          adapt=AdaptParams(enabled=False),
                          verify=VerifyParams(enabled=False),
                          capture=False)
        coord_task = asyncio.create_task(coord.run())
        rates = cap.pool_rates()
        n_miners = (miners if miners is not None
                    else min(16, len(rates)) if rates else 4)
        miner_tasks = []
        for i in range(max(1, n_miners)):
            rate = rates[i % len(rates)] if rates else 0.0
            rate_eff = (0.0 if rate <= 0 or rate > _REPLAY_RATE_CUTOFF
                        else rate * spd)
            miner_tasks.append(asyncio.create_task(_fake_miner(
                server.connect(), trace_spans=True, rate=rate_eff)))
        for _ in range(4):
            await asyncio.sleep(0)
        latencies: list = []
        sheds: list = []
        cpu0 = time.process_time()
        t0 = time.monotonic()
        tenant_tasks = [asyncio.create_task(_replay_tenant(
            server, p["name"], p["start"] / spd,
            [(dt / spd, n, mode, dc) for dt, n, mode, dc in p["reqs"]],
            latencies, sheds))
            for p in plan]
        try:
            await asyncio.wait_for(asyncio.gather(*tenant_tasks),
                                   timeout_s)
            timed_out = False
        except asyncio.TimeoutError:
            timed_out = True
        makespan = time.monotonic() - t0
        cpu_s = time.process_time() - cpu0
        for task in tenant_tasks + miner_tasks + [coord_task]:
            task.cancel()
        total = sum(len(p["reqs"]) for p in plan)
        completed = len(latencies)
        latencies.sort()

        def pct(q: float):
            if not latencies:
                return None
            return round(latencies[min(len(latencies) - 1,
                                       int(q * len(latencies)))], 4)

        out = {
            "workload": "replay",
            "source": path,
            "speed": spd,
            "tenants": len(plan),
            "replicas": 1,
            "miners": len(miner_tasks),
            "requests": total,
            "completed": completed,
            "shed_tenants": len(sheds),
            "shed_requests": sum(sheds),
            # sheds over arrivals — the SAME definition the capture
            # baseline uses, so the fidelity delta compares like with
            # like (run_load's 1 - completed/total would also fold
            # timeouts in).
            "shed_rate": round(sum(sheds) / total, 4) if total else 0.0,
            "makespan_s": round(makespan, 3),
            "admitted_per_s": round(completed / makespan, 1)
            if makespan > 0 else None,
            "p50_s": pct(0.50),
            "p99_s": pct(0.99),
            "cpu_s_per_request": round(cpu_s / completed, 6)
            if completed else None,
            "trace": _trace_summary(coord, 1),
        }
        if timed_out:
            out["timed_out"] = True
        out["capture"] = base
        out["fidelity"] = fidelity(base, out, speed=spd, bounds=bounds)
        return out

    prev_level = dbm_logger.level
    dbm_logger.setLevel(logging.CRITICAL)
    try:
        return asyncio.run(leg())
    finally:
        dbm_logger.setLevel(prev_level)


async def _replay_ring_tenant(statedir: str, params, name: str,
                              start_s: float, reqs: list,
                              latencies: list, sheds: list) -> None:
    """The --procs replay tenant: same pacing contract as
    :func:`_replay_tenant`, over real UDP against the advertised
    ring."""
    from ..lsp.client import new_async_client
    from .procs import resolve_owner
    if start_s > 0:
        await asyncio.sleep(start_s)
    owner = resolve_owner(statedir, name)
    if owner is None:
        sheds.append(len(reqs))
        return
    try:
        client = await new_async_client(owner[1], params)
    except LspError:
        sheds.append(len(reqs))
        return
    t0 = time.monotonic()
    stamps: list = []
    state = {"answered": 0}
    total = len(reqs)

    async def writer() -> None:
        for i, (dt, n, mode, dc) in enumerate(reqs):
            wait = t0 + dt - time.monotonic()
            if wait > 0:
                await asyncio.sleep(wait)
            stamps.append(time.monotonic())
            try:
                client.write(new_request(
                    _replay_data(f"{name}#{i}", dc), 0, max(1, n) - 1,
                    1 if mode == "diff" else 0).to_json())
            except LspError:
                return
    wtask = asyncio.create_task(writer())
    try:
        while state["answered"] < total:
            msg = Message.from_json(await client.read())
            if msg.type == MsgType.RESULT:
                latencies.append(
                    time.monotonic() - stamps[state["answered"]])
                state["answered"] += 1
    except LspError:
        if total - state["answered"] > 0:
            sheds.append(total - state["answered"])
    finally:
        wtask.cancel()
        await client.close()


def run_replay_procs(path: str, *, replicas: int = 2, miners: int = 4,
                     speed: Optional[float] = None,
                     max_tenants: Optional[int] = None,
                     bounds: Optional[dict] = None,
                     timeout_s: float = 180.0) -> dict:
    """Replay a capture through the REAL multi-process topology
    (``loadharness --replay ... --procs``): router + replica processes
    on their own LSP sockets + instant fake miner agents, arrivals
    re-driven over real localhost UDP with the captured per-tenant
    pacing. The serving side is the cluster's own (instant) agents —
    captured pool rates do not transfer across the process boundary —
    so the DEFAULT fidelity bounds here gate only the arrival/shed
    shape (request count, shed delta); the latency ratios are reported
    ungated (``bounds=`` re-arms them for a same-transport capture)."""
    import shutil
    import tempfile

    from .capture import (capture_baseline, fidelity, load_capture,
                          replay_plan, replay_speed)
    cap = load_capture(path)
    plan = replay_plan(cap, max_tenants=max_tenants)
    base = capture_baseline(cap, tenants={p["ten"] for p in plan})
    spd = speed if speed is not None else replay_speed()
    if bounds is None:
        bounds = {"admitted_ratio": None, "p99_ratio": None}

    async def leg() -> dict:
        from .procs import ProcCluster
        statedir = tempfile.mkdtemp(prefix="dbm_replayprocs_")
        # DBM_CAPTURE=0 pinned in the children: replaying must never
        # arm a fresh capture that truncates the source file (or
        # records the replay's own synthetic traffic as if measured).
        env = {"DBM_HEALTH_BEAT_S": "0.25", "DBM_HEALTH_MISS_K": "3",
               "DBM_EPOCH_MILLIS": "500", "DBM_EPOCH_LIMIT": "8",
               "DBM_TRACE_SAMPLE": "0.01", "DBM_ADAPT": "0",
               "DBM_CAPTURE": "0"}
        cluster = ProcCluster(statedir, replicas=replicas,
                              miners=miners, env=env, fake_miners=True)
        cluster.start()
        params = _proc_params()
        latencies: list = []
        sheds: list = []
        timed_out = False
        try:
            await cluster.wait_live(replicas, timeout_s=30.0,
                                    miners=miners)
            t0 = time.monotonic()
            tasks = [asyncio.create_task(_replay_ring_tenant(
                statedir, params, p["name"], p["start"] / spd,
                [(dt / spd, n, mode, dc)
                 for dt, n, mode, dc in p["reqs"]],
                latencies, sheds)) for p in plan]
            try:
                await asyncio.wait_for(asyncio.gather(*tasks),
                                       timeout_s)
            except asyncio.TimeoutError:
                timed_out = True
            makespan = time.monotonic() - t0
            for task in tasks:
                task.cancel()
        finally:
            cluster.close()
            shutil.rmtree(statedir, ignore_errors=True)
        total = sum(len(p["reqs"]) for p in plan)
        completed = len(latencies)
        latencies.sort()

        def pct(q: float):
            if not latencies:
                return None
            return round(latencies[min(len(latencies) - 1,
                                       int(q * len(latencies)))], 4)

        out = {
            "workload": "replay", "topology": "procs", "source": path,
            "speed": spd, "tenants": len(plan), "replicas": replicas,
            "miners": miners, "requests": total, "completed": completed,
            "shed_tenants": len(sheds), "shed_requests": sum(sheds),
            "shed_rate": round(sum(sheds) / total, 4) if total else 0.0,
            "makespan_s": round(makespan, 3),
            "admitted_per_s": round(completed / makespan, 1)
            if makespan > 0 else None,
            "p50_s": pct(0.50), "p99_s": pct(0.99),
            "trace": {"sampled_traces": 0},
        }
        if timed_out:
            out["timed_out"] = True
        out["capture"] = base
        out["fidelity"] = fidelity(base, out, speed=spd, bounds=bounds)
        return out

    return asyncio.run(leg())


def _children_cpu_s(pids) -> float:
    """Summed utime+stime of child processes (``/proc/<pid>/stat``) —
    the procs leg's scheduler CPU lives in other processes, so the
    harness's own ``process_time`` would measure nothing."""
    import os
    tick = os.sysconf("SC_CLK_TCK")
    total = 0.0
    for pid in pids:
        try:
            with open(f"/proc/{pid}/stat", encoding="ascii") as fh:
                parts = fh.read().rsplit(") ", 1)[-1].split()
            total += (int(parts[11]) + int(parts[12])) / tick
        except (OSError, ValueError, IndexError):
            continue
    return total


async def _ring_tenant(statedir: str, params, name: str, count: int,
                       req_nonces: int, latencies: list,
                       sheds: list) -> None:
    """One ring-resolving tenant over real UDP (the --procs driver's
    unit of work, shared by the in-harness driver and the sharded
    driver subprocesses)."""
    from ..lsp.client import new_async_client
    from .procs import resolve_owner
    owner = resolve_owner(statedir, name)
    if owner is None:
        sheds.append(count)
        return
    try:
        client = await new_async_client(owner[1], params)
    except LspError:
        sheds.append(count)
        return
    stamps = []
    got = 0
    try:
        for i in range(count):
            stamps.append(time.monotonic())
            client.write(new_request(f"{name}#{i}", 0,
                                     req_nonces - 1).to_json())
        while got < count:
            msg = Message.from_json(await client.read())
            if msg.type == MsgType.RESULT:
                latencies.append(time.monotonic() - stamps[got])
                got += 1
    except LspError:
        # Only the UNANSWERED requests are casualties of the dead conn
        # (same accounting rule as _paced_tenant: counting answered
        # ones too would lower the completed+shed-covers-all gate bar).
        if count - got > 0:
            sheds.append(count - got)
    finally:
        await client.close()


def _proc_params():
    from ..lsp.params import Params
    return Params(epoch_limit=8, epoch_millis=500, window_size=32,
                  max_backoff_interval=2)


async def drive_ring_tenants(statedir: str, start: int, count: int,
                             requests_per_tenant: int, req_nonces: int,
                             timeout_s: float) -> dict:
    """Drive tenants ``t<start>..t<start+count-1>`` against the ring at
    ``statedir``; returns ``{"latencies": [...], "sheds": [...]}`` —
    one DRIVER's share of a (possibly sharded) --procs storm."""
    params = _proc_params()
    latencies: list = []
    sheds: list = []
    tasks = [asyncio.create_task(
        _ring_tenant(statedir, params, f"t{start + i}",
                     requests_per_tenant, req_nonces, latencies, sheds))
        for i in range(count)]
    timed_out = False
    try:
        await asyncio.wait_for(asyncio.gather(*tasks), timeout_s)
    except asyncio.TimeoutError:
        timed_out = True
    for task in tasks:
        task.cancel()
    return {"latencies": latencies, "sheds": sheds,
            "timed_out": timed_out}


def run_load_procs(tenants: int = 200, replicas: int = 2,
                   miners: int = 4, *, requests_per_tenant: int = 1,
                   req_nonces: int = 256, drivers: int = 1,
                   rollup: Optional[bool] = None,
                   timeout_s: float = 180.0) -> dict:
    """Multi-process topology leg (ISSUE 12, ``loadharness --procs``):
    the REAL process topology — router + one OS process per replica on
    its own LSP socket + fake (instant-compute) miner agents — driven
    by ring-resolving tenants over real localhost UDP, so ``detail.load``
    can compare in-process vs multi-process replicas at equal tenant
    count. The shape of the returned dict matches :func:`run_load`
    (``cpu_s_per_request`` sums the CHILD processes' CPU from /proc).

    ``drivers > 1`` SHARDS the storm driver itself across that many
    OS processes (ISSUE 13 satellite): one harness process tops out
    around O(500) real UDP conns — its own event loop becomes the
    bottleneck and the measurement — so each driver subprocess
    (``python -m ...apps.loadharness driver``) runs an equal tenant
    slice and prints one JSON result line the parent merges. Driver
    CPU stays out of ``cpu_s_per_request`` exactly like the inline
    driver's (only cluster children are summed)."""
    import shutil
    import tempfile

    async def leg() -> dict:
        from .procs import ProcCluster
        statedir = tempfile.mkdtemp(prefix="dbm_loadprocs_")
        env = {"DBM_HEALTH_BEAT_S": "0.25", "DBM_HEALTH_MISS_K": "3",
               "DBM_EPOCH_MILLIS": "500", "DBM_EPOCH_LIMIT": "8",
               "DBM_TRACE_SAMPLE": "0.01",
               # Replica-plane measurement at static knobs (see the
               # in-process legs' adapt pin above).
               "DBM_ADAPT": "0"}
        if rollup is not None:
            # Pin the rollup plane for an A/B (bench detail.rollup);
            # None inherits the parent env / default-on.
            env["DBM_ROLLUP"] = "1" if rollup else "0"
        cluster = ProcCluster(statedir, replicas=replicas, miners=miners,
                              env=env, fake_miners=True)
        cluster.start()
        latencies: list = []
        sheds: list = []
        timed_out = False
        try:
            await cluster.wait_live(replicas, timeout_s=30.0,
                                    miners=miners)
            pids = [p.pid for p in cluster.procs.values()]
            cpu0 = _children_cpu_s(pids)
            t0 = time.monotonic()
            if drivers <= 1:
                out = await drive_ring_tenants(
                    statedir, 0, tenants, requests_per_tenant,
                    req_nonces, timeout_s)
                latencies, sheds = out["latencies"], out["sheds"]
                timed_out = out["timed_out"]
            else:
                shards = await _drive_sharded(
                    statedir, tenants, drivers, requests_per_tenant,
                    req_nonces, timeout_s, cluster.env)
                for out in shards:
                    latencies.extend(out.get("latencies", []))
                    sheds.extend(out.get("sheds", []))
                    timed_out = timed_out or out.get("timed_out", False)
            makespan = time.monotonic() - t0
            cpu_s = _children_cpu_s(pids) - cpu0
            rollup_summary = None
            if cluster.env.get("DBM_ROLLUP", "1") != "0":
                # Read the cluster's own published rollup while the
                # processes are still alive: the --assert-rollup gate
                # (scripts/loadharness.py) checks every live process
                # published fresh and the cluster counter totals cover
                # the storm the driver measured client-side. Publishers
                # stamp at the BEAT cadence, so the blobs lag the final
                # counters by up to one beat — poll a few beats until
                # the totals cover the storm rather than snapshotting a
                # mid-flight frame.
                from .procs import health_beat_s
                from .rollup import aggregate as _rollup_aggregate

                def _fam(doc, family):
                    pref = family + "{"
                    sec = doc["cluster"]["counters"]
                    return int(sum(v for k, v in sec.items()
                                   if k == family or k.startswith(pref)))

                try:
                    beat = health_beat_s()
                    doc = _rollup_aggregate(statedir)
                    for _ in range(8):
                        if _fam(doc, "sched.results_sent") \
                                + _fam(doc, "sched.qos_shed") \
                                >= len(latencies) + len(sheds):
                            break
                        await asyncio.sleep(max(0.05, beat / 2))
                        doc = _rollup_aggregate(statedir)
                    statuses = [p["status"] for p in doc["procs"]]
                    rollup_summary = {
                        "procs": len(statuses),
                        "fresh": statuses.count("fresh"),
                        "stale": statuses.count("stale"),
                        "fenced": statuses.count("fenced"),
                        "results_sent": _fam(doc, "sched.results_sent"),
                        "qos_shed": _fam(doc, "sched.qos_shed"),
                        "series_overflow":
                            doc["cluster"]["series_overflow"],
                    }
                except Exception:  # noqa: BLE001 — summary, not gate
                    rollup_summary = {"error": "aggregate failed"}
        finally:
            cluster.close()
            shutil.rmtree(statedir, ignore_errors=True)
        total = tenants * requests_per_tenant
        completed = len(latencies)
        latencies.sort()

        def pct(q: float):
            if not latencies:
                return None
            return round(latencies[min(len(latencies) - 1,
                                       int(q * len(latencies)))], 4)

        out = {
            "tenants": tenants, "replicas": replicas, "miners": miners,
            "topology": "procs", "drivers": max(1, drivers),
            "requests": total, "completed": completed,
            "shed_tenants": len(sheds),
            "shed_rate": round(1 - completed / total, 4) if total
            else 0.0,
            "makespan_s": round(makespan, 3),
            "admitted_per_s": round(completed / makespan, 1)
            if makespan > 0 else None,
            "p50_s": pct(0.50), "p99_s": pct(0.99),
            "cpu_s_per_request": round(cpu_s / completed, 6)
            if completed else None,
            "trace": {"sampled_traces": 0},
        }
        if rollup_summary is not None:
            out["rollup"] = rollup_summary
        if timed_out:
            out["timed_out"] = True
        return out

    return asyncio.run(leg())


async def _drive_sharded(statedir: str, tenants: int, drivers: int,
                         requests_per_tenant: int, req_nonces: int,
                         timeout_s: float, env: dict) -> list:
    """Spawn ``drivers`` driver subprocesses over equal tenant slices
    and collect their JSON result lines (ISSUE 13 satellite — the
    sharded storm driver). A driver that crashes or prints garbage
    contributes an empty shard (its tenants count as incomplete, which
    the gates then fail loudly) rather than wedging the parent."""
    import json
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    per = -(-tenants // max(1, drivers))
    procs = []
    for d in range(drivers):
        start = d * per
        count = min(per, tenants - start)
        if count <= 0:
            break
        procs.append(await asyncio.create_subprocess_exec(
            sys.executable, "-m",
            "distributed_bitcoinminer_tpu.apps.loadharness", "driver",
            statedir, "--start", str(start), "--count", str(count),
            "--requests-per-tenant", str(requests_per_tenant),
            "--nonces", str(req_nonces), "--timeout", str(timeout_s),
            cwd=repo, env=env, stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL))
    outs = []
    for proc in procs:
        try:
            stdout, _ = await asyncio.wait_for(proc.communicate(),
                                               timeout_s + 30.0)
        except asyncio.TimeoutError:
            proc.kill()
            outs.append({})
            continue
        try:
            outs.append(json.loads(
                stdout.decode("utf-8").strip().splitlines()[-1]))
        except (ValueError, IndexError):
            outs.append({})
    return outs


def driver_main(argv=None) -> int:
    """``python -m ...apps.loadharness driver <statedir> ...`` — ONE
    shard of a sharded --procs storm: drive a tenant slice against the
    advertised ring and print one JSON line (latencies + sheds) for
    the parent to merge."""
    import argparse
    import json
    import sys
    argv = sys.argv[1:] if argv is None else argv
    ap = argparse.ArgumentParser(prog="loadharness driver")
    ap.add_argument("role", choices=("driver",))
    ap.add_argument("statedir")
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--count", type=int, required=True)
    ap.add_argument("--requests-per-tenant", type=int, default=1)
    ap.add_argument("--nonces", type=int, default=256)
    ap.add_argument("--timeout", type=float, default=180.0)
    args = ap.parse_args(argv)
    out = asyncio.run(drive_ring_tenants(
        args.statedir, args.start, args.count,
        args.requests_per_tenant, args.nonces, args.timeout))
    print(json.dumps(out), flush=True)
    return 0


def load_curve(points, replica_counts=(1, 4), rounds: int = 2,
               **kw) -> dict:
    """The BENCH load curve: for each tenant count in ``points`` and
    each replica count, run ``rounds`` interleaved order-swapped legs
    (the repo's storm-probe noise discipline) and report medians.

    Returns ``{"points": [{"tenants": N, "r<k>": {...medians...}}, ...],
    "samples": [...]}``.
    """
    samples = []
    curve = []
    for tenants in points:
        entry: dict = {"tenants": tenants}
        per_rep: dict = {n: [] for n in replica_counts}
        for rnd in range(max(1, rounds)):
            order = (list(replica_counts) if rnd % 2 == 0
                     else list(reversed(replica_counts)))
            for n in order:
                leg = run_load(tenants=tenants, replicas=n, **kw)
                per_rep[n].append(leg)
                samples.append(leg)
        for n, legs in per_rep.items():
            med = {}
            for key in ("makespan_s", "admitted_per_s", "p50_s", "p99_s",
                        "cpu_s_per_request", "shed_rate"):
                vals = [leg[key] for leg in legs
                        if leg.get(key) is not None]
                med[key] = round(median(vals), 6) if vals else None
            med["completed"] = legs[0]["completed"]
            med["trace"] = legs[-1]["trace"]
            entry[f"r{n}"] = med
        curve.append(entry)
    return {"points": curve, "samples": samples}


if __name__ == "__main__":
    import sys
    sys.exit(driver_main())
