"""The scheduler: shard nonce ranges over an elastic miner pool, merge argmins.

Faithful state machine of the reference coordinator
(ref: bitcoin/server/server.go:19-403), as one asyncio actor instead of
channel-coupled goroutines:

- FIFO request queue, ONE request in flight at a time (deliberate reference
  simplification — no pipeline parallelism).
- ``load_balance``: bounds become exclusive (``upper += 1``); even split
  ``total // num_miners`` with the remainder given to the FIRST miner; when
  there are more miners than nonces, only ``total`` miners get 1-nonce chunks
  (ref: server.go:165-205).
- Bound quirk preserved for bit parity: chunks are sent with EXCLUSIVE upper
  bounds but the miner treats ``Upper`` as inclusive (ref: miner.go:51-52),
  so each chunk scans one extra nonce and the system as a whole scans
  ``[0, maxNonce+1]``.
- Result merge: strict ``<`` on the uint64 hash; barrier releases the Result
  to the client when every chunk of the request has been answered
  (ref: server.go:257-325).
- Difficulty extension (no reference analog; BASELINE config 5): a Request
  carrying ``Target`` fans out with the target on every chunk, miners
  early-exit at their chunk's first ``hash < target`` nonce, and the merge
  answers the lowest-nonce qualifying response — the globally first
  qualifying nonce when every miner speaks the extension (chunks ascend
  and each reports its chunk-first hit; a stock Target-dropping miner
  reports a chunk arg-min instead, weakening its chunk to "a qualifying
  nonce" — detected via the Result's target echo and surfaced in logs,
  see ``Request.weak``). No hit anywhere degrades to the exact arg-min,
  and stock Requests (``Target`` absent = 0) take the reference path
  byte-for-byte.
- Difficulty prefix release (VERDICT r4): chunks cover ascending disjoint
  ranges, so once some chunk ``c`` reports a qualifying hit and every chunk
  ``< c`` has answered without one, no later answer can beat it — the
  Result is released IMMEDIATELY, without waiting for the full barrier.
  The released job's remaining chunks are cancelled exactly like a
  client-drop (miners free, their late Results pop as stale via the
  job_id/FIFO machinery), so a tight target's time-to-first-hit is the
  winning chunk's scan, not the slowest full scan. Stock arg-min requests
  keep the reference's full barrier untouched (ref: server.go:309-324).
- Miner drop: reassign its unanswered chunks to available miners, else park
  them; parked chunks are re-issued when a miner joins or frees up
  (ref: server.go:326-376, 222-244, 285-304).
- Client drop: the in-flight request is cancelled immediately — miners are
  freed, parked chunks cleared, the next queued request starts.
- Robustness plane (no reference analog; PNPCoin-style lease discipline,
  PAPERS.md arxiv 2208.12628): every assigned chunk carries a LEASE whose
  deadline derives from its nonce-range size and an EWMA of the assigned
  miner's observed per-chunk throughput (pool-wide EWMA, then a flat grace,
  when unobserved). The reference's only fault trigger is the LSP
  epoch-limit drop; a miner whose transport still heartbeats but whose
  compute is wedged (hung device dispatch, stalled worker thread) passes
  that check forever. On lease expiry the chunk is speculatively RE-ISSUED
  to an available miner — first Result wins; the loser's late Result pops
  from its FIFO as answered/stale and is dropped by the existing
  ``job_id``/``answered[idx]`` machinery. A miner that blows
  ``quarantine_after`` consecutive leases is QUARANTINED: excluded from new
  assignments until it answers again (any Result pop lifts it). Leases and
  quarantine change scheduling latency under faults only — never the
  answer: re-issued chunks scan the same range, so the merge is idempotent.

Bookkeeping divergence from the reference (deliberate): the reference tracks
one recorded chunk per miner plus a positional ``responsibleMiners`` list,
which deadlocks or double-counts in several reachable states — a parked chunk
whose client drops stalls every later request (server.go:377-400 never
releases the barrier); a freed miner re-assigned before flushing its previous
Result leaks that stale Result into the new request; an idle miner dropping
reassigns a stale chunk from an older request (server.go:339-370). Here every
Request written to a miner pushes a full chunk record onto that miner's
pending FIFO; since miners answer sequentially over in-order exactly-once
LSP, each arriving Result pops exactly the chunk it answers, so stale Results
are identified precisely, and a dead miner's unanswered chunks are recovered
individually. The observable contract (assignment order, chunk boundaries,
merge rule, one-in-flight FIFO scheduling) is unchanged.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from ..bitcoin.hash import MAX_U64
from ..bitcoin.message import Message, MsgType, new_request, new_result
from ..lsp.errors import LspError
from ..lsp.server import AsyncServer
from ..utils.config import CacheParams, LeaseParams

logger = logging.getLogger("dbm.scheduler")


class ResultCache:
    """Bounded LRU of finished Results, keyed on the full request
    identity ``(data, lower, upper, target)``.

    submit_with_retry re-submits the identical request after a lost
    Result; without memoization every retry re-ran the whole search. A
    hit replays the recorded answer in O(1) — sound because the answer
    is a pure function of the key: the arg-min (and the
    first-qualifying-nonce difficulty answer) of a fixed range is
    deterministic. The one non-deterministic case — a WEAK difficulty
    merge, where a stock Target-dropping miner answered a chunk — is
    never stored (see Scheduler._finish).
    """

    def __init__(self, size: int):
        self.size = size
        self._d: dict = {}     # insertion order == LRU order (py3.7+)

    def get(self, key):
        hit = self._d.pop(key, None)
        if hit is not None:
            self._d[key] = hit          # refresh recency
        return hit

    def put(self, key, value) -> None:
        self._d.pop(key, None)
        self._d[key] = value
        while len(self._d) > self.size:
            self._d.pop(next(iter(self._d)))

    def __len__(self):
        return len(self._d)


@dataclass
class Chunk:
    job_id: int
    data: str
    lower: int
    upper: int              # exclusive end, as sent on the wire
    target: int = 0         # difficulty target; rides every (re)assignment
    idx: int = 0            # position in the request's ascending chunk order
    # Set when the requesting client drops: the chunk stays in the miner's
    # pending FIFO (its Result must still pop in order) but no longer
    # counts against the miner's availability.
    cancelled: bool = False
    # Lease plane. Each FIFO entry is one ASSIGNMENT: a speculative
    # re-issue pushes a fresh Chunk object (same job/idx/range) onto the
    # takeover miner's FIFO with its own lease, while the blown original
    # stays in its miner's FIFO awaiting the in-order pop.
    assigned_at: float = 0.0   # monotonic stamp set by _assign_chunk
    deadline: float = 0.0      # lease expiry (monotonic); 0 = no lease
    lease_blown: bool = False  # expiry observed (counted once per entry)
    reissued: bool = False     # a speculative copy is already in flight

    @property
    def size(self) -> int:
        """Nonce count the miner actually scans (``Upper`` read inclusive —
        the reference bound quirk, see module docstring)."""
        return self.upper - self.lower + 1


@dataclass
class MinerState:
    conn_id: int
    # Every Request written to this miner, in write order (see module doc).
    pending: list = field(default_factory=list)
    # Lease plane: observed per-chunk throughput (nonces/sec EWMA; None
    # until the first Result), consecutive blown leases, and the
    # quarantine latch (set at quarantine_after blown leases, cleared by
    # any Result pop from this miner).
    rate_ewma: Optional[float] = None
    blown_streak: int = 0
    quarantined: bool = False

    @property
    def available(self) -> bool:
        """Derived, not stored (ADVICE r2): a miner is available iff it has
        no LIVE pending chunk. Cancelled chunks still occupy the FIFO (their
        stale Results pop in order) without blocking new assignments."""
        return not any(not c.cancelled for c in self.pending)


@dataclass
class Request:
    conn_id: int
    data: str
    lower: int
    upper: int              # inclusive on arrival; +1 at load_balance
    target: int = 0         # difficulty target; 0 = exact arg-min (stock)
    job_id: int = 0
    num_chunks: int = 0
    min_hash: int = MAX_U64
    min_nonce: int = 0
    # Difficulty merge plane, per-chunk (VERDICT r4 prefix release).
    # Chunks cover ascending disjoint sub-ranges and each until-speaking
    # miner reports its chunk-FIRST qualifying (hash < target) nonce, so
    # the lowest-INDEX qualifying chunk holds the globally first
    # qualifying nonce — final as soon as every earlier chunk has
    # answered without a hit, regardless of chunks still in flight.
    # (A stock Target-dropping miner reports its chunk ARG-MIN, which may
    # qualify later than its chunk's first hit, weakening the answer to
    # "a qualifying nonce" — see client.submit_until docstring.)
    answered: list = field(default_factory=list)   # bool per chunk idx
    chunk_q: dict = field(default_factory=dict)    # idx -> (nonce, hash)
    # True once any responder answered a target chunk without echoing the
    # target (stock miner in the pool): the merged answer is then only
    # guaranteed qualifying, not guaranteed globally first (ADVICE r4 —
    # surfaced in logs, invisible on the reference-shaped wire).
    weak: bool = False
    started: float = 0.0           # set at dispatch (load_balance)
    # Memoization / observability plane.
    cache_key: Optional[tuple] = None  # (data, lower, upper, target) as received
    queued_at: float = 0.0         # monotonic stamp set at _on_request
    last_alarm: float = 0.0        # last queue-age warning for this request


class Scheduler:
    """Single-actor scheduler over an :class:`AsyncServer`."""

    def __init__(self, server: AsyncServer,
                 lease: Optional[LeaseParams] = None,
                 cache: Optional[CacheParams] = None):
        self.server = server
        self.lease = lease if lease is not None else LeaseParams()
        self.cache = cache if cache is not None else CacheParams()
        self.results: Optional[ResultCache] = (
            ResultCache(self.cache.size) if self.cache.enabled else None)
        self.miners: list[MinerState] = []      # join order, like minersArray
        self.parked: list[Chunk] = []           # chunks of dropped miners
        self.queue: list[Request] = []
        self.current: Optional[Request] = None
        self._next_job_id = 0
        self._pool_rate: Optional[float] = None   # pool-wide throughput EWMA
        self._dispatching = False                 # _maybe_dispatch guard
        self._starved = False                     # no-eligible-miner latch
        # Observability for tests/ops; never drives behavior.
        self.stats = {"results_sent": 0, "dup_results": 0,
                      "leases_blown": 0, "reissues": 0, "quarantines": 0,
                      "cache_hits": 0, "cache_stores": 0,
                      "queue_alarms": 0, "no_eligible_miner": 0}

    # ------------------------------------------------------------- main loop

    async def run(self) -> None:
        """Serve until the LSP server is closed."""
        # The sweep runs even with leases disabled: the queue-age alarm
        # (an observability plane, not a scheduling one) rides it.
        lease_task = asyncio.get_running_loop().create_task(
            self._lease_loop())
        try:
            while True:
                try:
                    conn_id, payload = await self.server.read()
                except LspError:
                    return
                if isinstance(payload, Exception):
                    self._on_drop(conn_id)
                    continue
                try:
                    msg = Message.from_json(payload)
                except ValueError:
                    continue
                if msg.type == MsgType.JOIN:
                    self._on_join(conn_id)
                elif msg.type == MsgType.REQUEST:
                    self._on_request(conn_id, msg)
                elif msg.type == MsgType.RESULT:
                    self._on_result(conn_id, msg)
        finally:
            if lease_task is not None:
                lease_task.cancel()

    async def _lease_loop(self) -> None:
        """Periodic sweep; the only timer the scheduler owns. Checks
        chunk leases (when enabled) and the queued-request age alarm."""
        while True:
            await asyncio.sleep(self.lease.tick_s)
            try:
                if self.lease.enabled:
                    self._check_leases()
                self._check_queue_age()
            except Exception:   # noqa: BLE001 — the sweep must never die
                logger.exception("lease sweep failed; continuing")

    # ---------------------------------------------------------------- events

    def _on_request(self, conn_id: int, msg: Message) -> None:
        key = (msg.data, msg.lower, msg.upper, msg.target)
        if self.results is not None:
            hit = self.results.get(key)
            if hit is not None:
                # O(1) replay: a retried/resubmitted request after a lost
                # Result answers from the memo without touching the pool
                # (and without queueing behind the in-flight request).
                h, nonce = hit
                self._write(conn_id, new_result(h, nonce))
                self.stats["results_sent"] += 1
                self.stats["cache_hits"] += 1
                logger.info("request %r [%d, %d] target=%d answered from "
                            "the result cache", msg.data, msg.lower,
                            msg.upper, msg.target)
                return
        request = Request(conn_id=conn_id, data=msg.data,
                          lower=msg.lower, upper=msg.upper,
                          target=msg.target, cache_key=key,
                          queued_at=time.monotonic())
        self.queue.append(request)
        self._maybe_dispatch()

    def _on_join(self, conn_id: int) -> None:
        miner = MinerState(conn_id=conn_id)
        # A joining miner immediately absorbs one parked chunk, if any
        # (ref: server.go:222-244).
        chunk = self._next_parked()
        if chunk is not None:
            self._assign_chunk(miner, chunk)
        self.miners.append(miner)
        self._maybe_dispatch()

    def _on_result(self, conn_id: int, msg: Message) -> None:
        miner = self._find_miner(conn_id)
        if miner is None or not miner.pending:
            return
        chunk = miner.pending.pop(0)   # the Result answers the oldest Request
        self._observe_result(miner, chunk)
        # A freed miner immediately absorbs one parked chunk
        # (ref: server.go:285-304) — BEFORE the stale-Result return, so a
        # miner freed by a stale answer still rescues parked work. The
        # just-popped (job, idx) is excluded: this very Result is about to
        # answer it, so a parked speculative copy of it is garbage — not
        # work to hand back to the miner that just did it.
        if self.parked and miner.available:
            parked = self._next_parked(skip_key=(chunk.job_id, chunk.idx))
            if parked is not None:
                self._assign_chunk(miner, parked)
        curr = self.current
        if curr is None or chunk.job_id != curr.job_id:
            return  # stale Result for a cancelled/finished request
        if curr.answered[chunk.idx]:
            # Loser of a speculative re-issue race: another assignment of
            # this same (job, idx) already merged. Re-issued copies scan
            # the identical range, so dropping the duplicate changes
            # nothing but the stats.
            self.stats["dup_results"] += 1
            logger.info("duplicate Result for job %d chunk %d from miner %d "
                        "(speculation loser)", curr.job_id, chunk.idx,
                        conn_id)
            return
        if msg.hash < curr.min_hash:
            curr.min_hash = msg.hash
            curr.min_nonce = msg.nonce
        curr.answered[chunk.idx] = True
        if curr.target and msg.target != curr.target and not curr.weak:
            curr.weak = True
            logger.info(
                "difficulty request %d: miner %d answered without the "
                "target extension; the merged result is guaranteed "
                "qualifying, not guaranteed globally first",
                curr.job_id, conn_id)
        if curr.target and msg.hash < curr.target:
            curr.chunk_q[chunk.idx] = (msg.nonce, msg.hash)
        # Prefix release (difficulty only): the lowest-index qualifying
        # chunk is final once every earlier chunk has answered clean —
        # later chunks cover strictly higher nonces and cannot beat it.
        if curr.chunk_q:
            c = min(curr.chunk_q)
            if all(curr.answered[:c]):
                nonce, q_hash = curr.chunk_q[c]
                self._finish(curr, q_hash, nonce, early=True)
                return
        if all(curr.answered):
            # Full barrier: stock request, or target missed everywhere —
            # the exact arg-min. (A difficulty hit always releases above:
            # at the barrier, its qualifying prefix is trivially complete.)
            self._finish(curr, curr.min_hash, curr.min_nonce)

    def _on_drop(self, conn_id: int) -> None:
        miner = self._find_miner(conn_id)
        if miner is not None:
            logger.info("miner %d dropped", conn_id)
            self.miners.remove(miner)
            curr = self.current
            if curr is None:
                return
            # Recover every unanswered chunk of the current request
            # (ref: server.go:326-376, single-chunk version). Chunks whose
            # idx already merged (speculation winner landed first) and
            # chunks with a live speculative copy in another FIFO need no
            # recovery — the copy is tracked independently.
            for chunk in miner.pending:
                if chunk.job_id != curr.job_id or chunk.cancelled:
                    continue
                if curr.answered[chunk.idx] or chunk.reissued:
                    continue
                takeover = next((m for m in self._eligible()), None)
                if takeover is not None:
                    self._assign_chunk(takeover, chunk)
                else:
                    self.parked.append(chunk)
        else:
            logger.info("client %d dropped", conn_id)
            # Purge the dead client's queued requests FIRST so cancelling its
            # in-flight request can't promote another of its own requests.
            self.queue = [r for r in self.queue if r.conn_id != conn_id]
            curr = self.current
            if curr is not None and curr.conn_id == conn_id:
                # Cancel immediately (divergence, see module docstring).
                self._retire()

    # -------------------------------------------------------------- internal

    def _finish(self, curr: Request, h: int, nonce: int,
                early: bool = False) -> None:
        """Answer the client and retire the request. ``early`` = prefix
        release: the job's other chunks are still in flight."""
        self._write(curr.conn_id, new_result(h, nonce))
        self.stats["results_sent"] += 1
        if self.results is not None and curr.cache_key is not None \
                and not curr.weak:
            # Weak merges excluded: "a qualifying nonce" from a stock
            # miner is not a deterministic function of the key.
            self.results.put(curr.cache_key, (h, nonce))
            self.stats["cache_stores"] += 1
        logger.info(
            "request %d served in %.3fs: [%d, %d) over %d chunks%s%s",
            curr.job_id, time.monotonic() - curr.started,
            curr.lower, curr.upper, curr.num_chunks,
            " (prefix release)" if early else "",
            " (weak merge)" if curr.weak else "")
        self._retire()

    def _retire(self) -> None:
        """Retire the in-flight request and start the next.

        Any still-pending chunks of the retiring job (prefix release,
        client drop, or the unanswered losers of speculative re-issues at
        a full-barrier finish) are marked cancelled: the pool frees
        immediately (availability is derived), the FIFO pop discipline for
        their late Results is preserved (they drop at the job_id check),
        and parked chunks — which can only belong to the job in flight —
        are discarded."""
        curr = self.current
        for m in self.miners:
            for c in m.pending:
                if c.job_id == curr.job_id:
                    c.cancelled = True
        self.parked.clear()
        self.current = None
        self._maybe_dispatch()

    def _find_miner(self, conn_id: int) -> Optional[MinerState]:
        for m in self.miners:
            if m.conn_id == conn_id:
                return m
        return None

    def _next_parked(self, skip_key=None) -> Optional[Chunk]:
        """Pop the next parked chunk that still NEEDS executing, discarding
        stale ones: a parked chunk whose idx was meanwhile answered by a
        speculation winner (its copy blew a lease, was re-issued, and the
        re-issue landed first) — or whose ``(job_id, idx)`` matches
        ``skip_key``, the assignment the caller is answering right now —
        would only burn a full scan to pop as a duplicate."""
        curr = self.current
        while self.parked:
            chunk = self.parked.pop(0)
            if curr is None or chunk.job_id != curr.job_id or \
                    curr.answered[chunk.idx]:
                continue
            if skip_key is not None and \
                    (chunk.job_id, chunk.idx) == skip_key:
                continue
            return chunk
        return None

    def _eligible(self) -> list[MinerState]:
        """Miners that may take new work: available and not quarantined."""
        return [m for m in self.miners
                if m.available and not m.quarantined]

    def _maybe_dispatch(self) -> None:
        """Start the next queued request when the pool can take one.

        Re-entrancy guard: an empty-range request finishes INSIDE its own
        dispatch (_load_balance -> _finish -> _retire -> here), so without
        the guard a burst of empty-range requests would recurse one stack
        frame set per request and overflow; with it, the inner call
        returns immediately and the OUTER while loop drains the queue
        iteratively."""
        if self._dispatching:
            return
        self._dispatching = True
        try:
            while self.current is None and self.queue and self._eligible():
                req = self.queue.pop(0)
                if self.results is not None and req.cache_key is not None:
                    hit = self.results.get(req.cache_key)
                    if hit is not None:
                        # A duplicate that queued BEHIND its original
                        # (retry raced the still-in-flight first copy)
                        # replays at pop time: the original finished and
                        # stored while this one waited.
                        self._write(req.conn_id, new_result(*hit))
                        self.stats["results_sent"] += 1
                        self.stats["cache_hits"] += 1
                        logger.info(
                            "queued request %r [%d, %d] answered from "
                            "the result cache at dispatch", req.data,
                            req.lower, req.upper)
                        continue
                self._load_balance(req)
                self._starved = False
        finally:
            self._dispatching = False
        if self.current is None and self.queue and not self._eligible():
            # A dispatch pass found work but no taker: latch so the
            # condition logs once per starvation episode (every later
            # event re-enters here until a miner joins/frees/answers),
            # while the sweep's queue-age alarm keeps counting time.
            if not self._starved:
                self._starved = True
                self.stats["no_eligible_miner"] += 1
                quarantined = sum(1 for m in self.miners if m.quarantined)
                logger.warning(
                    "no eligible miner for %d queued request(s): pool=%d "
                    "quarantined=%d busy=%d — queue is stalled until a "
                    "miner joins, frees, or answers",
                    len(self.queue), len(self.miners), quarantined,
                    sum(1 for m in self.miners
                        if not m.available and not m.quarantined))
        elif not self.queue:
            self._starved = False

    def _load_balance(self, request: Request) -> None:
        """Split the range over every eligible miner.

        Without faults this is ALL miners (the reference invariant: one
        request in flight, so every miner is free at dispatch); quarantined
        or still-busy miners (wedged compute holding a live lease-blown
        chunk) are excluded."""
        pool = self._eligible()
        self.current = request
        self._next_job_id += 1
        request.job_id = self._next_job_id
        request.started = time.monotonic()
        num = len(pool)
        request.upper += 1  # inclusive -> exclusive
        total = request.upper - request.lower
        if total <= 0:
            # Empty/inverted range: answer like an empty scan (the reference
            # would wrap negative totals through uint64 and wedge the pool).
            self._finish(request, MAX_U64, 0)
            return
        individual = total // num
        leftover = total - individual * num
        if individual == 0:  # more miners than nonces
            individual, leftover, num = 1, 0, total
        request.num_chunks = num
        request.answered = [False] * num
        start = request.lower
        for i in range(num):
            end = start + individual + (leftover if i == 0 else 0)
            self._assign_chunk(
                pool[i],
                Chunk(request.job_id, request.data, start, end,
                      target=request.target, idx=i))
            start = end

    def _assign_chunk(self, miner: MinerState, chunk: Chunk) -> None:
        now = time.monotonic()
        chunk.assigned_at = now
        chunk.deadline = now + self._lease_for(miner, chunk)
        chunk.lease_blown = False
        chunk.reissued = False
        miner.pending.append(chunk)
        self._write(miner.conn_id,
                    new_request(chunk.data, chunk.lower, chunk.upper,
                                chunk.target))

    # ---------------------------------------------------------- lease plane

    def _observe_result(self, miner: MinerState, chunk: Chunk) -> None:
        """Per-pop bookkeeping: throughput EWMA, streak reset, quarantine
        lift. Runs for EVERY pop — stale and cancelled chunks were computed
        too, so they are valid throughput samples, and an answer is an
        answer for quarantine purposes ("until it answers again")."""
        alpha = self.lease.ewma_alpha
        if chunk.assigned_at and not chunk.lease_blown and not chunk.target:
            # Two exclusions keep the sample set honest. Blown-lease
            # answers: a wedged miner's eventual 60s "sample" would
            # inflate its (and the pool's) lease to minutes and blunt
            # re-wedge detection. Difficulty chunks: an in-kernel early
            # exit may scan 1% of the range, so size/elapsed would
            # overestimate throughput ~100x and starve every later
            # stock chunk's lease.
            elapsed = max(time.monotonic() - chunk.assigned_at, 1e-6)
            rate = chunk.size / elapsed
            miner.rate_ewma = rate if miner.rate_ewma is None else \
                alpha * rate + (1 - alpha) * miner.rate_ewma
            self._pool_rate = rate if self._pool_rate is None else \
                alpha * rate + (1 - alpha) * self._pool_rate
        miner.blown_streak = 0
        if miner.quarantined:
            miner.quarantined = False
            logger.info("miner %d answered; quarantine lifted",
                        miner.conn_id)
            self._maybe_dispatch()

    def _lease_for(self, miner: MinerState, chunk: Chunk) -> float:
        """Lease duration for assigning ``chunk`` to ``miner``: headroom
        over the EWMA-predicted scan time, clamped below; a flat grace when
        nothing has been observed yet (cold pool)."""
        if not self.lease.enabled:
            return float("inf")
        rate = miner.rate_ewma if miner.rate_ewma is not None \
            else self._pool_rate
        if rate is None or rate <= 0:
            return self.lease.grace_s
        return max(self.lease.floor_s, chunk.size / rate * self.lease.factor)

    def _check_queue_age(self) -> None:
        """Queue-age alarm (ROADMAP open item): a request still queued
        past ``lease.queue_alarm_s`` emits a structured warning — once
        per bound interval per request — so an operator sees a stalled
        queue (empty pool, everything quarantined, or a wedged in-flight
        request ahead of it) instead of silence. Observability only:
        never changes scheduling."""
        bound = self.lease.queue_alarm_s
        if bound <= 0:
            return
        now = time.monotonic()
        for req in self.queue:
            age = now - req.queued_at
            if age < bound or now - req.last_alarm < bound:
                continue
            req.last_alarm = now
            self.stats["queue_alarms"] += 1
            logger.warning(
                "request %r [%d, %d] from client %d queued for %.1fs "
                "(bound %.1fs): pool=%d eligible=%d in_flight=%s",
                req.data, req.lower, req.upper, req.conn_id, age, bound,
                len(self.miners), len(self._eligible()),
                self.current is not None)

    def _check_leases(self) -> None:
        """One lease sweep: blow expired leases (quarantining repeat
        offenders) and speculatively re-issue each blown chunk to an
        eligible miner — first Result wins, the loser pops as a duplicate
        (``_on_result``). A blown chunk with no taker stays watched and is
        re-issued on a later sweep once a miner frees up or joins."""
        curr = self.current
        if curr is None:
            return
        now = time.monotonic()
        for miner in list(self.miners):
            for chunk in list(miner.pending):
                if chunk.cancelled or chunk.job_id != curr.job_id:
                    continue
                if curr.answered[chunk.idx]:
                    continue
                if not chunk.lease_blown:
                    if now < chunk.deadline:
                        continue
                    chunk.lease_blown = True
                    self.stats["leases_blown"] += 1
                    miner.blown_streak += 1
                    logger.warning(
                        "miner %d blew the lease on job %d chunk %d "
                        "[%d, %d) after %.2fs (streak %d)",
                        miner.conn_id, chunk.job_id, chunk.idx,
                        chunk.lower, chunk.upper, now - chunk.assigned_at,
                        miner.blown_streak)
                    if (miner.blown_streak >= self.lease.quarantine_after
                            and not miner.quarantined):
                        miner.quarantined = True
                        self.stats["quarantines"] += 1
                        logger.warning(
                            "miner %d quarantined after %d consecutive "
                            "blown leases; no new assignments until it "
                            "answers", miner.conn_id, miner.blown_streak)
                if chunk.reissued:
                    continue
                takeover = next(
                    (m for m in self._eligible() if m is not miner), None)
                if takeover is None:
                    continue   # retry next sweep
                chunk.reissued = True
                self.stats["reissues"] += 1
                logger.warning(
                    "speculatively re-issuing job %d chunk %d [%d, %d) "
                    "from miner %d to miner %d",
                    chunk.job_id, chunk.idx, chunk.lower, chunk.upper,
                    miner.conn_id, takeover.conn_id)
                self._assign_chunk(
                    takeover,
                    Chunk(chunk.job_id, chunk.data, chunk.lower,
                          chunk.upper, target=chunk.target, idx=chunk.idx))

    def _write(self, conn_id: int, msg: Message) -> None:
        try:
            self.server.write(conn_id, msg.to_json())
        except LspError:
            # The drop event for this connection is already in flight; the
            # drop handler will repair the assignment.
            logger.info("write to %d failed; awaiting drop event", conn_id)
