"""The scheduler: shard nonce ranges over an elastic miner pool, merge argmins.

Faithful state machine of the reference coordinator
(ref: bitcoin/server/server.go:19-403). Since ISSUE 11 the one ~1.8k-line
class is SPLIT into two planes joined by an explicit internal interface,
with this module keeping only the REQUEST STATE MACHINE — arrival,
dispatch decisions, the merge rules and barriers, retirement — plus the
compatibility surface every earlier PR's tests and tools drive:

- :mod:`.tenant_plane` — conn lifecycle, admission/shedding, the
  indexed request queue, QoS/DRR state, trace buffers + sampling, and
  the queue-age alarms;
- :mod:`.miner_plane` — the pool roster and per-miner pending FIFOs,
  leases (EWMA sizing, speculative re-issue, quarantine, the
  position-aware FIFO clock), the stripe planner, parked-chunk
  recovery, throughput windows + pool EWMA, and coalescing-window
  slots;
- the interface between them: **grant** (``MinerPlane.assign_chunk``),
  **complete** (``MinerPlane.pop_result`` returning the popped
  ``(miner, chunk)`` for this module to merge), and **lease-event**
  (the ``blown``/``reissue``/``quarantine``/``quarantine_lifted``/
  ``park`` callback this module turns into trace/flight/log fanout).
  ``apps/replicas.py`` instantiates N of these schedulers as replicas,
  each owning a miner-pool slice.

Behavioral contract (unchanged through the split — dbmcheck's scenario
pack re-proves it on every run):

- FIFO request queue, ONE request in flight at a time on the stock path
  (deliberate reference simplification — no pipeline parallelism).
- ``load_balance``: bounds become exclusive (``upper += 1``); even split
  ``total // num_miners`` with the remainder given to the FIRST miner; when
  there are more miners than nonces, only ``total`` miners get 1-nonce chunks
  (ref: server.go:165-205).
- Bound quirk preserved for bit parity: chunks are sent with EXCLUSIVE upper
  bounds but the miner treats ``Upper`` as inclusive (ref: miner.go:51-52),
  so each chunk scans one extra nonce and the system as a whole scans
  ``[0, maxNonce+1]``.
- Request striping (ISSUE 4, ``DBM_STRIPE``): each miner's even-split
  share may be subdivided into up to ``StripeParams.depth`` contiguous
  chunks sized at ``StripeParams.chunk_s`` seconds of work from its
  throughput EWMA; chunk indices still ascend globally, so the merge
  rules below are untouched; a cold pool or ``DBM_STRIPE=0`` reproduces
  the reference one-chunk-per-miner split bit-for-bit.
- Result merge: strict ``<`` on the uint64 hash; barrier releases the Result
  to the client when every chunk of the request has been answered
  (ref: server.go:257-325).
- Difficulty extension + prefix release (VERDICT r4): a Request carrying
  ``Target`` fans out with the target on every chunk; the lowest-index
  qualifying chunk is final once every earlier chunk answered clean and
  is released IMMEDIATELY; a stock Target-dropping miner weakens the
  merge to "a qualifying nonce" (``Request.weak``); no hit anywhere
  degrades to the exact arg-min.
- Miner drop: reassign its unanswered chunks to available miners, else park
  them; parked chunks are re-issued when a miner joins or frees up
  (ref: server.go:326-376, 222-244, 285-304).
- Client drop: the in-flight request is cancelled immediately — miners are
  freed, parked chunks cleared, the next queued request starts.
- Robustness plane (PNPCoin-style lease discipline, arXiv 2208.12628):
  every assigned chunk carries a LEASE; expiry speculatively RE-ISSUES
  the chunk (first Result wins, the loser pops as a stale duplicate);
  ``quarantine_after`` consecutive blown leases QUARANTINE a miner until
  it answers again; desperation dispatch serves a fully-quarantined pool
  as a last resort. Leases change scheduling latency under faults only —
  never the answer.
- Fair-share QoS dispatch plane (ISSUE 5, ``DBM_QOS``): tenants (client
  conn ids) are admitted through token buckets, large requests are
  CHUNKED and granted incrementally by deficit-round-robin (grant share
  converges to the configured weights), overload sheds the OLDEST queued
  request by closing its conn, and the coalescing grant window
  (ISSUE 9, ``DBM_COALESCE``) stacks small cross-request grants onto one
  miner for a shared device launch. ``DBM_QOS=0`` reproduces stock FIFO
  dispatch bit-for-bit.
- Observability (ISSUE 3/10): every counter lives in a per-scheduler
  metrics Registry mounted under ``sched.``; each SAMPLED request
  (``DBM_TRACE_SAMPLE``, default 1.0 = every request) records a trace
  stitched with miner-side spans, dumped on age alarms and exportable
  as Perfetto JSON.

Hot-path scaling (ISSUE 11, measured by ``bench.py detail.load``): the
recv loop drains up to ``DBM_RECV_BATCH`` already-delivered messages per
awaited read; the queue is indexed per tenant (O(1) pops/purges, O(active)
pump scans); the DRR ring holds backlogged tenants only; the QoS pump
early-exits without touching heads when the pool has no capacity; and
unsampled requests skip trace allocation entirely.

Bookkeeping divergence from the reference (deliberate): the reference tracks
one recorded chunk per miner plus a positional ``responsibleMiners`` list,
which deadlocks or double-counts in several reachable states. Here every
Request written to a miner pushes a full chunk record onto that miner's
pending FIFO; since miners answer sequentially over in-order exactly-once
LSP, each arriving Result pops exactly the chunk it answers, so stale
Results are identified precisely, and a dead miner's unanswered chunks are
recovered individually. The observable contract (assignment order, chunk
boundaries, merge rule, one-in-flight FIFO scheduling) is unchanged.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Optional

from ..bitcoin.hash import MAX_U64, hash_op
from ..bitcoin.message import Message, MsgType, new_result
from ..lsp.errors import LspError
from ..lsp.server import AsyncServer
from ..utils import sanitize as _sanitize
from ..utils import trace as _tracing
from ..utils._env import int_env as _int_env
from ..utils.config import AdaptParams, CacheParams, CoalesceParams, \
    LeaseParams, QosParams, StripeParams, VerifyParams, adapt_from_env, \
    coalesce_from_env, qos_from_env, stripe_from_env, verify_from_env
from ..utils.metrics import (Registry, RequestTrace, ensure_emitter,
                             registry as process_registry)
from . import capture as _capture
from .adapt import AdaptPlane
from .miner_plane import Chunk, MinerPlane, MinerState
from .qos import LAZY_REMOVE
from .tenant_plane import TenantPlane

logger = logging.getLogger("dbm.scheduler")

__all__ = ["Chunk", "MinerState", "Request", "ResultCache", "Scheduler",
           "STAT_COUNTERS"]

#: Every monotonic counter the scheduler keeps (the old ``stats`` dict keys
#: plus the ISSUE 3 additions). ``Scheduler.stats`` is a dict view of these.
STAT_COUNTERS = (
    "results_sent", "dup_results", "leases_blown", "reissues",
    "quarantines", "cache_hits", "cache_misses", "cache_stores",
    "queue_alarms", "inflight_alarms", "no_eligible_miner",
    "desperation_dispatch", "leases_blown_spurious", "chunks_striped",
    "qos_grants", "qos_shed", "qos_window_grants",
    # Verification tier (ISSUE 16).
    "claims_checked", "claims_failed", "audits_issued",
    "audits_passed", "audits_failed", "audits_inconclusive",
    "trust_decays_claim", "trust_decays_audit",
    # Federation (ISSUE 20): repeat-JOIN rate-hint refreshes absorbed
    # in place instead of minting duplicate roster entries.
    "rate_hints_refreshed",
)


class ResultCache:
    """Bounded LRU of finished Results, keyed on the full request
    identity ``(data, lower, upper, target)``.

    submit_with_retry re-submits the identical request after a lost
    Result; without memoization every retry re-ran the whole search. A
    hit replays the recorded answer in O(1) — sound because the answer
    is a pure function of the key: the arg-min (and the
    first-qualifying-nonce difficulty answer) of a fixed range is
    deterministic. The one non-deterministic case — a WEAK difficulty
    merge, where a stock Target-dropping miner answered a chunk — is
    never stored (see Scheduler._finish).

    Replica sharding (ISSUE 11) passes ONE instance to every replica as
    the shared replay tier: a tenant re-hashed to a different replica
    after a takeover replays its lost answers without re-scanning.
    """

    def __init__(self, size: int):
        self.size = size
        self._d: dict = {}     # insertion order == LRU order (py3.7+)

    def get(self, key):
        hit = self._d.pop(key, None)
        if hit is not None:
            self._d[key] = hit          # refresh recency
        return hit

    def put(self, key, value) -> None:
        self._d.pop(key, None)
        self._d[key] = value
        while len(self._d) > self.size:
            self._d.pop(next(iter(self._d)))

    def __len__(self):
        return len(self._d)


@dataclass
class Request:
    conn_id: int
    data: str
    lower: int
    upper: int              # inclusive on arrival; +1 at load_balance
    target: int = 0         # difficulty target; 0 = exact arg-min (stock)
    job_id: int = 0
    num_chunks: int = 0
    min_hash: int = MAX_U64
    min_nonce: int = 0
    # Difficulty merge plane, per-chunk (VERDICT r4 prefix release).
    # Chunks cover ascending disjoint sub-ranges and each until-speaking
    # miner reports its chunk-FIRST qualifying (hash < target) nonce, so
    # the lowest-INDEX qualifying chunk holds the globally first
    # qualifying nonce — final as soon as every earlier chunk has
    # answered without a hit, regardless of chunks still in flight.
    answered: list = field(default_factory=list)   # bool per chunk idx
    chunk_q: dict = field(default_factory=dict)    # idx -> (nonce, hash)
    # True once any responder answered a target chunk without echoing the
    # target (stock miner in the pool): the merged answer is then only
    # guaranteed qualifying, not guaranteed globally first (ADVICE r4).
    weak: bool = False
    started: float = 0.0           # set at dispatch (load_balance)
    # Memoization / observability plane.
    cache_key: Optional[tuple] = None  # (data, lower, upper, target)
    queued_at: float = 0.0         # monotonic stamp set at _on_request
    qkey: int = 0                  # tenant-plane queue index stamp
    last_alarm: float = 0.0        # last queue-age warning for this request
    # Separate stamp for the in-flight age alarm: a request that alarmed
    # while QUEUED must not have its first in-flight alarm suppressed for
    # a full extra bound after dispatch.
    last_inflight_alarm: float = 0.0
    trace: object = None           # RequestTrace (or NULL_TRACE, unsampled)
    # QoS dispatch plane (ISSUE 5). ``qos_mode`` is "" until dispatch,
    # then "wholesale" (stock path: every chunk assigned at dispatch) or
    # "chunked" (chunk plan held centrally, granted incrementally).
    qos_mode: str = ""
    chunk_bounds: list = None      # chunked mode: [(lo, up_excl), ...]
    next_chunk: int = 0            # chunked mode: first ungranted idx
    granted_chunks: int = 0        # chunks handed to miners so far
    # Verification tier (ISSUE 16): outstanding audits sampled from this
    # request's chunks. The reply HOLDS until they resolve — an audit
    # that lands after the client was answered could only detect, never
    # prevent, a sentinel-without-scan lie reaching the client.
    audit_holds: int = 0

    def __post_init__(self):
        # Every Request carries a trace from birth, even when constructed
        # directly (tests, programmatic drivers) rather than via
        # _on_request — the scheduler records events unconditionally.
        # _on_request passes the tenant plane's (possibly sampled) trace.
        if self.trace is None:
            self.trace = RequestTrace(data=self.data, lower=self.lower,
                                      upper=self.upper, target=self.target,
                                      client=self.conn_id)


@dataclass
class AuditRecord:
    """One outstanding probabilistic audit (ISSUE 16): a random
    subwindow of a completed argmin chunk, re-granted to a DISJOINT
    miner under a fresh job id that never enters ``_inflight`` — the
    audit Result routes here (side table) instead of the merge path,
    so audits survive the request's retirement and are invisible to
    lease sweeps and recovery (both skip chunks whose job is not in
    flight; the scheduler's own sweep expires them via ``deadline``
    instead, so a wedged auditor cannot hold a reply forever). While
    outstanding, the audited request's reply HOLDS (``audit_holds``)
    — and on failure the AUDITOR's verified sub-argmin merges in its
    place, so a full-window audit repairs the answer, not just the
    liar's reputation."""
    job_id: int          # the ORIGINAL job the audited claim answered
    idx: int             # original chunk idx (fanout/logging context)
    suspect: int         # conn id of the miner whose claim is audited
    auditor: int         # conn id of the disjoint re-executing miner
    lower: int           # audit subwindow, inclusive bounds (the
    upper: int           # reference's Upper-read-inclusive quirk)
    claimed_hash: int    # the suspect's chunk-argmin claim
    claimed_nonce: int
    deadline: float = float("inf")   # monotonic expiry (sweep tick)


class Scheduler:
    """Single-actor scheduler over an :class:`AsyncServer` — the
    request state machine over the tenant/miner plane pair."""

    #: Compat re-export: the throughput-window span now lives on the
    #: miner plane (tests and embedded drivers read it here).
    RATE_WINDOW_S = MinerPlane.RATE_WINDOW_S

    def __init__(self, server: AsyncServer,
                 lease: Optional[LeaseParams] = None,
                 cache: Optional[CacheParams] = None,
                 stripe: Optional[StripeParams] = None,
                 qos: Optional[QosParams] = None,
                 coalesce: Optional[CoalesceParams] = None,
                 adapt: Optional[AdaptParams] = None,
                 clock=None,
                 result_cache: Optional[ResultCache] = None,
                 recv_batch: Optional[int] = None,
                 trace_sample: Optional[float] = None,
                 capture=None,
                 verify: Optional[VerifyParams] = None,
                 audit_rng: Optional[random.Random] = None):
        self.server = server
        lease = lease if lease is not None else LeaseParams()
        self.cache = cache if cache is not None else CacheParams()
        # Env-defaulted (unlike lease/cache) so the tier-1 knob-off matrix
        # leg (DBM_STRIPE=0) exercises the Go-parity split through every
        # existing harness without threading a parameter into each test.
        stripe = stripe if stripe is not None else stripe_from_env()
        # Env-defaulted like stripe: DBM_QOS=0 pins the stock FIFO path
        # through every existing harness (the tier-1 matrix leg).
        qos = qos if qos is not None else qos_from_env()
        # Env-defaulted like stripe/qos: DBM_COALESCE=0 pins stock grant
        # accounting (no windows, no shared live slots) bit-for-bit.
        coalesce = (coalesce if coalesce is not None
                    else coalesce_from_env())
        # Verification tier (ISSUE 16): env-defaulted like stripe/qos so
        # the tier-1 knob-off matrix leg (DBM_VERIFY=0) pins the
        # believe-every-Result stock path bit-for-bit. ``audit_rng``
        # injects a seeded stream (the schedcheck explorer's fork
        # discipline) so audit draws — probability AND subwindow — are
        # a function of the explored schedule, not of global RNG state.
        verify = verify if verify is not None else verify_from_env()
        self._audit_rng = (audit_rng if audit_rng is not None
                           else random.Random())
        #: Outstanding audits by audit job id (ids come off the shared
        #: _next_job_id counter, so they can never collide with a live
        #: request). Empty dict when audits are off — the hot-path
        #: routing guard is one truthiness test.
        self._audits: dict[int, AuditRecord] = {}
        # ``result_cache`` overrides with a SHARED instance (the replica
        # tier's replay plane); otherwise each scheduler owns one.
        self.results: Optional[ResultCache] = (
            result_cache if result_cache is not None
            else (ResultCache(self.cache.size) if self.cache.enabled
                  else None))
        # Batched recv drain (ISSUE 11): after each awaited read, up to
        # this many already-delivered messages are handled without a
        # loop round-trip. 1 = stock one-message-per-await.
        self._recv_batch = max(1, recv_batch if recv_batch is not None
                               else _int_env("DBM_RECV_BATCH", 64))
        self._read_nowait = getattr(server, "read_nowait", None)
        # Federation (ISSUE 20, DBM_GATEWAY default 1): with the knob on,
        # a repeat JOIN from a conn already registered as a live miner
        # refreshes its rate hint in place (the GatewayMiner pool-sum
        # path). 0 = bit-for-bit stock: every JOIN mints a fresh miner
        # (the knob-off matrix leg pin). Read once at construction like
        # the recv-batch knob so a live scheduler's behavior is stable.
        self._gateway = _int_env("DBM_GATEWAY", 1) != 0
        # In-flight requests by job_id, oldest first (dict preserves
        # insertion order). The stock FIFO path keeps AT MOST ONE entry
        # — the reference's one-request-in-flight invariant — while the
        # QoS plane runs several concurrently; ``current`` (below) stays
        # the single-request view every existing caller reads. The dict
        # object is shared BY REFERENCE with the miner plane (its sweep
        # and recovery consult it) and must never be reassigned.
        self._inflight: dict[int, Request] = {}
        self._next_job_id = 0
        self._chunked_inflight = 0                # count of chunked mode
        # Lazy-DRR per-tenant indexes (ISSUE 12, DBM_QOS_LAZY): the
        # tenant's chunked in-flight requests with ungranted chunks
        # (insertion order = activation order = oldest first) and its
        # total in-flight request count — what makes the lazy pump's
        # per-visit head pricing O(1) instead of an O(inflight +
        # backlogged tenants) heads rebuild per grant. Maintained
        # unconditionally (dict ops on retire/dispatch are noise); read
        # only by the lazy pump.
        self._qos_chunked_reqs: dict = {}         # tenant -> {job: Request}
        self._tenant_inflight: dict = {}          # tenant -> request count
        self._dispatching = False                 # _maybe_dispatch guard
        self._starved = False                     # no-eligible-miner latch
        # Observability plane (ISSUE 3): a per-scheduler registry (so unit
        # tests see exactly THIS instance's counts), mounted into the
        # process registry under "sched." for the emitter/bench snapshot.
        # The prefix is FIXED and latest-wins by design: production runs
        # one scheduler per process, and a stable key set is what keeps
        # emitter lines and BENCH snapshots diffable across restarts. A
        # process deliberately embedding several live schedulers (e.g.
        # the in-process replica tier) should read each instance's own
        # `.metrics`/`.stats` — only the newest is visible through the
        # process snapshot. Never drives behavior.
        self.metrics = Registry()
        process_registry().mount("sched", self.metrics)
        ensure_emitter()
        # Runtime sanitizer (ISSUE 7, DBM_SANITIZE=1): installs the
        # process slow-callback watchdog and pins the hot dispatch
        # structures (miners/queue/_inflight and everything reachable
        # from the event handlers) to the actor's own thread. None when
        # the knob is off — the guard below is then one attribute test.
        self._owner = (_sanitize.ThreadOwner(
            "Scheduler hot state (miners/queue/_inflight)")
            if _sanitize.ensure_sanitizer() else None)
        self._counters = {n: self.metrics.counter(n) for n in STAT_COUNTERS}
        self._cache_hit_ratio = self.metrics.gauge("cache_hit_ratio")
        # Cross-process tracing plane (ISSUE 10, DBM_TRACE=1 default).
        self._trace_on = _tracing.ensure_tracer()
        # Workload capture plane (ISSUE 15, DBM_CAPTURE, default OFF):
        # with the knob off (and no explicit instance) this is None and
        # every hook below is one attribute test — no capture state
        # exists anywhere, the bit-for-bit stock contract the knob-off
        # matrix leg pins. ``capture=`` injects an explicit instance
        # (harness legs, tests); ``capture=False`` REFUSES env arming —
        # the replay harness must never let a lingering DBM_CAPTURE=1
        # open (and truncate) the very file it is replaying (code
        # review); env-driven processes share ONE capture so the
        # in-process replica tier interleaves into one trace.
        if capture is False:
            self.capture = None
        elif capture is not None:
            self.capture = capture
        else:
            self.capture = _capture.ensure_from_env()
        # The two planes (ISSUE 11 split; see module docstring).
        # ``clock`` (ISSUE 8) feeds the admission token buckets: the
        # deterministic-schedule explorer (analysis/schedcheck) injects
        # its virtual clock here so bucket refills are a function of the
        # explored schedule, not of wall time. The scheduler's own
        # lease/trace stamps read ``time.monotonic`` directly — the
        # explorer patches that; this parameter exists because the
        # bucket CAPTURES its clock at construction.
        self.tenant_plane = TenantPlane(
            self.metrics, self._count, qos, lease,
            clock=clock, close_conn=getattr(server, "close_conn", None),
            trace_on=self._trace_on, trace_sample=trace_sample,
            capture=self.capture)
        if self.capture is not None:
            # Workload-shape context a replay reproduces (the capture
            # records knob VALUES, never identities). ``transport``
            # lets the replay side gate latency fidelity only against
            # a SAME-transport capture — a real-LSP capture replayed
            # on detnet differs by the transport's own latency floor,
            # not by workload shape (found in a live 3-process drive).
            self.capture.config(max_queued=qos.max_queued,
                                wholesale_s=qos.wholesale_s,
                                qos=bool(qos.enabled),
                                transport=type(server).__name__)
        self.miner_plane = MinerPlane(
            self.metrics, self._count, lease, stripe, coalesce,
            write=self._write, inflight=self._inflight,
            trace_get=self.tenant_plane.traces.get,
            lease_event=self._on_lease_event,
            dispatch=self._maybe_dispatch, trace_on=self._trace_on,
            verify=verify)
        # Self-tuning control plane (ISSUE 13, DBM_ADAPT, default OFF):
        # env-defaulted like stripe/qos/coalesce so the knob pins the
        # stock shape through every existing harness. Disabled = None —
        # every hook below is one attribute test, no controller state
        # exists anywhere (the DBM_ADAPT=0 parity contract). Seeded
        # with the LIVE param blocks' statics so an adaptive run starts
        # at the static configuration and departs only on evidence;
        # the injected clock is the same one the admission buckets get.
        adapt = adapt if adapt is not None else adapt_from_env()
        if adapt.enabled:
            # Controllers only mount over LIVE knobs (the "never
            # re-enable what an operator turned off" contract): the
            # chunk controller's signal and both its knobs' consumers
            # need the QoS chunked path, the window bound is consulted
            # only by QoS window grants, and the admission gate sits
            # inside the qos-enabled arrival path — with the owning
            # plane off, mounting a controller would tune a dead knob
            # and report misleading gauges. The 0-disables convention
            # on chunk_s/small_s (AdaptPlane ctor) carries the flag.
            from dataclasses import replace as _dc_replace
            eff = _dc_replace(adapt,
                              admit=adapt.admit and qos.enabled)
            self.adapt_plane: Optional[AdaptPlane] = AdaptPlane(
                eff, self.metrics, clock,
                chunk_s=qos.chunk_s if qos.enabled else 0.0,
                small_s=coalesce.small_s
                if (qos.enabled and coalesce.enabled) else 0.0,
                trace_on=self._trace_on)
        else:
            self.adapt_plane = None
        self._sync_backlog_hook()

    # Param blocks live on the planes (single source of truth); these
    # properties keep the pre-split read/WRITE surface — tests and
    # embedded drivers reconfigure a live scheduler by assignment.

    @property
    def lease(self) -> LeaseParams:
        return self.miner_plane.lease

    @lease.setter
    def lease(self, value: LeaseParams) -> None:
        self.miner_plane.lease = value
        self.tenant_plane.lease = value

    @property
    def stripe(self) -> StripeParams:
        return self.miner_plane.stripe

    @stripe.setter
    def stripe(self, value: StripeParams) -> None:
        self.miner_plane.stripe = value

    @property
    def coalesce(self) -> CoalesceParams:
        return self.miner_plane.coalesce

    @coalesce.setter
    def coalesce(self, value: CoalesceParams) -> None:
        self.miner_plane.coalesce = value

    @property
    def verify(self) -> VerifyParams:
        return self.miner_plane.verify

    @verify.setter
    def verify(self, value: VerifyParams) -> None:
        self.miner_plane.verify = value

    @property
    def qos(self) -> QosParams:
        return self.tenant_plane.qos

    @qos.setter
    def qos(self, value: QosParams) -> None:
        self.tenant_plane.qos = value
        self._sync_backlog_hook()

    def _sync_backlog_hook(self) -> None:
        """(Un)register the lazy-DRR ring-entry hook to match the live
        QoS params — tests reconfigure a live scheduler by assignment,
        and the hook must track the ``lazy`` knob with them. On
        REGISTRATION the ring is seeded from the backlog that already
        exists (queued tenants + chunked in-flight requests with
        ungranted chunks): the hook only fires on FUTURE enqueues, so
        without the seed a request queued before the reconfigure would
        never enter the ring and never be granted (code review)."""
        lazy = self.qos.enabled and self.qos.lazy
        if not lazy:
            self.tenant_plane.backlog_hook = None
            return
        self.tenant_plane.backlog_hook = self.qos_plane.backlog_enter
        for tenant in self.tenant_plane.backlog_tenants():
            self.qos_plane.backlog_enter(tenant)
        for tenant in self._qos_chunked_reqs:
            self.qos_plane.backlog_enter(tenant)

    # ---------------------------------------------------------- public view

    @property
    def current(self) -> Optional[Request]:
        """The OLDEST in-flight request, or None. Under the stock FIFO
        path this is the reference's single in-flight request; under QoS
        several may be in flight — callers that need them all read
        :attr:`inflight`."""
        return next(iter(self._inflight.values()), None)

    @property
    def inflight(self) -> dict:
        """Read-only view of every in-flight request by job id."""
        return dict(self._inflight)

    # Plane-state views: the pre-split attribute surface, now owned by
    # the planes (tests, bench probes, and the dbmcheck harness read
    # these; the planes hold the live objects).

    @property
    def miners(self) -> list:
        return self.miner_plane.miners

    @property
    def parked(self) -> list:
        return self.miner_plane.parked

    @property
    def queue(self) -> list:
        """Arrival-ordered COPY of the queued requests (read-only in
        effect — appends to it are discarded; inject via
        ``tenant_plane.enqueue``)."""
        return self.tenant_plane.queue

    @property
    def qos_plane(self):
        return self.tenant_plane.qos_plane

    @property
    def traces(self):
        return self.tenant_plane.traces

    @property
    def _tracks(self):
        return self.tenant_plane.tracks

    @property
    def _pool_rate(self):
        return self.miner_plane.pool_rate

    @_pool_rate.setter
    def _pool_rate(self, rate) -> None:
        self.miner_plane.pool_rate = rate

    # ------------------------------------------------------- stats / metrics

    @property
    def stats(self) -> dict:
        """Read-only dict view of every counter (the pre-ISSUE-3 ``stats``
        dict surface, now backed by the registry)."""
        return {n: c.value for n, c in self._counters.items()}

    def _count(self, name: str, n: int = 1) -> None:
        self._counters[name].inc(n)

    def _cache_lookup(self, key, count_miss: bool = True):
        """ResultCache get + hit/miss/ratio accounting in one place.

        ``count_miss=False`` for the dispatch-time RE-check of a key that
        already missed at enqueue: counting it again would charge every
        normally-dispatched request two misses and skew the hit ratio."""
        hit = self.results.get(key)
        if hit is not None:
            self._count("cache_hits")
        elif count_miss:
            self._count("cache_misses")
        hits = self._counters["cache_hits"].value
        total = hits + self._counters["cache_misses"].value
        self._cache_hit_ratio.set(hits / total if total else 0.0)
        return hit

    def trace(self, request_id: int):
        """The recorded :class:`RequestTrace` for a job id (or a
        ``cache:N`` replay key); None when unknown, evicted, or the
        request was unsampled (``DBM_TRACE_SAMPLE``)."""
        return self.tenant_plane.traces.get(request_id)

    def _dump_trace(self, why: str, trace) -> None:
        self.tenant_plane.dump_trace(why, trace)

    def _fold_span(self, trace, conn_id: int, chunk: Chunk,
                   span: Optional[dict]) -> None:
        """Stitch one miner-side chunk span (the Result's Span wire
        extension) into the request's trace as a ``miner_span`` event
        (ISSUE 10). The span vocabulary is whitelisted (a hostile peer
        cannot inject arbitrary keys into dumps), the DOMINANT phase is
        named inline so a stalled request's dump reads "force stalled on
        miner 7" without arithmetic, and the owning miner's export track
        is registered (retired again on miner drop). Unsampled requests
        (NULL trace) skip the fold entirely."""
        if span is not None and self.capture is not None:
            # Capture sees every served span (ISSUE 15) — independent of
            # trace sampling and of the trace plane itself, because the
            # fidelity report's per-phase medians must describe the
            # WORKLOAD, not the sampled subset.
            self.capture.span(span)
        if span is None or trace is None or trace.null \
                or not self._trace_on:
            return
        clean = {}
        for key in _tracing.SPAN_PHASES + _tracing.SPAN_EXTRAS:
            v = span.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                clean[key] = v
        if not clean:
            return
        self.tenant_plane.track_miner(conn_id)
        slow = _tracing.slow_phase(clean)
        if slow is not None:
            clean["slow"] = slow
        trace.event("miner_span", miner=conn_id, idx=chunk.idx, **clean)

    def export_trace(self, path: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable) of every retained
        request trace: one track per tenant (scheduler process) and per
        miner, request slices + instant fault events + the stitched
        miner-side phase spans (``scripts/dbmtrace.py`` is the CLI
        wrapper). Returns the document; ``path`` also writes it."""
        import json as _json
        dicts = []
        for _key, t in self.tenant_plane.traces.items():
            d = t.to_dict()
            d["t0"] = t.t0
            dicts.append(d)
        tenant_tracks, miner_tracks = {}, {}
        for labels, tid in self.tenant_plane.tracks.items("trace_track"):
            labels = dict(labels)
            if "tenant" in labels:
                tenant_tracks[labels["tenant"]] = tid
            if "miner" in labels:
                miner_tracks[labels["miner"]] = tid
        doc = _tracing.to_chrome_trace(dicts, tenant_tracks=tenant_tracks,
                                       miner_tracks=miner_tracks)
        if path:
            with open(path, "w", encoding="utf-8") as fh:
                _json.dump(doc, fh, sort_keys=True)
        return doc

    def _track_tenant(self, conn_id: int) -> None:
        self.tenant_plane.track_tenant(conn_id)

    # ------------------------------------------------------------- main loop

    async def run(self) -> None:
        """Serve until the LSP server is closed."""
        # The sweep runs even with leases disabled: the queue-age alarm
        # (an observability plane, not a scheduling one) rides it.
        lease_task = asyncio.get_running_loop().create_task(
            self._lease_loop())
        try:
            while True:
                try:
                    conn_id, payload = await self.server.read()
                except LspError:
                    return
                self.handle(conn_id, payload)
                # Batched recv (ISSUE 11): drain what is already
                # delivered without a loop round-trip per message — at
                # 10k conns the per-await wakeups dominate the recv
                # path. Handlers run in identical order either way;
                # DBM_RECV_BATCH=1 restores one-message-per-await.
                if self._recv_batch > 1 and self._read_nowait is not None:
                    for _ in range(self._recv_batch - 1):
                        item = self._read_nowait()
                        if item is None:
                            break
                        self.handle(item[0], item[1])
        finally:
            if lease_task is not None:
                lease_task.cancel()

    def handle(self, conn_id: int, payload) -> None:
        """Handle ONE transport item — a payload or a conn-death
        exception. Public so embedding drivers (the replica router,
        apps/replicas.py) can feed a scheduler without owning its read
        loop."""
        if isinstance(payload, Exception):
            self._on_drop(conn_id)
            return
        try:
            msg = Message.from_json(payload)
        except ValueError:
            return
        if msg.type == MsgType.JOIN:
            self._on_join(conn_id, msg)
        elif msg.type == MsgType.REQUEST:
            self._on_request(conn_id, msg)
        elif msg.type == MsgType.RESULT:
            self._on_result(conn_id, msg)

    async def _lease_loop(self) -> None:
        """Periodic sweep; the only timer the scheduler owns. Checks
        chunk leases (when enabled) and the queued-request age alarm."""
        while True:
            await asyncio.sleep(self.lease.tick_s)
            try:
                self.sweep()
            except Exception:   # noqa: BLE001 — the sweep must never die
                logger.exception("lease sweep failed; continuing")

    def sweep(self) -> None:
        """One sweep tick: lease checks, age alarms, tenant GC. Public
        so the replica tier can drive each replica's sweep."""
        if self.lease.enabled:
            self._check_leases()
        if self._audits:
            self._expire_audits()
        self.miner_plane.decay_rate_hints()
        self._check_queue_age()
        if self.capture is not None:
            # Periodic pool-composition snapshot (ISSUE 15): what a
            # replay needs to model the serving side — miner count and
            # rate EWMAs — plus queue/in-flight depth for context.
            self.capture.maybe_snapshot(
                miners=len(self.miner_plane.miners),
                rates=[m.rate_ewma for m in self.miner_plane.miners
                       if m.rate_ewma],     # cold miners carry None
                queued=self.tenant_plane.queue_len(),
                inflight=len(self._inflight))
        if self.adapt_plane is not None:
            self._apply_adapt()
        if self.qos.enabled:
            # backlog_tenants is exactly the queued conn-id set, read
            # from the per-tenant index — no O(queued-requests) list
            # materialization per tick (code review).
            busy = (set(self.tenant_plane.backlog_tenants())
                    | {r.conn_id for r in self._inflight.values()})
            self.tenant_plane.gc(busy)

    def _apply_adapt(self) -> None:
        """One self-tuning tick (ISSUE 13; rides the sweep): feed the
        admission controller the oldest queued request's age, then
        apply whatever knob values the controllers moved — the
        chunk/stripe seconds track ONE controlled value (both knobs
        mean "seconds of work per dispatch unit"), the coalescing
        bound replaces ``small_s``, and the admission rate lives
        inside the plane's own bucket. Changes go through the param-
        block property setters, so reconfiguration follows the exact
        path tests already drive (frozen replace; ``__post_init__``
        re-validation; backlog-hook re-sync). Bounds of already-
        activated chunk plans are immutable — a new chunk_s affects
        only future activations, so no merge invariant can move."""
        from dataclasses import replace as _replace
        head = self.tenant_plane.head()
        age = (time.monotonic() - head.queued_at) if head is not None \
            else 0.0
        # Pool rate divergence (ISSUE 14, DBM_ADAPT_PER_MINER): the
        # per-miner chunk setpoints fork only once MEASURED rate EWMAs
        # spread past the controller's ratio gate — hinted (unconfirmed)
        # claims are excluded, or a miner could fork the pool off a
        # wire claim before any Result confirms it (code review); the
        # O(miners) scan is also skipped entirely when no per-miner
        # controller is mounted.
        ratio = None
        chunk_ctl = self.adapt_plane.chunk
        if chunk_ctl is not None and chunk_ctl.per_miner:
            ewmas = [m.rate_ewma for m in self.miner_plane.miners
                     if m.rate_ewma and not m.rate_hinted]
            if len(ewmas) >= 2:
                ratio = max(ewmas) / max(min(ewmas), 1e-9)
        changes = self.adapt_plane.tick(
            age, self._counters["results_sent"].value, rate_ratio=ratio)
        if not changes:
            return
        if changes.get("chunk_s_miner_clear"):
            # The pool re-converged: the forks retired, and the stale
            # overrides must stop shadowing the live pool-wide knob.
            self.miner_plane.clear_chunk_s_overrides()
        per = changes.get("chunk_s_miner")
        if per:
            # Per-miner stripe setpoints land on the miner plane's
            # override map (gauge + drop-retirement live there).
            for conn, v in per.items():
                if self.miner_plane.find_miner(conn) is not None:
                    self.miner_plane.set_chunk_s_override(conn, v)
        v = changes.get("chunk_s")
        if v is not None:
            # Write the plane's block directly, NOT through the qos
            # property setter: the setter re-runs _sync_backlog_hook,
            # whose ring re-seed walks every backlogged tenant — an
            # O(backlog) scan per adjustment that a chunk_s change
            # (which cannot alter the lazy flag, the enabled bit, or
            # ring membership) never needs. The stripe/coalesce
            # setters are plain assignments either way.
            self.tenant_plane.qos = _replace(self.qos, chunk_s=v)
            self.stripe = _replace(self.stripe, chunk_s=v)
        v = changes.get("small_s")
        if v is not None:
            self.coalesce = _replace(self.coalesce, small_s=v)

    # ---------------------------------------------------------------- events

    def _on_request(self, conn_id: int, msg: Message) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        if self.capture is not None:
            # Arrival stamp + geometry BEFORE admission (ISSUE 15): a
            # shed arrival is part of the measured workload — the
            # capture's shed rate is sheds over ALL arrivals.
            self.capture.request(conn_id, len(msg.data),
                                 msg.upper - msg.lower + 1,
                                 bool(msg.target))
        request = self._build_request(conn_id, msg)
        if request is None:
            return       # answered from the ResultCache at arrival
        if self.qos.enabled:
            if self.adapt_plane is not None:
                # Self-tuning plane (ISSUE 13): the window controller
                # counts small arrivals (mouse-flood signal), and the
                # congestion-style admission bucket gates CAPACITY
                # ahead of the per-tenant fairness buckets below —
                # shed semantics (conn close, counters) are the stock
                # shed path either way.
                if self.adapt_plane.window is not None:
                    # _qos_small walks the eligible pool — don't pay
                    # it per arrival just to discard the answer.
                    self.adapt_plane.observe_arrival(
                        self._qos_small(request))
                if not self.adapt_plane.admit():
                    self._shed(request, "admission")
                    return
            # Admission (cache replays above never reach here — an
            # already-answered retry must not burn quota, ISSUE 5
            # satellite). A drained bucket sheds the NEW request;
            # overload sheds the OLDEST queued one (their client is
            # nearest its own deadline; shedding it now gives its
            # backed-off resubmission the best chance of landing in a
            # drained queue).
            if not self.tenant_plane.admit(conn_id):
                self._shed(request, "admission")
                return
        self._intake(request, bound_queue=True)

    def reserve_request(self, conn_id: int, msg: Message) -> None:
        """Takeover re-serve (apps/replicas.kill): exactly
        :meth:`_on_request` EXCEPT that neither the admission bucket
        nor the overload shed is consulted — this work was already
        admitted once by the dead replica, and a failover must not
        convert admitted requests into sheds (code review). The
        ``max_queued`` bound re-asserts on the next ordinary arrival
        (its overload trim runs whenever the queue exceeds the
        bound)."""
        if self._owner is not None:
            self._owner.assert_here()
        request = self._build_request(conn_id, msg)
        if request is None:
            return       # replayed from the SHARED ResultCache
        if self.qos.enabled:
            self._tenant(conn_id)     # tenant state, no bucket charge
        self._intake(request, bound_queue=False)

    def _build_request(self, conn_id: int, msg: Message):
        """Arrival common path: cache replay (None = answered), else a
        fresh Request with its (possibly sampled) trace."""
        key = (msg.data, msg.lower, msg.upper, msg.target)
        if self.results is not None:
            hit = self._cache_lookup(key)
            if hit is not None:
                # O(1) replay: a retried/resubmitted request after a lost
                # Result answers from the memo without touching the pool
                # (and without queueing behind the in-flight request).
                h, nonce = hit
                self._write(conn_id, new_result(h, nonce))
                self._count("results_sent")
                if self.capture is not None:
                    self.capture.reply(conn_id, 0.0, cached=True)
                self.tenant_plane.cache_replay_trace(conn_id, key, h, nonce)
                logger.info("request %r [%d, %d] target=%d answered from "
                            "the result cache", msg.data, msg.lower,
                            msg.upper, msg.target)
                return None
        return Request(conn_id=conn_id, data=msg.data,
                       lower=msg.lower, upper=msg.upper,
                       target=msg.target, cache_key=key,
                       queued_at=time.monotonic(),
                       trace=self.tenant_plane.new_trace(
                           data=msg.data, lower=msg.lower,
                           upper=msg.upper, target=msg.target,
                           client=conn_id))

    def _intake(self, request: Request, bound_queue: bool) -> None:
        request.trace.event("enqueue",
                            queue_depth=self.tenant_plane.queue_len())
        self.tenant_plane.enqueue(request)
        if bound_queue and self.qos.enabled:
            bound = self.qos.max_queued
            if self.adapt_plane is not None:
                # Congestion depth bound (ISSUE 13): capacity x age
                # knee, tighter than (or substituting for) the static
                # cap once a service rate has been measured.
                bound = self.adapt_plane.effective_max_queued(bound)
            if bound > 0:
                while self.tenant_plane.queue_len() > bound:
                    self._shed(self.tenant_plane.pop_head(), "overload")
        self._maybe_dispatch()

    def _on_join(self, conn_id: int, msg: Optional[Message] = None) -> None:
        """``msg`` carries the optional Rate hint (ISSUE 14); callers on
        the pre-split surface (tests, embedded drivers) may omit it —
        a hint-less join is the stock path bit-for-bit.

        Repeat JOIN from a conn already registered as a live miner
        (ISSUE 20, ``DBM_GATEWAY``): the GatewayMiner's rate-hint
        refresh — the hint updates the existing roster entry in place
        via :meth:`MinerPlane.refresh_rate_hint` instead of minting a
        duplicate MinerState whose phantom capacity the stripe planner
        would plan against forever."""
        if self._owner is not None:
            self._owner.assert_here()
        rate_hint = float(msg.rate) if msg is not None else 0.0
        if self._gateway:
            miner = self.miner_plane.find_miner(conn_id)
            if miner is not None:
                self.miner_plane.refresh_rate_hint(miner, rate_hint)
                if rate_hint > 0:
                    # Refreshes recur every hint interval for the life
                    # of a gateway conn — debug, not INFO, or a quiet
                    # federated cluster logs nothing but hints.
                    logger.debug(
                        "miner %d refreshed rate hint %.3g nonces/s",
                        conn_id, rate_hint)
                self._maybe_dispatch()
                return
        self.miner_plane.on_join(conn_id, rate_hint=rate_hint)
        if rate_hint > 0:
            logger.info("miner %d joined with rate hint %.3g nonces/s",
                        conn_id, rate_hint)
        self._maybe_dispatch()

    def _on_result(self, conn_id: int, msg: Message) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        popped = self.miner_plane.pop_result(conn_id)
        if popped is None:
            return
        miner, chunk = popped
        if self._audits:
            # Audit Results route to the side table (see AuditRecord),
            # never the merge path: an audit job id is not in
            # _inflight, so without this it would read as stale.
            rec = self._audits.pop(chunk.job_id, None)
            if rec is not None:
                self._on_audit_result(rec, miner, chunk, msg)
                return
        curr = self._inflight.get(chunk.job_id)
        if self.adapt_plane is not None:
            # Chunk-sizing signal (ISSUE 13): the lease plane's own
            # stamps (service time + remaining-lease fraction) plus the
            # Result's span when one rode it — no new instrumentation.
            # Only chunked-mode grants are `sized` (their size came
            # from the controlled knob; a mouse's wholesale split did
            # not — see AdaptPlane.observe_chunk).
            service_s, margin = self.miner_plane.service_sample(chunk)
            self.adapt_plane.observe_chunk(
                service_s, margin, span=msg.span,
                sized=curr is not None and curr.qos_mode == "chunked",
                miner=conn_id)
        if curr is None:
            stale = self.tenant_plane.traces.get(chunk.job_id)
            if stale is not None:
                stale.event("stale_result", miner=conn_id, idx=chunk.idx)
                # A wedged/slow miner's span arrives LATE by definition
                # (its chunk was re-issued and the request already
                # replied): stitching it into the closed trace is what
                # names the miner-side phase that stalled.
                self._fold_span(stale, conn_id, chunk, msg.span)
            # A freed miner may unblock a queued/ungranted chunk.
            if self.qos.enabled:
                self._maybe_dispatch()
            return  # stale Result for a cancelled/finished request
        if curr.answered[chunk.idx]:
            # Loser of a speculative re-issue race: another assignment of
            # this same (job, idx) already merged. Re-issued copies scan
            # the identical range, so dropping the duplicate changes
            # nothing but the stats.
            self._count("dup_results")
            self._fold_span(curr.trace, conn_id, chunk, msg.span)
            curr.trace.event("result", miner=conn_id, idx=chunk.idx,
                             duplicate=True)
            logger.info("duplicate Result for job %d chunk %d from miner %d "
                        "(speculation loser)", curr.job_id, chunk.idx,
                        conn_id)
            if self.qos.enabled:
                # The duplicate still freed a live-FIFO slot on this miner.
                self._maybe_dispatch()
            return
        # Claim check (ISSUE 16): one host-side SHA-256 recompute per
        # claimed WINNER, before any merge state moves — a Result is a
        # CLAIM, not a fact, once miners may lie. Microseconds against
        # the multi-second chunk it answers; DBM_VERIFY=0 skips to the
        # stock believe-verbatim merge (one boolean test).
        if self.verify.enabled and not self._claim_ok(curr, chunk,
                                                      miner, msg):
            return
        if msg.hash < curr.min_hash:
            curr.min_hash = msg.hash
            curr.min_nonce = msg.nonce
        curr.answered[chunk.idx] = True
        if self.qos.enabled:
            self.qos_plane.on_chunk_answered(curr.conn_id)
        self._fold_span(curr.trace, conn_id, chunk, msg.span)
        curr.trace.event("result", miner=conn_id, idx=chunk.idx)
        curr.trace.event("merge", idx=chunk.idx,
                         answered=sum(curr.answered))
        if self.verify.audit_p > 0 and not curr.target:
            # Probabilistic audit (ISSUE 16): the claim check above
            # proved the pair REAL, not MINIMAL — only re-execution
            # can catch a sentinel-without-scan miner. Argmin chunks
            # only: a difficulty miner's in-kernel early exit makes
            # "sub-argmin over a window" unfalsifiable.
            self._maybe_audit(curr, chunk, miner, msg)
        if curr.target and msg.target != curr.target and not curr.weak:
            curr.weak = True
            logger.info(
                "difficulty request %d: miner %d answered without the "
                "target extension; the merged result is guaranteed "
                "qualifying, not guaranteed globally first",
                curr.job_id, conn_id)
        if curr.target and msg.hash < curr.target:
            curr.chunk_q[chunk.idx] = (msg.nonce, msg.hash)
        # Prefix release (difficulty only): the lowest-index qualifying
        # chunk is final once every earlier chunk has answered clean —
        # later chunks cover strictly higher nonces and cannot beat it.
        if curr.chunk_q:
            c = min(curr.chunk_q)
            if all(curr.answered[:c]):
                nonce, q_hash = curr.chunk_q[c]
                self._finish(curr, q_hash, nonce, early=True)
                return
        if curr.answered and all(curr.answered) and not curr.audit_holds:
            # Full barrier: stock request, or target missed everywhere —
            # the exact arg-min. (A difficulty hit always releases above:
            # at the barrier, its qualifying prefix is trivially complete.)
            # Outstanding audits HOLD the reply: _on_audit_result (or the
            # sweep's expiry) re-checks this barrier when the last one
            # resolves.
            self._finish(curr, curr.min_hash, curr.min_nonce)
        elif self.qos.enabled:
            # The answering miner freed a live-FIFO slot: grant the next
            # chunk (this request's or another tenant's, per DRR).
            self._maybe_dispatch()

    def _on_drop(self, conn_id: int) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        miner = self.miner_plane.find_miner(conn_id)
        if miner is not None:
            logger.info("miner %d dropped", conn_id)
            self.miner_plane.drop_miner(conn_id)
            if self._audits:
                # A dead auditor's outstanding audits can never
                # conclude, and each holds a request's reply: re-issue
                # to another disjoint miner or release as inconclusive.
                # (Audit chunks carry job ids recover() skips — not in
                # _inflight — so recovery never reassigns them; this
                # path owns them.)
                for c in miner.pending:
                    rec = self._audits.pop(c.job_id, None)
                    if rec is not None:
                        self._reaudit_or_release(rec)
            if self.adapt_plane is not None:
                self.adapt_plane.forget_miner(conn_id)
            # Export-track retirement (ISSUE 10): same churn rule as the
            # labeled series — a dead conn id's track must free its slot
            # under the cardinality bound.
            self.tenant_plane.retire_miner_track(conn_id)
            _tracing.flight("miner_drop", miner=conn_id)
            if not self._inflight:
                return
            for req in self._inflight.values():
                req.trace.event("miner_drop", miner=conn_id)
            self.miner_plane.recover(miner)
        else:
            logger.info("client %d dropped", conn_id)
            # Purge the dead client's queued requests FIRST so cancelling
            # its in-flight request can't promote another of its own
            # requests.
            purged = self.tenant_plane.purge_tenant(conn_id)
            for req in purged:
                req.trace.event("cancel", reason="client_drop")
            self.tenant_plane.retire_tenant_track(conn_id)
            if self.qos.enabled:
                self.qos_plane.forget(conn_id)
            cancelled = len(purged)
            for req in [r for r in self._inflight.values()
                        if r.conn_id == conn_id]:
                # Cancel immediately (divergence, see module docstring).
                req.trace.event("cancel", reason="client_drop")
                cancelled += 1
                self._retire(req)
            if self.capture is not None and cancelled:
                self.capture.cancel(conn_id, cancelled)

    def _on_lease_event(self, kind: str, chunk: Chunk, miner_conn: int,
                        **info) -> None:
        """Lease-event edge of the internal interface: the miner plane
        reports every lease state transition here, and this side does
        the trace/flight/log fanout against the owning request."""
        curr = self._inflight.get(chunk.job_id)
        if kind == "blown":
            spurious = info.get("spurious", False)
            if curr is not None:
                curr.trace.event("lease_blown", miner=miner_conn,
                                 idx=chunk.idx, streak=info["streak"],
                                 spurious=spurious)
            if self._trace_on:
                _tracing.flight("lease_blown", job=chunk.job_id,
                                idx=chunk.idx, miner=miner_conn,
                                streak=info["streak"])
            logger.warning(
                "miner %d blew the lease on job %d chunk %d "
                "[%d, %d) after %.2fs (streak %d)%s",
                miner_conn, chunk.job_id, chunk.idx,
                chunk.lower, chunk.upper, info.get("overdue_s", 0.0),
                info["streak"],
                " [spurious: miner had not reached this chunk]"
                if spurious else "")
        elif kind == "quarantine":
            if curr is not None:
                curr.trace.event("quarantine", miner=miner_conn)
            logger.warning(
                "miner %d quarantined after %d consecutive "
                "blown leases; no new assignments until it "
                "answers", miner_conn, info["streak"])
        elif kind == "reissue":
            if curr is not None:
                curr.trace.event("reissue", idx=chunk.idx,
                                 from_miner=miner_conn,
                                 to_miner=info["to_miner"])
            if self._trace_on:
                _tracing.flight("reissue", job=chunk.job_id,
                                idx=chunk.idx, from_miner=miner_conn,
                                to_miner=info["to_miner"])
            if self.capture is not None:
                self.capture.reissue()
            logger.warning(
                "speculatively re-issuing job %d chunk %d [%d, %d) "
                "from miner %d to miner %d",
                chunk.job_id, chunk.idx, chunk.lower, chunk.upper,
                miner_conn, info["to_miner"])
        elif kind == "quarantine_lifted":
            logger.info("miner %d answered; quarantine lifted", miner_conn)
        elif kind == "park":
            if curr is not None:
                curr.trace.event("park", idx=chunk.idx)
        elif kind == "claim_failed":
            if curr is not None:
                curr.trace.event("claim_failed", miner=miner_conn,
                                 idx=chunk.idx, nonce=info.get("nonce"),
                                 claimed=info.get("claimed"),
                                 actual=info.get("actual"))
            if self._trace_on:
                _tracing.flight("claim_failed", job=chunk.job_id,
                                idx=chunk.idx, miner=miner_conn,
                                trust=info.get("trust"))
            logger.warning(
                "miner %d FAILED the claim check on job %d chunk %d: "
                "claimed hash %s for nonce %s, recomputed %s "
                "(trust -> %.3g)%s",
                miner_conn, chunk.job_id, chunk.idx,
                info.get("claimed"), info.get("nonce"),
                info.get("actual"), info.get("trust", 0.0),
                " [audit re-execution]" if info.get("audit") else "")
        elif kind == "audit_failed":
            job = info.get("job", chunk.job_id)
            trace = self.tenant_plane.traces.get(job)
            if trace is not None:
                trace.event("audit_failed", miner=miner_conn,
                            idx=info.get("idx"),
                            lower=chunk.lower, upper=chunk.upper)
            if self._trace_on:
                _tracing.flight("audit_failed", job=job,
                                idx=info.get("idx"), miner=miner_conn,
                                auditor=info.get("auditor"),
                                trust=info.get("trust"))
            logger.warning(
                "miner %d FAILED an audit on job %s chunk %s: claimed "
                "argmin hash %s, but auditor %s found %s at nonce %s "
                "inside [%d, %d] (trust -> %s)",
                miner_conn, job, info.get("idx"), info.get("claimed"),
                info.get("auditor"), info.get("found"),
                info.get("found_nonce"), chunk.lower, chunk.upper,
                info.get("trust"))

    # ----------------------------------------------- verification (ISSUE 16)

    def _claim_ok(self, curr: Request, chunk: Chunk, miner: MinerState,
                  msg: Message) -> bool:
        """Claim check: is this Result's ``(hash, nonce)`` pair real?

        Three tests, all against values the scheduler can verify
        itself: the nonce must lie in the chunk's assigned range (a
        real pair lifted from OUTSIDE the range would otherwise pass),
        the hash must equal the host-side SHA-256 recompute, and a
        difficulty claim entering the qualifying set must satisfy the
        target bound ON THE RECOMPUTED hash (never the claimed one).
        A failed claim decays the liar's trust, fires the
        ``claim_failed`` lease event, and hands the range back for
        re-execution — ``answered[idx]`` stays False, so the request
        can still finish correctly off another miner's scan."""
        self._count("claims_checked")
        actual = hash_op(curr.data, msg.nonce)
        if chunk.lower <= msg.nonce <= chunk.upper \
                and actual == msg.hash \
                and not (curr.target and msg.hash < curr.target
                         and not actual < curr.target):
            return True
        self._count("claims_failed")
        trust = self.miner_plane.trust_fail(miner, "claim")
        self._on_lease_event("claim_failed", chunk, miner.conn_id,
                             nonce=msg.nonce, claimed=msg.hash,
                             actual=actual, trust=trust)
        # The liar's FIFO already popped this assignment: unless a
        # speculative copy is in flight the range must re-execute, to
        # a different miner when one is eligible (mirrors the lease
        # plane's re-issue; the park path keeps it alive otherwise).
        if not chunk.reissued:
            mp = self.miner_plane
            copy = Chunk(chunk.job_id, chunk.data, chunk.lower,
                         chunk.upper, target=chunk.target, idx=chunk.idx)
            takeover = next(
                (m for m in mp.eligible() if m is not miner), None)
            if takeover is not None:
                mp.assign_chunk(takeover, copy, kind="claim_retry")
            else:
                mp.parked.append(copy)
                self._on_lease_event("park", copy, miner.conn_id)
        self._maybe_dispatch()
        return False

    def _maybe_audit(self, curr: Request, chunk: Chunk,
                     miner: MinerState, msg: Message) -> None:
        """With probability ``audit_p``, re-grant a random subwindow of
        the just-merged chunk to a DISJOINT miner (see AuditRecord) and
        HOLD the request's reply until the cross-check resolves. No
        eligible disjoint miner = no audit: an audit is a spot check,
        never a reason to queue work behind a busy pool."""
        v = self.verify
        if self._audit_rng.random() >= v.audit_p:
            return
        mp = self.miner_plane
        auditor = mp.pick_auditor(miner.conn_id)
        if auditor is None:
            return
        span = min(v.audit_max_nonces, chunk.size)
        lo = chunk.lower + self._audit_rng.randrange(chunk.size - span + 1)
        hi = lo + span - 1       # inclusive, like every scanned bound
        self._issue_audit(AuditRecord(
            job_id=chunk.job_id, idx=chunk.idx, suspect=miner.conn_id,
            auditor=auditor.conn_id, lower=lo, upper=hi,
            claimed_hash=msg.hash, claimed_nonce=msg.nonce),
            curr.data, auditor)
        curr.audit_holds += 1

    def _issue_audit(self, rec: AuditRecord, data: str,
                     auditor: MinerState) -> None:
        """Grant one audit subwindow to ``auditor`` under a fresh job
        id, with a FIFO-budgeted expiry deadline (a wedged auditor's
        audit re-issues on a sweep tick instead of holding the reply
        forever). Shared by first issue and re-issue paths; the caller
        owns the hold accounting."""
        self._next_job_id += 1
        aid = self._next_job_id
        ac = Chunk(aid, data, rec.lower, rec.upper, target=0, idx=0)
        rec.auditor = auditor.conn_id
        rec.deadline = time.monotonic() + \
            self.miner_plane.lease_for(auditor, ac) \
            * (1 + len(auditor.pending))
        self._audits[aid] = rec
        self._count("audits_issued")
        trace = self.tenant_plane.traces.get(rec.job_id)
        if trace is not None:
            trace.event("audit", idx=rec.idx, auditor=auditor.conn_id,
                        lower=rec.lower, upper=rec.upper)
        if self._trace_on:
            _tracing.flight("audit", job=rec.job_id, idx=rec.idx,
                            suspect=rec.suspect, auditor=auditor.conn_id)
        self.miner_plane.assign_chunk(auditor, ac, kind="audit")

    def _resolve_audit(self, rec: AuditRecord) -> None:
        """Release the audited request's reply hold (whatever the
        verdict — failure already merged the auditor's repair) and
        finish it if this was the last thing it waited on."""
        curr = self._inflight.get(rec.job_id)
        if curr is None:
            return
        if curr.audit_holds:
            curr.audit_holds -= 1
        if not curr.audit_holds and curr.answered \
                and all(curr.answered):
            self._finish(curr, curr.min_hash, curr.min_nonce)

    def _reaudit_or_release(self, rec: AuditRecord) -> None:
        """An audit lost its auditor (drop, or sweep expiry): re-issue
        the same subwindow to another disjoint miner when one is
        eligible, else record it inconclusive and release the hold —
        liveness beats a spot check with nobody left to run it."""
        curr = self._inflight.get(rec.job_id)
        if curr is None:
            return          # audited request already retired
        mp = self.miner_plane
        auditor = mp.pick_auditor(rec.suspect, rec.auditor)
        if auditor is not None:
            self._issue_audit(rec, curr.data, auditor)
            return
        self._count("audits_inconclusive")
        self._resolve_audit(rec)

    def _expire_audits(self) -> None:
        """Sweep-tick expiry for outstanding audits (see AuditRecord:
        audit chunks are invisible to the lease plane by design)."""
        now = time.monotonic()
        for aid, rec in [(a, r) for a, r in self._audits.items()
                         if now >= r.deadline]:
            del self._audits[aid]
            logger.warning(
                "audit of job %d chunk %d expired on auditor %d; "
                "re-issuing", rec.job_id, rec.idx, rec.auditor)
            self._reaudit_or_release(rec)

    def _on_audit_result(self, rec: AuditRecord, miner: MinerState,
                         chunk: Chunk, msg: Message) -> None:
        """Cross-check an audit Result against the audited claim.

        The auditor's Result is a CLAIM too, verified first — a
        byzantine auditor must not frame an honest miner with a
        fabricated lower hash. Then: the suspect claimed
        ``claimed_hash`` as the argmin of the WHOLE chunk, so (a) a
        strictly better recomputed-real hash inside the subwindow
        proves the suspect never scanned it (sentinel-without-scan) —
        and since that pair is verified real and in-range, it MERGES
        into the held request, repairing the answer (a full-window
        audit by an honest auditor thereby restores the exact chunk
        argmin); (b) if the claimed winner lies INSIDE the window, an
        honest auditor must rediscover exactly it — reporting only a
        worse hash convicts the AUDITOR of the same laziness. A verdict
        that convicts the AUDITOR leaves the suspect's claim unchecked,
        so the same subwindow re-audits on another disjoint miner — a
        byzantine auditor must not be able to LAUNDER a byzantine
        suspect's lie by burning the spot check (the convictions decay
        its trust out of the auditor pool, so the loop terminates).
        Every other verdict releases the request's reply hold here."""
        mp = self.miner_plane
        curr = self._inflight.get(rec.job_id)
        self._count("claims_checked")
        actual = hash_op(chunk.data, msg.nonce)
        if msg.nonce < rec.lower or msg.nonce > rec.upper \
                or actual != msg.hash:
            self._count("claims_failed")
            trust = mp.trust_fail(miner, "claim")
            self._on_lease_event("claim_failed", chunk, miner.conn_id,
                                 nonce=msg.nonce, claimed=msg.hash,
                                 actual=actual, trust=trust, audit=True)
            self._reaudit_or_release(rec)
            self._maybe_dispatch()
            return
        elif msg.hash < rec.claimed_hash:
            self._count("audits_failed")
            suspect = mp.find_miner(rec.suspect)
            trust = (mp.trust_fail(suspect, "audit")
                     if suspect is not None else None)
            self._on_lease_event("audit_failed", chunk, rec.suspect,
                                 job=rec.job_id, idx=rec.idx,
                                 claimed=rec.claimed_hash,
                                 found=msg.hash, found_nonce=msg.nonce,
                                 auditor=miner.conn_id, trust=trust)
            if curr is not None and msg.hash < curr.min_hash:
                # Repair: the auditor's pair is claim-checked real and
                # inside the audited chunk's range — it supersedes the
                # liar's sentinel in the running min before the held
                # reply releases.
                curr.min_hash = msg.hash
                curr.min_nonce = msg.nonce
                curr.trace.event("merge", idx=rec.idx, audit_repair=True)
        elif rec.lower <= rec.claimed_nonce <= rec.upper \
                and msg.hash != rec.claimed_hash:
            # The real claimed winner is in-window; the auditor missed
            # it, so the auditor did not actually scan. The suspect's
            # claim is still unchecked — re-audit elsewhere.
            trust = mp.trust_fail(miner, "audit")
            self._on_lease_event("audit_failed", chunk, miner.conn_id,
                                 job=rec.job_id, idx=rec.idx,
                                 claimed=rec.claimed_hash,
                                 found=msg.hash, found_nonce=msg.nonce,
                                 auditor=miner.conn_id, trust=trust)
            self._reaudit_or_release(rec)
            self._maybe_dispatch()
            return
        else:
            self._count("audits_passed")
            trace = self.tenant_plane.traces.get(rec.job_id)
            if trace is not None:
                trace.event("audit_passed", idx=rec.idx,
                            auditor=miner.conn_id)
        self._resolve_audit(rec)
        # The auditor freed a live-FIFO slot either way.
        self._maybe_dispatch()

    # -------------------------------------------------------------- internal

    def _finish(self, curr: Request, h: int, nonce: int,
                early: bool = False) -> None:
        """Answer the client and retire the request. ``early`` = prefix
        release: the job's other chunks are still in flight."""
        self._write(curr.conn_id, new_result(h, nonce))
        self._count("results_sent")
        if self.results is not None and curr.cache_key is not None \
                and not curr.weak:
            # Weak merges excluded: "a qualifying nonce" from a stock
            # miner is not a deterministic function of the key.
            self.results.put(curr.cache_key, (h, nonce))
            self._count("cache_stores")
        elapsed = time.monotonic() - curr.started
        if self.capture is not None:
            # Arrival-to-reply latency (queued_at, not dispatch start):
            # the replay harness measures submit-to-reply client-side,
            # and the fidelity p50/p99 columns must compare like with
            # like.
            self.capture.reply(curr.conn_id,
                               time.monotonic() - curr.queued_at)
        curr.trace.event("reply", hash=h, nonce=nonce, early=early,
                         weak=curr.weak, elapsed_s=round(elapsed, 6))
        if self._trace_on:
            _tracing.flight("reply", job=curr.job_id, tenant=curr.conn_id,
                            elapsed_s=round(elapsed, 6))
        logger.info(
            "request %d served in %.3fs: [%d, %d) over %d chunks%s%s",
            curr.job_id, elapsed,
            curr.lower, curr.upper, curr.num_chunks,
            " (prefix release)" if early else "",
            " (weak merge)" if curr.weak else "")
        self._retire(curr)

    def _retire(self, curr: Request) -> None:
        """Retire one in-flight request and pump the queue.

        Any still-pending chunks of the retiring job (prefix release,
        client drop, or the unanswered losers of speculative re-issues at
        a full-barrier finish) are marked cancelled: the pool frees
        immediately (availability is derived), the FIFO pop discipline for
        their late Results is preserved (they drop at the job_id check),
        and the job's parked chunks are discarded. Under QoS the tenant's
        in-flight slots for granted-but-unanswered chunks are released
        and any UNGRANTED chunks simply evaporate (a difficulty prefix
        release on a chunked elephant skips their scans entirely)."""
        self.miner_plane.cancel_job(curr.job_id)
        if self._inflight.pop(curr.job_id, None) is not None:
            if curr.qos_mode == "chunked":
                self._chunked_inflight -= 1
            n = self._tenant_inflight.get(curr.conn_id, 0)
            if n <= 1:
                self._tenant_inflight.pop(curr.conn_id, None)
            else:
                self._tenant_inflight[curr.conn_id] = n - 1
            d = self._qos_chunked_reqs.get(curr.conn_id)
            if d is not None:
                d.pop(curr.job_id, None)
                if not d:
                    del self._qos_chunked_reqs[curr.conn_id]
        if self.qos.enabled:
            self.qos_plane.release(
                curr.conn_id, curr.granted_chunks - sum(curr.answered))
        if not self._inflight:
            self.miner_plane.clear_lease_gauges()
        self._maybe_dispatch()

    def _maybe_dispatch(self) -> None:
        """Start queued work when the pool can take it: the stock FIFO
        pump (one wholesale request at a time), or the QoS grant pump.

        Re-entrancy guard: an empty-range request finishes INSIDE its own
        dispatch (_load_balance -> _finish -> _retire -> here), so without
        the guard a burst of empty-range requests would recurse one stack
        frame set per request and overflow; with it, the inner call
        returns immediately and the OUTER pump loop drains the queue
        iteratively."""
        if self._owner is not None:
            self._owner.assert_here()
        if self._dispatching:
            return
        self._dispatching = True
        try:
            if self.qos.enabled:
                if self.qos.lazy:
                    self._qos_pump_lazy()
                else:
                    self._qos_pump()
            else:
                self._fifo_pump()
        finally:
            self._dispatching = False
        if not self._inflight and self.tenant_plane.queue_len() \
                and not self.miner_plane.eligible():
            # A dispatch pass found work but no taker: latch so the
            # condition logs once per starvation episode (every later
            # event re-enters here until a miner joins/frees/answers),
            # while the sweep's queue-age alarm keeps counting time.
            if not self._starved:
                self._starved = True
                self._count("no_eligible_miner")
                miners = self.miner_plane.miners
                quarantined = sum(1 for m in miners if m.quarantined)
                logger.warning(
                    "no eligible miner for %d queued request(s): pool=%d "
                    "quarantined=%d busy=%d — queue is stalled until a "
                    "miner joins, frees, or answers",
                    self.tenant_plane.queue_len(), len(miners), quarantined,
                    sum(1 for m in miners
                        if not m.available and not m.quarantined))
        elif not self.tenant_plane.queue_len():
            self._starved = False

    def _fifo_pump(self) -> None:
        """The stock dispatch loop: pop the queue head whenever nothing
        is in flight — the reference's FIFO order, bit-for-bit."""
        while not self._inflight and self.tenant_plane.queue_len():
            pool = self.miner_plane.eligible()
            desperate = False
            if not pool:
                pool = self.miner_plane.desperation_pool()
                if not pool:
                    break
                desperate = True
            req = self.tenant_plane.pop_head()
            if self._replay_at_dispatch(req):
                continue
            self._load_balance(req, pool, desperate=desperate)
            self._starved = False

    def _replay_at_dispatch(self, req: Request) -> bool:
        """Dispatch-time memo re-check: a duplicate that queued BEHIND
        its original (retry raced the still-in-flight first copy) replays
        at pop time — the original finished and stored while this one
        waited. The request's OWN trace is completed and registered
        (under a cache:N key — it never gets a job id) so the real queue
        wait stays on record. True = replayed (the caller drops it)."""
        if self.results is None or req.cache_key is None:
            return False
        hit = self._cache_lookup(req.cache_key, count_miss=False)
        if hit is None:
            return False
        self._write(req.conn_id, new_result(*hit))
        self._count("results_sent")
        if self.capture is not None:
            # Every results_sent path records a reply (code review:
            # a missing one under-counts completions in the baseline
            # and fails faithful replays on admitted_ratio). Real
            # queue wait — this copy did sit in line.
            self.capture.reply(req.conn_id,
                               time.monotonic() - req.queued_at,
                               cached=True)
        self.tenant_plane.observe_queue_wait(
            time.monotonic() - req.queued_at)
        req.trace.event("cache_hit", at="dispatch")
        req.trace.event("reply", hash=hit[0], nonce=hit[1], cached=True)
        self.tenant_plane.register_replay(req)
        logger.info(
            "queued request %r [%d, %d] answered from "
            "the result cache at dispatch", req.data,
            req.lower, req.upper)
        return True

    # ------------------------------------------------------------ QoS plane

    def _tenant(self, conn_id):
        return self.tenant_plane.tenant(conn_id)

    def _weight_for(self, tenant) -> float:
        return self.tenant_plane.weight_for(tenant)

    def set_tenant_weight(self, tenant, weight: float) -> None:
        """Programmatic per-tenant DRR weight override (tests and
        embedded drivers; the env path is ``DBM_QOS_WEIGHTS``)."""
        self.tenant_plane.set_weight(tenant, weight)

    @staticmethod
    def _qos_is_small(total: int, cold: bool, bound: float) -> bool:
        """THE wholesale-smallness predicate: empty/inverted ranges and
        cold pools are small; otherwise one comparison against the
        hoisted bound. One definition shared by head pricing, pump
        candidacy, and the dispatch decision — the three MUST agree, or
        a head priced as a chunked start could dispatch wholesale and
        debit a whole request against a one-chunk deficit (code
        review)."""
        return total <= 0 or cold or total <= bound

    def _qos_small(self, req: Request) -> bool:
        """Small enough for the stock wholesale dispatch: the estimated
        scan fits ``wholesale_s``, or the pool is cold (no throughput
        observed — wholesale preserves reference parity for first
        requests, exactly like the striping plane's cold fallback)."""
        cold, bound = self._qos_small_bound()
        return self._qos_is_small(req.upper - req.lower + 1, cold, bound)

    def _qos_small_bound(self):
        """Hoisted smallness test state: ``(cold, bound_nonces)``.

        ``est <= wholesale_s`` with ``est = total / (rate * n)`` is
        ``total <= wholesale_s * rate * n`` — computing the right-hand
        side ONCE per heads pass turns the per-tenant test into one
        comparison. The old per-head ``_qos_small`` walked the eligible
        pool (O(miners × pending)) for EVERY backlogged tenant on every
        pump — the single hottest line of the 10k-tenant storm profile
        (ISSUE 11)."""
        rate = self.miner_plane.pool_rate
        if rate is None or rate <= 0:
            return True, 0.0
        n = max(1, len(self.miner_plane.eligible())
                or len(self.miner_plane.miners) or 1)
        return False, self.qos.wholesale_s * rate * n

    def _qos_chunk_plan(self, total: int, pool_n: int) -> tuple[int, int]:
        """``(n_chunks, first_chunk_size)`` for a chunked activation of
        ``total`` nonces: chunks sized at ``chunk_s`` seconds of one
        miner's pool-EWMA work, capped at ``max_chunks`` (a request too
        large for the cap gets proportionally larger chunks); an even
        split over ``pool_n`` when cold. Shared by the activation (the
        actual plan) and the DRR head cost (what one grant will debit) —
        the two MUST agree, or a chunked start banks the whole request's
        cost as unearned deficit and starves every other tenant."""
        rate = self.miner_plane.pool_rate or 0.0
        if rate > 0:
            n = -(-total // max(1, int(rate * self.qos.chunk_s)))
        else:
            n = max(1, pool_n)
        n = max(1, min(self.qos.max_chunks, n, total))
        return n, total // n + (1 if total % n else 0)

    def _qos_heads(self) -> dict:
        """Each tenant's next grantable work item:
        ``{tenant: (kind, request, cost_nonces)}``.

        - ``("chunk", req, n)`` — the next ungranted chunk of the
          tenant's oldest chunked in-flight request.
        - ``("start", req, n)`` — the tenant's oldest queued request
          (tenants serve their own requests FIFO; fairness is across
          tenants). Starts are withheld while a WHOLESALE request is in
          flight — that is the stock one-at-a-time order, which keeps
          single-tenant and small-request traffic bit-identical to the
          FIFO scheduler — but flow freely alongside chunked requests.

        Tenants at their ``max_inflight`` cap are skipped. The queued
        scan rides the tenant plane's per-tenant FIFO index — O(tenants
        with backlog), not O(queued requests) (ISSUE 11).
        """
        heads: dict = {}
        cap = self.qos.max_inflight
        tenants_map = self.qos_plane.tenants
        any_chunked = self._chunked_inflight > 0
        for req in self._inflight.values():     # oldest first
            if req.qos_mode != "chunked" or \
                    req.next_chunk >= req.num_chunks:
                continue
            t = req.conn_id
            if t in heads:
                continue
            if cap > 0 and self._tenant(t).inflight >= cap:
                continue
            lo, up = req.chunk_bounds[req.next_chunk]
            heads[t] = ("chunk", req, up - lo)
        if self._inflight and not any_chunked:
            return heads        # wholesale in flight: stock FIFO wait
        cold, small_bound = self._qos_small_bound()
        none_inflight = not self._inflight
        pool_n = len(self.miner_plane.miners) or 1
        busy = {r.conn_id for r in self._inflight.values()}
        for t, req in self.tenant_plane.tenant_heads():
            if t in heads or t in busy:
                continue
            if cap > 0:
                # Existing-state read only (the hot path must not pay a
                # create-with-weight per head): admission already
                # created the tenant; an unknown tenant has 0 in flight.
                st = tenants_map.get(t)
                if st is not None and st.inflight >= cap:
                    continue
            # The head COST is what granting it will actually DEBIT —
            # the same branch the pump executes: the whole range for a
            # start that will dispatch wholesale (nothing in flight and
            # small — every chunk is assigned at dispatch), but only the
            # FIRST planned chunk for one that will activate chunked.
            # Pricing a to-be-chunked start at its full 2^40 range banks
            # the difference as unearned deficit, and quantum (the max
            # candidate cost) balloons with it — one mispriced start
            # then outbids every tenant for the rest of its life.
            total = req.upper - req.lower + 1
            if none_inflight and self._qos_is_small(total, cold,
                                                    small_bound):
                cost = max(1, total)
            else:
                _, cost = self._qos_chunk_plan(max(1, total), pool_n)
            heads[t] = ("start", req, cost)
        return heads

    def _coalescible_cost(self, req: Request, cost: int) -> bool:
        return self.miner_plane.coalescible_cost(req.target, cost)

    def _window_slot(self, window: dict, job_id: int):
        return self.miner_plane.window_slot(window, job_id)

    def _window_room(self, window: dict, job_id: int = 0) -> bool:
        return self.miner_plane.window_room(window, job_id)

    def _qos_pump(self) -> None:
        """The QoS grant loop: while grantable work and pool capacity
        exist, pick the next tenant by deficit-round-robin and execute
        ONE grant — an incremental chunk, a chunked activation, or a
        stock wholesale dispatch for small/cold requests.

        The pass carries a COALESCING WINDOW map (ISSUE 9): miner conn
        id -> ``[coalesce_id, lanes_used, {job_ids}]``. A small grant
        may land in an open window even when the capacity pool is empty
        (the window counts as one live slot however many lanes it
        holds), which is what batches N mice onto one miner within a
        single pump pass. Windows live for ONE pass only — the next
        pump starts fresh, so a window can never span a lease sweep or
        quarantine event.

        Hot-path discipline (ISSUE 11): the DRR ring is synced to the
        backlogged tenant set (idle tenants leave it, forfeiting their
        deficit — the classic rule the old O(all tenants) reset loop
        applied), and the pass EARLY-EXITS before any head scan when
        the pool has no grant capacity and no wholesale/desperation
        start is possible — an arrival storm on a saturated pool costs
        O(miners) per event, not O(tenants)."""
        plane = self.qos_plane
        mp = self.miner_plane
        tp = self.tenant_plane
        # O(1) no-op exits FIRST (ISSUE 11): during a wholesale request
        # with nothing chunked, no start may flow (stock one-at-a-time
        # order) and no chunk head exists — the 10k-storm profile showed
        # every chunk Result paying a full backlog walk here for
        # nothing. Likewise an empty backlog.
        if self._inflight and not self._chunked_inflight:
            return
        if not tp.queue_len() and not self._chunked_inflight:
            return
        backlogged = list(dict.fromkeys(
            tp.backlog_tenants()
            + [r.conn_id for r in self._inflight.values()
               if r.qos_mode == "chunked"
               and r.next_chunk < r.num_chunks]))
        plane.sync_backlog(backlogged)
        if not backlogged:
            return
        if not mp.capacity_pool(self.qos.depth) and \
                (self._inflight or not (mp.eligible()
                                        or mp.desperation_pool())):
            return     # saturated: nothing grantable this event
        window: dict = {}
        while True:
            heads = self._qos_heads()
            if not heads:
                break
            eligible = mp.eligible()
            cap_pool = mp.capacity_pool(self.qos.depth)
            cold, small_bound = self._qos_small_bound()
            none_inflight = not self._inflight
            can_start = bool(eligible) or bool(mp.desperation_pool())
            candidates = {}
            for t, (kind, req, cost) in heads.items():
                # window_room first: an empty window map (the common
                # case) short-circuits the whole joinability test.
                joinable = (mp.window_room(window, req.job_id)
                            and self._coalescible_cost(req, cost))
                if kind == "chunk":
                    if cap_pool or joinable:
                        candidates[t] = cost
                elif none_inflight and self._qos_is_small(
                        req.upper - req.lower + 1, cold, small_bound):
                    # Wholesale start: needs the stock eligibility (or
                    # the desperation fallback), exactly like the FIFO
                    # pump.
                    if can_start:
                        candidates[t] = cost
                elif cap_pool or joinable:
                    candidates[t] = cost
            if not candidates:
                break
            t = plane.pick(candidates)
            kind, req, cost = heads[t]
            if kind == "chunk":
                self._qos_grant(req, cap_pool, window)
                continue
            self.tenant_plane.dequeue(req)
            if self._replay_at_dispatch(req):
                continue
            # Same (cold, bound) pair as candidacy above: pricing,
            # candidacy, and the dispatch decision share ONE predicate.
            if not self._inflight and self._qos_is_small(
                    req.upper - req.lower + 1, cold, small_bound):
                pool, desperate = mp.eligible(), False
                if not pool:
                    pool, desperate = mp.desperation_pool(), True
                self._load_balance(req, pool, desperate=desperate)
            else:
                self._qos_activate(req, cap_pool, window)
            self._starved = False

    def _qos_pump_lazy(self) -> None:
        """The lazy-walk QoS grant loop (ISSUE 12, ``DBM_QOS_LAZY``,
        default on; 0 = the stock :meth:`_qos_pump`).

        Same grant semantics as the stock pump — chunk heads for chunked
        in-flight requests, start heads for queued ones, the wholesale/
        chunked dispatch decision, coalescing windows, DRR fairness —
        but candidate DISCOVERY is lazy: instead of rebuilding the full
        O(backlogged-tenants) heads map and re-syncing the ring before
        every grant (the per-completion scan behind the 10k-tenant N=1
        superlinear tail, BENCH_r06), the DRR ring itself is walked and
        each visited tenant's head is priced on demand from two O(1)
        per-tenant indexes (``_qos_chunked_reqs``,
        ``tenant_plane.tenant_head``). Ring membership is maintained at
        the edges (enqueue hook, chunked activation) and pruned lazily
        by the walk (:data:`LAZY_REMOVE`), so a grant costs O(tenants
        actually visited) — O(1) amortized — rather than O(backlogged).

        Grant ORDER may differ from the stock walk (the incremental
        quantum bound and visit order are not bit-identical), but the
        DRR guarantees — no starvation within ``ceil(1/weight)``
        cycles, share convergence to the weight ratio — and every merge
        /accounting invariant are unchanged (dbmcheck explores this
        path by default; the tier-1 matrix leg pins the stock walk)."""
        plane = self.qos_plane
        mp = self.miner_plane
        tp = self.tenant_plane
        # Same O(1) no-op exits as the stock pump.
        if self._inflight and not self._chunked_inflight:
            return
        if not tp.queue_len() and not self._chunked_inflight:
            return
        if not mp.capacity_pool(self.qos.depth) and \
                (self._inflight or not (mp.eligible()
                                        or mp.desperation_pool())):
            return     # saturated: nothing grantable this event
        window: dict = {}
        cap = self.qos.max_inflight
        tenants_map = plane.tenants
        while True:
            # Stock one-at-a-time order: a wholesale dispatch from THIS
            # pass (or a concurrent event) withholds further starts,
            # and with no chunked work in flight there are no heads.
            if self._inflight and not self._chunked_inflight:
                break
            eligible = mp.eligible()
            cap_pool = mp.capacity_pool(self.qos.depth)
            cold, small_bound = self._qos_small_bound()
            none_inflight = not self._inflight
            can_start = bool(eligible) or bool(mp.desperation_pool())
            pool_n = len(mp.miners) or 1
            heads: dict = {}     # tenants priced by THIS pick's walk

            def head_for(tenant):
                # Chunk head first: the tenant's oldest chunked
                # in-flight request with ungranted chunks (pruning
                # retired/exhausted index entries as they surface).
                reqs = self._qos_chunked_reqs.get(tenant)
                creq = None
                while reqs:
                    cand = next(iter(reqs.values()))
                    if cand.job_id not in self._inflight or \
                            cand.next_chunk >= cand.num_chunks:
                        reqs.pop(cand.job_id, None)
                        if not reqs:
                            self._qos_chunked_reqs.pop(tenant, None)
                        continue
                    creq = cand
                    break
                st = tenants_map.get(tenant)
                at_cap = cap > 0 and st is not None \
                    and st.inflight >= cap
                if creq is not None:
                    if at_cap:
                        return None
                    lo, up = creq.chunk_bounds[creq.next_chunk]
                    cost = up - lo
                    joinable = (mp.window_room(window, creq.job_id)
                                and self._coalescible_cost(creq, cost))
                    if not (cap_pool or joinable):
                        return None
                    heads[tenant] = ("chunk", creq, cost)
                    return cost
                # Start head: the tenant's oldest queued request.
                sreq = tp.tenant_head(tenant)
                if sreq is None:
                    return LAZY_REMOVE        # no backlog at all
                if self._tenant_inflight.get(tenant) or at_cap:
                    return None               # busy tenant: no start
                total = sreq.upper - sreq.lower + 1
                if none_inflight and self._qos_is_small(total, cold,
                                                        small_bound):
                    if not can_start:
                        return None
                    cost = max(1, total)
                else:
                    _, cost = self._qos_chunk_plan(max(1, total), pool_n)
                    joinable = (mp.window_room(window, sreq.job_id)
                                and self._coalescible_cost(sreq, cost))
                    if not (cap_pool or joinable):
                        return None
                heads[tenant] = ("start", sreq, cost)
                return cost

            t = plane.pick_lazy(head_for)
            if t is None:
                break
            kind, req, _cost = heads[t]
            if kind == "chunk":
                self._qos_grant(req, cap_pool, window)
                if req.next_chunk >= req.num_chunks:
                    d = self._qos_chunked_reqs.get(t)
                    if d is not None:
                        d.pop(req.job_id, None)
                        if not d:
                            self._qos_chunked_reqs.pop(t, None)
                continue
            self.tenant_plane.dequeue(req)
            if self._replay_at_dispatch(req):
                continue
            # Same (cold, bound) pair as pricing above: pricing,
            # candidacy, and the dispatch decision share ONE predicate.
            if not self._inflight and self._qos_is_small(
                    req.upper - req.lower + 1, cold, small_bound):
                pool, desperate = mp.eligible(), False
                if not pool:
                    pool, desperate = mp.desperation_pool(), True
                self._load_balance(req, pool, desperate=desperate)
            else:
                self._qos_activate(req, cap_pool, window)
            self._starved = False

    def _qos_activate(self, req: Request, pool: list[MinerState],
                      window: Optional[dict] = None) -> None:
        """Activate a request in CHUNKED mode: plan contiguous ascending
        chunks sized at ``chunk_s`` seconds of pool-EWMA work (capped at
        ``max_chunks``; an even split over the capacity pool when cold)
        and grant the first one. Later chunks are granted by subsequent
        pump turns, so concurrent tenants' chunks interleave."""
        self._next_job_id += 1
        req.job_id = self._next_job_id
        req.qos_mode = "chunked"
        self._chunked_inflight += 1
        self._tenant_inflight[req.conn_id] = \
            self._tenant_inflight.get(req.conn_id, 0) + 1
        req.started = time.monotonic()
        self.tenant_plane.observe_queue_wait(req.started - req.queued_at)
        if self.adapt_plane is not None:
            self.adapt_plane.observe_wait(req.started - req.queued_at)
        self.tenant_plane.traces.register(req.job_id, req.trace)
        if not req.trace.null:
            self.tenant_plane.track_tenant(req.conn_id)
        self._inflight[req.job_id] = req
        req.upper += 1  # inclusive -> exclusive
        total = req.upper - req.lower
        req.trace.event("dispatch", job=req.job_id, mode="chunked",
                        miners=[m.conn_id for m in pool])
        if self._trace_on:
            _tracing.flight("dispatch", job=req.job_id, mode="chunked",
                            tenant=req.conn_id)
        if total <= 0:
            # Empty/inverted range, same answer as the wholesale path.
            self._finish(req, MAX_U64, 0)
            return
        # Cold-pool fallback sized over the WHOLE pool, exactly like the
        # DRR head pricing in _qos_heads — the activation may now run
        # with an EMPTY capacity pool (the window-joinable path), and
        # len(pool)=0 on a cold rate would plan ONE whole-request chunk
        # that diverges from the priced head cost (code review, PR 8).
        n, _ = self._qos_chunk_plan(total,
                                    len(self.miner_plane.miners) or 1)
        bounds = []
        base = req.lower
        size, rem = divmod(total, n)
        for i in range(n):
            step = size + (1 if i < rem else 0)
            bounds.append((base, base + step))
            base += step
        req.chunk_bounds = bounds
        req.num_chunks = n
        req.answered = [False] * n
        req.next_chunk = 0
        # Lazy-DRR index (ISSUE 12): the tenant's chunked requests with
        # ungranted chunks, activation order; the lazy pump prices
        # chunk heads from it in O(1) and the entry retires with the
        # request (or at grant exhaustion).
        self._qos_chunked_reqs.setdefault(req.conn_id, {})[req.job_id] = req
        if self.tenant_plane.backlog_hook is not None:
            self.qos_plane.backlog_enter(req.conn_id)
        self._qos_grant(req, pool, window)

    def _qos_grant(self, req: Request, pool: list[MinerState],
                   window: Optional[dict] = None) -> None:
        """Hand the request's next planned chunk to the least-loaded
        capacity miner and account the grant with the DRR plane.

        Coalescing (ISSUE 9): a SMALL chunk first tries to join an open
        window in ``window`` (sharing that window's ``coalesce_id`` —
        one live slot, one future shared launch); failing that it goes
        to the least-loaded capacity miner and, still being small,
        OPENS a window there for later grants of this pump pass. Large
        or difficulty chunks never touch windows. Accounting (DRR
        debit, tenant in-flight, lease) is identical either way."""
        mp = self.miner_plane
        idx = req.next_chunk
        lo, up = req.chunk_bounds[idx]
        miner = None
        cid = None
        small = mp.coalescible_cost(req.target, up - lo)
        if small and window:
            miner, slot = mp.window_slot(window, req.job_id)
            if miner is not None:
                cid = slot[0]
                slot[1] += 1
                slot[2].add(req.job_id)
                self._count("qos_window_grants")
        if miner is None:
            if not pool:
                return    # window gone and no capacity: next pump turn
            miner = pool[0]
            if small and window is not None \
                    and miner.conn_id not in window:
                cid = mp.open_window(window, miner, req.job_id)
        req.next_chunk += 1
        req.granted_chunks += 1
        self._count("qos_grants")
        self.qos_plane.on_grant(req.conn_id, up - lo)
        mp.assign_chunk(
            miner, Chunk(req.job_id, req.data, lo, up,
                         target=req.target, idx=idx, coalesce_id=cid),
            kind="qos")

    def _shed(self, req: Request, reason: str) -> None:
        self.tenant_plane.shed(req, reason)

    def _load_balance(self, request: Request, pool: list[MinerState],
                      desperate: bool = False) -> None:
        """Split the range over ``pool`` (the eligible miners, or the
        single-miner desperation pool).

        Without faults this is ALL miners (the reference invariant: one
        request in flight, so every miner is free at dispatch); quarantined
        or still-busy miners (wedged compute holding a live lease-blown
        chunk) are excluded."""
        mp = self.miner_plane
        self._next_job_id += 1
        request.job_id = self._next_job_id
        request.qos_mode = "wholesale"
        self._inflight[request.job_id] = request
        self._tenant_inflight[request.conn_id] = \
            self._tenant_inflight.get(request.conn_id, 0) + 1
        request.started = time.monotonic()
        self.tenant_plane.observe_queue_wait(
            request.started - request.queued_at)
        if self.adapt_plane is not None:
            self.adapt_plane.observe_wait(
                request.started - request.queued_at)
        self.tenant_plane.traces.register(request.job_id, request.trace)
        if not request.trace.null:
            self.tenant_plane.track_tenant(request.conn_id)
        request.trace.event("dispatch", job=request.job_id,
                            miners=[m.conn_id for m in pool],
                            desperate=desperate)
        if self._trace_on:
            _tracing.flight("dispatch", job=request.job_id,
                            mode="wholesale", tenant=request.conn_id)
        if desperate:
            self._count("desperation_dispatch")
            m = pool[0]
            logger.warning(
                "DESPERATION dispatch: entire pool (%d miner(s)) is "
                "quarantined; assigning request %r [%d, %d] to least-bad "
                "miner %d (blown streak %d, rate %s) as a last resort",
                len(mp.miners), request.data, request.lower,
                request.upper, m.conn_id, m.blown_streak,
                f"{m.rate_ewma:.0f}/s" if m.rate_ewma else "unknown")
        num = len(pool)
        request.upper += 1  # inclusive -> exclusive
        total = request.upper - request.lower
        if total <= 0:
            # Empty/inverted range: answer like an empty scan (the reference
            # would wrap negative totals through uint64 and wedge the pool).
            self._finish(request, MAX_U64, 0)
            return
        individual = total // num
        leftover = total - individual * num
        if individual == 0:  # more miners than nonces
            individual, leftover, num = 1, 0, total
        # Striping (dispatch pipeline, ISSUE 4): each miner's even-split
        # share may be cut into several contiguous chunks so its pending
        # FIFO is deep enough for the miner-side pipeline to overlap.
        # The full chunk plan is built FIRST — chunk indices must ascend
        # with nonce range globally (the difficulty prefix-release merge
        # depends on it) and ``answered`` must be sized before the first
        # assignment records a trace event against it.
        plan: list[tuple[MinerState, int, int]] = []
        start = request.lower
        for i in range(num):
            end = start + individual + (leftover if i == 0 else 0)
            share = end - start
            n_i = mp.stripe_chunks(pool[i], share)
            mp.observe_stripe(n_i)
            base = start
            for j in range(n_i):
                size = share // n_i + (1 if j < share % n_i else 0)
                plan.append((pool[i], base, base + size))
                base += size
            start = end
        if len(plan) > num:
            self._count("chunks_striped", len(plan) - num)
        request.num_chunks = len(plan)
        request.answered = [False] * len(plan)
        request.granted_chunks = len(plan)
        if self.qos.enabled:
            # Wholesale chunks count against the tenant's in-flight cap
            # and grant share like incremental ones — an elephant that
            # slipped through wholesale (cold pool) still pays its DRR
            # deficit, so later contended rounds stay fair.
            self._tenant(request.conn_id)
            for _, lo, up in plan:
                self.qos_plane.on_grant(request.conn_id, up - lo)
        for idx, (miner, lo, up) in enumerate(plan):
            mp.assign_chunk(
                miner,
                Chunk(request.job_id, request.data, lo, up,
                      target=request.target, idx=idx))

    # ---------------------------------------- plane shims (compat surface)

    # The pre-split private surface, delegated: tests, the dbmcheck
    # harness, and the bench probes drive these; new code should call
    # the planes directly.

    def _find_miner(self, conn_id: int) -> Optional[MinerState]:
        return self.miner_plane.find_miner(conn_id)

    def _eligible(self) -> list[MinerState]:
        return self.miner_plane.eligible()

    def _desperation_pool(self) -> list[MinerState]:
        return self.miner_plane.desperation_pool()

    def _next_parked(self, skip_key=None) -> Optional[Chunk]:
        return self.miner_plane.next_parked(skip_key=skip_key)

    def _assign_chunk(self, miner: MinerState, chunk: Chunk,
                      kind: str = "initial") -> None:
        self.miner_plane.assign_chunk(miner, chunk, kind=kind)

    def _start_lease(self, miner: MinerState, chunk: Chunk) -> None:
        self.miner_plane.start_lease(miner, chunk)

    def _observe_result(self, miner: MinerState, chunk: Chunk) -> None:
        self.miner_plane.observe_result(miner, chunk)

    def _lease_for(self, miner: MinerState, chunk: Chunk) -> float:
        return self.miner_plane.lease_for(miner, chunk)

    def _stripe_chunks(self, miner: MinerState, share: int) -> int:
        return self.miner_plane.stripe_chunks(miner, share)

    def _miner_live(self, miner: MinerState) -> int:
        return self.miner_plane.miner_live(miner)

    def _qos_capacity_pool(self) -> list[MinerState]:
        return self.miner_plane.capacity_pool(self.qos.depth)

    def _update_pool_gauges(self) -> None:
        self.miner_plane.update_pool_gauges()

    def _check_leases(self) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        self.miner_plane.check_leases()

    def _check_queue_age(self) -> None:
        mp = self.miner_plane
        self.tenant_plane.check_queue_age(
            self._inflight, self.current,
            len(mp.miners), len(mp.eligible()),
            distrusted_n=sum(1 for m in mp.miners if mp.distrusted(m)))

    def _write(self, conn_id: int, msg: Message) -> None:
        try:
            self.server.write(conn_id, msg.to_json())
        except LspError:
            # The drop event for this connection is already in flight; the
            # drop handler will repair the assignment.
            logger.info("write to %d failed; awaiting drop event", conn_id)
