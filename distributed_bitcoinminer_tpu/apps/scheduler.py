"""The scheduler: shard nonce ranges over an elastic miner pool, merge argmins.

Faithful state machine of the reference coordinator
(ref: bitcoin/server/server.go:19-403), as one asyncio actor instead of
channel-coupled goroutines:

- FIFO request queue, ONE request in flight at a time (deliberate reference
  simplification — no pipeline parallelism).
- ``load_balance``: bounds become exclusive (``upper += 1``); even split
  ``total // num_miners`` with the remainder given to the FIRST miner; when
  there are more miners than nonces, only ``total`` miners get 1-nonce chunks
  (ref: server.go:165-205).
- Bound quirk preserved for bit parity: chunks are sent with EXCLUSIVE upper
  bounds but the miner treats ``Upper`` as inclusive (ref: miner.go:51-52),
  so each chunk scans one extra nonce and the system as a whole scans
  ``[0, maxNonce+1]``.
- Result merge: strict ``<`` on the uint64 hash; barrier releases the Result
  to the client when every chunk of the request has been answered
  (ref: server.go:257-325).
- Difficulty extension (no reference analog; BASELINE config 5): a Request
  carrying ``Target`` fans out with the target on every chunk, miners
  early-exit at their chunk's first ``hash < target`` nonce, and the merge
  answers the lowest-nonce qualifying response — the globally first
  qualifying nonce when every miner speaks the extension (chunks ascend
  and each reports its chunk-first hit; a stock Target-dropping miner
  reports a chunk arg-min instead, weakening its chunk to "a qualifying
  nonce" — detected via the Result's target echo and surfaced in logs,
  see ``Request.weak``). No hit anywhere degrades to the exact arg-min,
  and stock Requests (``Target`` absent = 0) take the reference path
  byte-for-byte.
- Difficulty prefix release (VERDICT r4): chunks cover ascending disjoint
  ranges, so once some chunk ``c`` reports a qualifying hit and every chunk
  ``< c`` has answered without one, no later answer can beat it — the
  Result is released IMMEDIATELY, without waiting for the full barrier.
  The released job's remaining chunks are cancelled exactly like a
  client-drop (miners free, their late Results pop as stale via the
  job_id/FIFO machinery), so a tight target's time-to-first-hit is the
  winning chunk's scan, not the slowest full scan. Stock arg-min requests
  keep the reference's full barrier untouched (ref: server.go:309-324).
- Miner drop: reassign its unanswered chunks to available miners, else park
  them; parked chunks are re-issued when a miner joins or frees up
  (ref: server.go:326-376, 222-244, 285-304).
- Client drop: the in-flight request is cancelled immediately — miners are
  freed, parked chunks cleared, the next queued request starts.

Bookkeeping divergence from the reference (deliberate): the reference tracks
one recorded chunk per miner plus a positional ``responsibleMiners`` list,
which deadlocks or double-counts in several reachable states — a parked chunk
whose client drops stalls every later request (server.go:377-400 never
releases the barrier); a freed miner re-assigned before flushing its previous
Result leaks that stale Result into the new request; an idle miner dropping
reassigns a stale chunk from an older request (server.go:339-370). Here every
Request written to a miner pushes a full chunk record onto that miner's
pending FIFO; since miners answer sequentially over in-order exactly-once
LSP, each arriving Result pops exactly the chunk it answers, so stale Results
are identified precisely, and a dead miner's unanswered chunks are recovered
individually. The observable contract (assignment order, chunk boundaries,
merge rule, one-in-flight FIFO scheduling) is unchanged.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from ..bitcoin.hash import MAX_U64
from ..bitcoin.message import Message, MsgType, new_request, new_result
from ..lsp.errors import LspError
from ..lsp.server import AsyncServer

logger = logging.getLogger("dbm.scheduler")


@dataclass
class Chunk:
    job_id: int
    data: str
    lower: int
    upper: int              # exclusive end, as sent on the wire
    target: int = 0         # difficulty target; rides every (re)assignment
    idx: int = 0            # position in the request's ascending chunk order
    # Set when the requesting client drops: the chunk stays in the miner's
    # pending FIFO (its Result must still pop in order) but no longer
    # counts against the miner's availability.
    cancelled: bool = False


@dataclass
class MinerState:
    conn_id: int
    # Every Request written to this miner, in write order (see module doc).
    pending: list = field(default_factory=list)

    @property
    def available(self) -> bool:
        """Derived, not stored (ADVICE r2): a miner is available iff it has
        no LIVE pending chunk. Cancelled chunks still occupy the FIFO (their
        stale Results pop in order) without blocking new assignments."""
        return not any(not c.cancelled for c in self.pending)


@dataclass
class Request:
    conn_id: int
    data: str
    lower: int
    upper: int              # inclusive on arrival; +1 at load_balance
    target: int = 0         # difficulty target; 0 = exact arg-min (stock)
    job_id: int = 0
    num_chunks: int = 0
    min_hash: int = MAX_U64
    min_nonce: int = 0
    # Difficulty merge plane, per-chunk (VERDICT r4 prefix release).
    # Chunks cover ascending disjoint sub-ranges and each until-speaking
    # miner reports its chunk-FIRST qualifying (hash < target) nonce, so
    # the lowest-INDEX qualifying chunk holds the globally first
    # qualifying nonce — final as soon as every earlier chunk has
    # answered without a hit, regardless of chunks still in flight.
    # (A stock Target-dropping miner reports its chunk ARG-MIN, which may
    # qualify later than its chunk's first hit, weakening the answer to
    # "a qualifying nonce" — see client.submit_until docstring.)
    answered: list = field(default_factory=list)   # bool per chunk idx
    chunk_q: dict = field(default_factory=dict)    # idx -> (nonce, hash)
    # True once any responder answered a target chunk without echoing the
    # target (stock miner in the pool): the merged answer is then only
    # guaranteed qualifying, not guaranteed globally first (ADVICE r4 —
    # surfaced in logs, invisible on the reference-shaped wire).
    weak: bool = False
    started: float = 0.0           # set at dispatch (load_balance)


class Scheduler:
    """Single-actor scheduler over an :class:`AsyncServer`."""

    def __init__(self, server: AsyncServer):
        self.server = server
        self.miners: list[MinerState] = []      # join order, like minersArray
        self.parked: list[Chunk] = []           # chunks of dropped miners
        self.queue: list[Request] = []
        self.current: Optional[Request] = None
        self._next_job_id = 0

    # ------------------------------------------------------------- main loop

    async def run(self) -> None:
        """Serve until the LSP server is closed."""
        while True:
            try:
                conn_id, payload = await self.server.read()
            except LspError:
                return
            if isinstance(payload, Exception):
                self._on_drop(conn_id)
                continue
            try:
                msg = Message.from_json(payload)
            except ValueError:
                continue
            if msg.type == MsgType.JOIN:
                self._on_join(conn_id)
            elif msg.type == MsgType.REQUEST:
                self._on_request(conn_id, msg)
            elif msg.type == MsgType.RESULT:
                self._on_result(conn_id, msg)

    # ---------------------------------------------------------------- events

    def _on_request(self, conn_id: int, msg: Message) -> None:
        request = Request(conn_id=conn_id, data=msg.data,
                          lower=msg.lower, upper=msg.upper,
                          target=msg.target)
        if not self.queue and self.current is None and self.miners:
            self._load_balance(request)
        else:
            self.queue.append(request)

    def _on_join(self, conn_id: int) -> None:
        miner = MinerState(conn_id=conn_id)
        # A joining miner immediately absorbs one parked chunk, if any
        # (ref: server.go:222-244).
        if self.parked:
            self._assign_chunk(miner, self.parked.pop(0))
        self.miners.append(miner)
        if self.current is None and self.queue:
            self._load_balance(self.queue.pop(0))

    def _on_result(self, conn_id: int, msg: Message) -> None:
        miner = self._find_miner(conn_id)
        if miner is None or not miner.pending:
            return
        chunk = miner.pending.pop(0)   # the Result answers the oldest Request
        # A freed miner immediately absorbs one parked chunk
        # (ref: server.go:285-304) — BEFORE the stale-Result return, so a
        # miner freed by a stale answer still rescues parked work.
        if self.parked and miner.available:
            self._assign_chunk(miner, self.parked.pop(0))
        curr = self.current
        if curr is None or chunk.job_id != curr.job_id:
            return  # stale Result for a cancelled/finished request
        if msg.hash < curr.min_hash:
            curr.min_hash = msg.hash
            curr.min_nonce = msg.nonce
        curr.answered[chunk.idx] = True
        if curr.target and msg.target != curr.target and not curr.weak:
            curr.weak = True
            logger.info(
                "difficulty request %d: miner %d answered without the "
                "target extension; the merged result is guaranteed "
                "qualifying, not guaranteed globally first",
                curr.job_id, conn_id)
        if curr.target and msg.hash < curr.target:
            curr.chunk_q[chunk.idx] = (msg.nonce, msg.hash)
        # Prefix release (difficulty only): the lowest-index qualifying
        # chunk is final once every earlier chunk has answered clean —
        # later chunks cover strictly higher nonces and cannot beat it.
        if curr.chunk_q:
            c = min(curr.chunk_q)
            if all(curr.answered[:c]):
                nonce, q_hash = curr.chunk_q[c]
                self._finish(curr, q_hash, nonce, early=True)
                return
        if all(curr.answered):
            # Full barrier: stock request, or target missed everywhere —
            # the exact arg-min. (A difficulty hit always releases above:
            # at the barrier, its qualifying prefix is trivially complete.)
            self._finish(curr, curr.min_hash, curr.min_nonce)

    def _on_drop(self, conn_id: int) -> None:
        miner = self._find_miner(conn_id)
        if miner is not None:
            logger.info("miner %d dropped", conn_id)
            self.miners.remove(miner)
            curr = self.current
            if curr is None:
                return
            # Recover every unanswered chunk of the current request
            # (ref: server.go:326-376, single-chunk version).
            for chunk in miner.pending:
                if chunk.job_id != curr.job_id:
                    continue
                takeover = next((m for m in self.miners if m.available), None)
                if takeover is not None:
                    self._assign_chunk(takeover, chunk)
                else:
                    self.parked.append(chunk)
        else:
            logger.info("client %d dropped", conn_id)
            # Purge the dead client's queued requests FIRST so cancelling its
            # in-flight request can't promote another of its own requests.
            self.queue = [r for r in self.queue if r.conn_id != conn_id]
            curr = self.current
            if curr is not None and curr.conn_id == conn_id:
                # Cancel immediately (divergence, see module docstring).
                self._retire(cancel=True)

    # -------------------------------------------------------------- internal

    def _finish(self, curr: Request, h: int, nonce: int,
                early: bool = False) -> None:
        """Answer the client and retire the request. ``early`` = prefix
        release: the job's other chunks are still in flight."""
        self._write(curr.conn_id, new_result(h, nonce))
        logger.info(
            "request %d served in %.3fs: [%d, %d) over %d chunks%s%s",
            curr.job_id, time.monotonic() - curr.started,
            curr.lower, curr.upper, curr.num_chunks,
            " (prefix release)" if early else "",
            " (weak merge)" if curr.weak else "")
        self._retire(cancel=early)

    def _retire(self, cancel: bool) -> None:
        """Retire the in-flight request and start the next. ``cancel``
        (prefix release and client drop) marks its unanswered chunks
        cancelled: the pool frees immediately (availability is derived),
        the FIFO pop discipline for their late Results is preserved (they
        drop at the job_id check), and parked chunks — which can only
        belong to the job in flight — are discarded."""
        if cancel:
            for m in self.miners:
                for c in m.pending:
                    if c.job_id == self.current.job_id:
                        c.cancelled = True
            self.parked.clear()
        self.current = None
        if self.queue and self.miners:
            self._load_balance(self.queue.pop(0))

    def _find_miner(self, conn_id: int) -> Optional[MinerState]:
        for m in self.miners:
            if m.conn_id == conn_id:
                return m
        return None

    def _load_balance(self, request: Request) -> None:
        """Split the range over ALL miners (they must all be available)."""
        self.current = request
        self._next_job_id += 1
        request.job_id = self._next_job_id
        request.started = time.monotonic()
        num = len(self.miners)
        request.upper += 1  # inclusive -> exclusive
        total = request.upper - request.lower
        if total <= 0:
            # Empty/inverted range: answer like an empty scan (the reference
            # would wrap negative totals through uint64 and wedge the pool).
            self._finish(request, MAX_U64, 0)
            return
        individual = total // num
        leftover = total - individual * num
        if individual == 0:  # more miners than nonces
            individual, leftover, num = 1, 0, total
        request.num_chunks = num
        request.answered = [False] * num
        start = request.lower
        for i in range(num):
            end = start + individual + (leftover if i == 0 else 0)
            self._assign_chunk(
                self.miners[i],
                Chunk(request.job_id, request.data, start, end,
                      target=request.target, idx=i))
            start = end

    def _assign_chunk(self, miner: MinerState, chunk: Chunk) -> None:
        miner.pending.append(chunk)
        self._write(miner.conn_id,
                    new_request(chunk.data, chunk.lower, chunk.upper,
                                chunk.target))

    def _write(self, conn_id: int, msg: Message) -> None:
        try:
            self.server.write(conn_id, msg.to_json())
        except LspError:
            # The drop event for this connection is already in flight; the
            # drop handler will repair the assignment.
            logger.info("write to %d failed; awaiting drop event", conn_id)
